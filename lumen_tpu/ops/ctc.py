"""CTC greedy decoding for the OCR recognizer.

The dense part (argmax over the vocab at every timestep + per-step
confidence) is jit-safe and runs batched on device; the collapse/lookup to
strings is host-side. Semantics match the reference decoder
(``lumen_ocr/backends/onnxrt_backend.py:596-632``): blank index 0, collapse
repeats, mean probability of emitted (non-blank, non-repeat) steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def ctc_greedy_device(logits: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, T, V] logits (or probabilities) -> ([B, T] argmax ids, [B, T] probs)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    ids = jnp.argmax(probs, axis=-1)
    conf = jnp.max(probs, axis=-1)
    return ids, conf


def ctc_collapse(
    ids: np.ndarray,
    confs: np.ndarray,
    vocab: list[str],
    blank: int = 0,
) -> tuple[str, float]:
    """Host collapse of one sequence: drop repeats-then-blanks, join chars,
    mean confidence over emitted steps (1.0 if nothing emitted)."""
    prev = -1
    chars: list[str] = []
    scores: list[float] = []
    for t, idx in enumerate(ids):
        idx = int(idx)
        if idx != blank and idx != prev:
            if idx < len(vocab):
                chars.append(vocab[idx])
                scores.append(float(confs[t]))
        prev = idx
    text = "".join(chars)
    return text, (float(np.mean(scores)) if scores else 1.0)


def load_ctc_vocab(path: str, use_space_char: bool = True) -> list[str]:
    """Character list: blank placeholder at index 0, then dictionary lines,
    then optional trailing space (reference: ``onnxrt_backend.py:104-114``)."""
    with open(path, "r", encoding="utf-8") as f:
        chars = [line.rstrip("\n") for line in f if line.rstrip("\n")]
    vocab = ["<blank>"] + chars
    if use_space_char:
        vocab.append(" ")
    return vocab
