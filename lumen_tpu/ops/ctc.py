"""CTC greedy decoding for the OCR recognizer.

The dense part (argmax over the vocab at every timestep + per-step
confidence) is jit-safe and runs batched on device; the collapse/lookup to
strings is host-side. Semantics match the reference decoder
(``lumen_ocr/backends/onnxrt_backend.py:596-632``): blank index 0, collapse
repeats, mean probability of emitted (non-blank, non-repeat) steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def ctc_greedy_device(logits: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """[B, T, V] logits (or probabilities) -> ([B, T] argmax ids, [B, T] probs)."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    ids = jnp.argmax(probs, axis=-1)
    conf = jnp.max(probs, axis=-1)
    return ids, conf


def _emitted_to_text(
    emitted: list[tuple[int, float]], vocab: list[str]
) -> tuple[str, float]:
    """Shared tail of both collapse paths: out-of-vocab filter, char join,
    mean confidence over emitted steps (1.0 if nothing emitted)."""
    kept = [(vocab[i], c) for i, c in emitted if i < len(vocab)]
    text = "".join(ch for ch, _ in kept)
    return text, (float(np.mean([c for _, c in kept])) if kept else 1.0)


def ctc_collapse(
    ids: np.ndarray,
    confs: np.ndarray,
    vocab: list[str],
    blank: int = 0,
) -> tuple[str, float]:
    """Host collapse of one sequence: drop repeats-then-blanks, join chars,
    mean confidence over emitted steps (1.0 if nothing emitted)."""
    prev = -1
    emitted: list[tuple[int, float]] = []
    for t, idx in enumerate(ids):
        idx = int(idx)
        if idx != blank and idx != prev:
            emitted.append((idx, float(confs[t])))
        prev = idx
    return _emitted_to_text(emitted, vocab)


def ctc_collapse_rows(
    ids: np.ndarray,
    confs: np.ndarray,
    vocab: list[str],
    blank: int = 0,
) -> list[tuple[str, float]]:
    """Collapse a [B, T] batch; native C core when available (one GIL-free
    call for the whole batch), else the per-row python collapse above."""
    from lumen_tpu import native

    ids = np.asarray(ids)
    confs = np.asarray(confs)
    if native.available() and ids.ndim == 2:
        out_ids, out_confs, counts = native.ctc_collapse_batch(ids, confs, blank)
        results = []
        for b in range(ids.shape[0]):
            n = int(counts[b])
            emitted = [(int(i), float(c)) for i, c in zip(out_ids[b, :n], out_confs[b, :n])]
            results.append(_emitted_to_text(emitted, vocab))
        return results
    return [ctc_collapse(ids[b], confs[b], vocab, blank) for b in range(ids.shape[0])]


def load_ctc_vocab(path: str, use_space_char: bool = True) -> list[str]:
    """Character list: blank placeholder at index 0, then dictionary lines,
    then optional trailing space (reference: ``onnxrt_backend.py:104-114``)."""
    with open(path, "r", encoding="utf-8") as f:
        chars = [line.rstrip("\n") for line in f if line.rstrip("\n")]
    vocab = ["<blank>"] + chars
    if use_space_char:
        vocab.append(" ")
    return vocab
