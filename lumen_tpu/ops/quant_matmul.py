"""Pallas w8a16 dequant-matmul for the weight-streaming decode path.

Why this kernel exists (measured on chip, round 5): XLA:TPU lowers the
decode-shape dequant projection ``dot(x[B,1,K], convert(s8 W[K,N]))`` to a
broadcast-multiply-REDUCE on the VPU instead of an MXU matmul — the
optimized while-body HLO for the int8 decoder carries 85 ``reduce`` ops
where the bf16 body has none, and the measured decode is ~34x slower than
bf16 (119 vs 4065 tok/s, HBM util 0.43%: the chip spends the step grinding
29M weights/step through the vector unit). The same program at batch-256
CLIP shapes lowers fine (int8 MXU), so the pathology is specific to tiny
row counts.

This kernel restores the intended cost model — stream one byte per weight
element, convert s8->bf16 in-register, feed the MXU:

    y[B, N] = (x[B, K] @ convert(W[K, N])) * scale[N]

Grid: one step per N block; the weight tile [K, block_n] streams HBM->VMEM
while the MXU consumes the previous block (pallas double-buffers block
inputs automatically). ``x`` is tiny (B<=32 rows) and stays resident.

The reference has no quantized execution at all (its ONNX sessions run
exported precision as-is, ``packages/lumen-vlm/src/lumen_vlm/backends/
onnxrt_backend.py:107-140``); this is TPU-native capability on top.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .pallas_compat import CompilerParams as _compiler_params

#: Max rows routed to this kernel: decode/serving matvec-ish shapes. Larger
#: row counts (batch embedding) already lower to the MXU via XLA.
MAX_PALLAS_ROWS = 64

#: Largest ``model``-axis size any serving mesh in this process has
#: reported (see :func:`note_mesh_model_axis`). ``pl.pallas_call`` inside a
#: GSPMD-jitted program has no sharding rule: under tensor parallelism
#: (INT8_TP_RULES shard ``q`` along ``model``) the kernel would fail to
#: partition or silently all-gather/replicate the weights it exists to
#: stream — so TP disables this route entirely and decode falls back to
#: the XLA dequant dot, which shards fine.
_MESH_MODEL_AXIS = 1


def note_mesh_model_axis(size: int) -> None:
    """Serving managers report their mesh's ``model``-axis size here at
    construction. Sticky maximum: one TP manager anywhere in the process
    disables the Pallas route for everyone — conservative, because a
    replicated sibling sharing the process cannot be told apart at trace
    time, and the fallback is merely slower, not wrong."""
    global _MESH_MODEL_AXIS
    _MESH_MODEL_AXIS = max(_MESH_MODEL_AXIS, int(size))

_SUBLANE_S8 = 32  # s8 VMEM tile is (32, 128): K must divide into sublanes
_LANES = 128


def _kernel(x_ref, q_ref, s_ref, o_ref):
    # q tile [K, block_n] s8 -> bf16 in-register; integers |w|<=127 are
    # exact in bf16 (8 mantissa bits cover 0..256).
    w = q_ref[...].astype(jnp.bfloat16)
    acc = jnp.dot(
        x_ref[...].astype(jnp.bfloat16), w, preferred_element_type=jnp.float32
    )
    o_ref[...] = (acc * s_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def _w8a16_2d(x, q, scale, *, block_n: int, interpret: bool):
    b, k = x.shape
    _, n = q.shape
    # scale rides as [1, N]: Mosaic rejects 1D operand blocks whose lane
    # tile disagrees with XLA's padded 1D layout (T(1024) vs T(128)).
    return pl.pallas_call(
        _kernel,
        grid=(n // block_n,),
        in_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((k, block_n), lambda j: (0, j)),
            pl.BlockSpec((1, block_n), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((b, block_n), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b, n), x.dtype),
        compiler_params=_compiler_params(dimension_semantics=("parallel",)),
        interpret=interpret,
    )(x, q, scale.reshape(1, n))


def pallas_usable(rows: int, k: int, n: int, dtype=None) -> bool:
    """Route through the Pallas kernel? TPU backend (or forced interpret),
    decode-sized row count, tile-aligned dims, bf16 activations, and no
    tensor-parallel serving mesh in the process.

    The dtype gate is a precision contract: the kernel computes the dot in
    bf16 (weights convert s8->bf16 in-register) and applies scale in f32 —
    correct for the bf16 serving policy, but an f32 caller routed here
    would silently lose activation mantissa vs. the XLA dequant fallback,
    which computes in the caller's dtype. Both correctness gates sit BEFORE
    the ``LUMEN_Q8_PALLAS=1`` force knob: the knob forces interpret-mode
    execution off-TPU, never an unsound routing."""
    if os.environ.get("LUMEN_Q8_PALLAS") == "0":
        return False
    if _MESH_MODEL_AXIS > 1:
        return False
    if rows > MAX_PALLAS_ROWS or k % _SUBLANE_S8 or n % _LANES:
        return False
    if dtype is not None and jnp.dtype(dtype) != jnp.bfloat16:
        return False
    if os.environ.get("LUMEN_Q8_PALLAS") == "1":
        return True
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001 - backend probe must never break callers
        return False


def _interpret() -> bool:
    try:
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return True


def w8a16_matmul(x: jax.Array, q: jax.Array, scale: jax.Array) -> jax.Array:
    """``(x @ convert(q)) * scale`` via the Pallas MXU kernel.

    ``x``: [..., K] activations (leading dims flattened to rows),
    ``q``: [K, N] int8 weights, ``scale``: [N] f32 per-output-channel.
    Caller gates on :func:`pallas_usable`.
    """
    k, n = q.shape
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= d
    x2 = x.reshape(rows, k)
    # Pad rows to the f32/bf16 sublane (8): pallas wants aligned blocks and
    # decode rows are small, so the pad cost is noise.
    pad = (-rows) % 8
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    block_n = 256 if n % 256 == 0 else _LANES
    y = _w8a16_2d(x2, q, scale, block_n=block_n, interpret=_interpret())
    if pad:
        y = y[:rows]
    return y.reshape(*lead, n)
