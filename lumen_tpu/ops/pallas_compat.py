"""jax version-skew shim for the Pallas TPU kernels.

``TPUCompilerParams`` was renamed ``CompilerParams`` across jax 0.4 -> 0.5;
every pallas kernel module imports the resolved name from here so the ops
package imports — and its CPU interpret-mode tests run — on both sides of
the skew (the pinned CI image and the TPU runtime image are rarely the
same jax). Counterpart of ``lumen_tpu/parallel/compat.py`` (shard_map).
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

__all__ = ["CompilerParams"]
