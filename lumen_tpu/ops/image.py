"""Device-side image preprocessing.

The reference does all preprocessing on host with PIL/cv2 per image
(``onnxrt_backend.py:378-433``); here the dense parts (resize, normalize,
layout) run batched on TPU so the host only decodes bytes. Host decode
lives with the model managers (cv2/PIL are control-flow heavy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Normalization statistics (reference: clip loader defaults,
# packages/lumen-clip/src/lumen_clip/resources/loader.py:101-139).
OPENAI_CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
OPENAI_CLIP_STD = (0.26862954, 0.26130258, 0.27577711)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


@functools.partial(jax.jit, static_argnames=("size", "method"))
def resize_bilinear(images: jax.Array, size: tuple[int, int], method: str = "bilinear") -> jax.Array:
    """[B, H, W, C] uint8/float -> [B, size_h, size_w, C] float32."""
    b, _, _, c = images.shape
    return jax.image.resize(
        images.astype(jnp.float32), (b, size[0], size[1], c), method=method
    )


@functools.partial(jax.jit, static_argnames=())
def normalize(images: jax.Array, mean: jax.Array, std: jax.Array) -> jax.Array:
    """[B, H, W, C] in [0, 255] -> normalized float32."""
    x = images.astype(jnp.float32) / 255.0
    return (x - mean) / std


@functools.partial(jax.jit, static_argnames=("size", "mean", "std"))
def clip_preprocess(
    images: jax.Array,
    size: int = 224,
    mean: tuple[float, ...] = OPENAI_CLIP_MEAN,
    std: tuple[float, ...] = OPENAI_CLIP_STD,
) -> jax.Array:
    """Batched CLIP preprocessing: resize + normalize, NHWC output.

    Mirrors the reference preprocessor's semantics (direct resize to target,
    ``onnxrt_backend.py:410-431``) so embeddings stay comparable.
    """
    x = resize_bilinear(images, (size, size))
    return normalize(x, jnp.asarray(mean), jnp.asarray(std))


# Host-side decode primitives now live in the jax-free
# lumen_tpu.utils.host_decode (the process decode-pool workers import
# THAT module — importing this one would drag jax into every worker).
# Re-exported here so existing import sites keep working unchanged.
from lumen_tpu.utils.host_decode import (  # noqa: E402,F401
    DECODE_POLICY,
    _factor_from_hw,
    _reduced_decode_factor,
    decode_image_bytes,
    decode_image_bytes_scaled,
    letterbox_numpy,
    letterbox_params,
    probe_image_size,
)
