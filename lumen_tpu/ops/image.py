"""Device-side image preprocessing.

The reference does all preprocessing on host with PIL/cv2 per image
(``onnxrt_backend.py:378-433``); here the dense parts (resize, normalize,
layout) run batched on TPU so the host only decodes bytes. Host decode
lives with the model managers (cv2/PIL are control-flow heavy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Normalization statistics (reference: clip loader defaults,
# packages/lumen-clip/src/lumen_clip/resources/loader.py:101-139).
OPENAI_CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
OPENAI_CLIP_STD = (0.26862954, 0.26130258, 0.27577711)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


@functools.partial(jax.jit, static_argnames=("size", "method"))
def resize_bilinear(images: jax.Array, size: tuple[int, int], method: str = "bilinear") -> jax.Array:
    """[B, H, W, C] uint8/float -> [B, size_h, size_w, C] float32."""
    b, _, _, c = images.shape
    return jax.image.resize(
        images.astype(jnp.float32), (b, size[0], size[1], c), method=method
    )


@functools.partial(jax.jit, static_argnames=())
def normalize(images: jax.Array, mean: jax.Array, std: jax.Array) -> jax.Array:
    """[B, H, W, C] in [0, 255] -> normalized float32."""
    x = images.astype(jnp.float32) / 255.0
    return (x - mean) / std


@functools.partial(jax.jit, static_argnames=("size", "mean", "std"))
def clip_preprocess(
    images: jax.Array,
    size: int = 224,
    mean: tuple[float, ...] = OPENAI_CLIP_MEAN,
    std: tuple[float, ...] = OPENAI_CLIP_STD,
) -> jax.Array:
    """Batched CLIP preprocessing: resize + normalize, NHWC output.

    Mirrors the reference preprocessor's semantics (direct resize to target,
    ``onnxrt_backend.py:410-431``) so embeddings stay comparable.
    """
    x = resize_bilinear(images, (size, size))
    return normalize(x, jnp.asarray(mean), jnp.asarray(std))


def letterbox_params(h: int, w: int, target: int) -> tuple[float, int, int, int, int]:
    """Aspect-preserving resize-with-padding geometry (host-side helper).

    Returns ``(scale, new_h, new_w, pad_top, pad_left)``; the inverse maps
    detector boxes back to original coordinates (reference face pipeline,
    ``lumen_face/backends/onnxrt_backend.py:749-808``).
    """
    scale = min(target / h, target / w)
    new_h, new_w = int(round(h * scale)), int(round(w * scale))
    pad_top = (target - new_h) // 2
    pad_left = (target - new_w) // 2
    return scale, new_h, new_w, pad_top, pad_left


def letterbox_numpy(img: np.ndarray, target: int, fill: int = 0) -> tuple[np.ndarray, float, int, int]:
    """Host letterbox for a single decoded image [H, W, C] -> [target, target, C].

    cv2 (SIMD resize) when present; otherwise the fused native C letterbox,
    so the serving path also works in a no-OpenCV environment.
    """
    try:
        import cv2
    except ImportError:
        cv2 = None
    if cv2 is None and img.dtype == np.uint8:
        from lumen_tpu import native

        if native.available():
            return native.letterbox_u8(img, target, fill)
    if cv2 is None:
        raise RuntimeError("letterbox requires cv2 or the native host-ops library")

    h, w = img.shape[:2]
    scale, new_h, new_w, pad_top, pad_left = letterbox_params(h, w, target)
    resized = cv2.resize(img, (new_w, new_h), interpolation=cv2.INTER_LINEAR)
    out = np.full((target, target, img.shape[2]), fill, dtype=img.dtype)
    out[pad_top : pad_top + new_h, pad_left : pad_left + new_w] = resized
    return out, scale, pad_top, pad_left


#: result-cache namespace qualifier for the scaled-decode generation.
#: Decode resolution changes result numerics (resampling, thresholded
#: detections): disk-tier entries computed under one decode policy must
#: not answer for another across deploys. Bump when the policy changes.
DECODE_POLICY = "sd1"


def probe_image_size(payload: bytes) -> tuple[int, int] | None:
    """Header-only (h, w) probe — no pixel decode. PIL reads just the
    container header lazily; anything unprobeable returns None (the caller
    falls back to a full decode)."""
    try:
        from io import BytesIO

        from PIL import Image

        with Image.open(BytesIO(payload)) as im:
            w, h = im.size
        return (int(h), int(w))
    except Exception:  # noqa: BLE001 - probe is best-effort by contract
        return None


def _factor_from_hw(hw: tuple[int, int] | None, max_edge: int) -> int:
    """Largest scaled-decode factor in {2, 4, 8} that keeps BOTH decoded
    dims >= ``max_edge`` (downstream resizes — square squash or letterbox
    — must only ever downscale). 1 = decode full; engages only when the
    target edge is <= half the source edge."""
    if hw is None or max_edge <= 0:
        return 1
    short = min(hw)
    factor = 1
    while factor < 8 and short // (factor * 2) >= max_edge:
        factor *= 2
    return factor


def _reduced_decode_factor(payload: bytes, max_edge: int) -> int:
    """Header probe + :func:`_factor_from_hw`; an unprobeable payload
    decodes full."""
    if max_edge <= 0:
        return 1
    return _factor_from_hw(probe_image_size(payload), max_edge)


def decode_image_bytes(
    payload: bytes, color: str = "rgb", max_edge: int | None = None, _factor: int | None = None
) -> np.ndarray:
    """Host-side decode to [H, W, 3] uint8 (cv2; PIL fallback for exotic
    formats).

    ``max_edge`` opts into SCALED decode: when the image is at least 2x
    oversized for the target edge, the JPEG is decoded directly at 1/2,
    1/4 or 1/8 scale (cv2 ``IMREAD_REDUCED_COLOR_*`` / PIL ``draft``) —
    the IDCT runs on a fraction of the blocks, cutting decode cost ~4x on
    typical photos. Both decoded dims stay >= ``max_edge``, so downstream
    resize/letterbox to the target only ever downscales. Callers that
    must map coordinates back to the original frame use
    :func:`decode_image_bytes_scaled` instead (``_factor`` lets it reuse
    its one header probe instead of probing twice)."""
    import cv2

    if _factor is not None:
        factor = _factor
    else:
        factor = _reduced_decode_factor(payload, max_edge) if max_edge else 1
    flag = {1: cv2.IMREAD_COLOR, 2: cv2.IMREAD_REDUCED_COLOR_2,
            4: cv2.IMREAD_REDUCED_COLOR_4, 8: cv2.IMREAD_REDUCED_COLOR_8}[factor]
    buf = np.frombuffer(payload, dtype=np.uint8)
    try:
        img = cv2.imdecode(buf, flag)
        if img is None:
            from io import BytesIO

            from PIL import Image

            pil = Image.open(BytesIO(payload))
            if factor > 1:
                # draft() is JPEG-only and advisory; for other formats it
                # is a no-op and the full-size image decodes (correct,
                # just not reduced).
                pil.draft("RGB", (pil.size[0] // factor, pil.size[1] // factor))
            pil = pil.convert("RGB")
            img = np.asarray(pil)
            if color == "bgr":
                img = img[:, :, ::-1]
            return np.ascontiguousarray(img)
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 - normalize any decode failure
        raise ValueError(f"cannot decode image payload: {e}") from e
    if color == "rgb":
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img


def decode_image_bytes_scaled(
    payload: bytes, color: str = "rgb", max_edge: int | None = None
) -> tuple[np.ndarray, float, tuple[int, int]]:
    """Scaled decode WITH provenance: returns ``(img, decode_scale,
    orig_hw)`` where ``decode_scale = decoded_edge / original_edge``
    (1.0 = full decode). Callers that report coordinates (face boxes,
    OCR quads) fold ``decode_scale`` into their letterbox unmap so
    results stay in ORIGINAL image coordinates."""
    hw = probe_image_size(payload) if max_edge else None
    factor = _factor_from_hw(hw, max_edge) if max_edge else 1
    img = decode_image_bytes(payload, color=color, max_edge=max_edge, _factor=factor)
    if hw is None or min(hw) <= 0:
        return img, 1.0, img.shape[:2]
    # Long-edge ratio: robust to decoders that apply a 90-degree EXIF
    # rotation the header probe doesn't see; orig_hw is then derived from
    # the DECODED orientation so callers unclip against consistent axes.
    scale = max(img.shape[:2]) / max(hw)
    if scale >= 0.999:  # full decode (or non-reducible format)
        return img, 1.0, img.shape[:2]
    h, w = img.shape[:2]
    return img, scale, (round(h / scale), round(w / scale))
