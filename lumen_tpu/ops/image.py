"""Device-side image preprocessing.

The reference does all preprocessing on host with PIL/cv2 per image
(``onnxrt_backend.py:378-433``); here the dense parts (resize, normalize,
layout) run batched on TPU so the host only decodes bytes. Host decode
lives with the model managers (cv2/PIL are control-flow heavy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# Normalization statistics (reference: clip loader defaults,
# packages/lumen-clip/src/lumen_clip/resources/loader.py:101-139).
OPENAI_CLIP_MEAN = (0.48145466, 0.4578275, 0.40821073)
OPENAI_CLIP_STD = (0.26862954, 0.26130258, 0.27577711)
IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


@functools.partial(jax.jit, static_argnames=("size", "method"))
def resize_bilinear(images: jax.Array, size: tuple[int, int], method: str = "bilinear") -> jax.Array:
    """[B, H, W, C] uint8/float -> [B, size_h, size_w, C] float32."""
    b, _, _, c = images.shape
    return jax.image.resize(
        images.astype(jnp.float32), (b, size[0], size[1], c), method=method
    )


@functools.partial(jax.jit, static_argnames=())
def normalize(images: jax.Array, mean: jax.Array, std: jax.Array) -> jax.Array:
    """[B, H, W, C] in [0, 255] -> normalized float32."""
    x = images.astype(jnp.float32) / 255.0
    return (x - mean) / std


@functools.partial(jax.jit, static_argnames=("size", "mean", "std"))
def clip_preprocess(
    images: jax.Array,
    size: int = 224,
    mean: tuple[float, ...] = OPENAI_CLIP_MEAN,
    std: tuple[float, ...] = OPENAI_CLIP_STD,
) -> jax.Array:
    """Batched CLIP preprocessing: resize + normalize, NHWC output.

    Mirrors the reference preprocessor's semantics (direct resize to target,
    ``onnxrt_backend.py:410-431``) so embeddings stay comparable.
    """
    x = resize_bilinear(images, (size, size))
    return normalize(x, jnp.asarray(mean), jnp.asarray(std))


def letterbox_params(h: int, w: int, target: int) -> tuple[float, int, int, int, int]:
    """Aspect-preserving resize-with-padding geometry (host-side helper).

    Returns ``(scale, new_h, new_w, pad_top, pad_left)``; the inverse maps
    detector boxes back to original coordinates (reference face pipeline,
    ``lumen_face/backends/onnxrt_backend.py:749-808``).
    """
    scale = min(target / h, target / w)
    new_h, new_w = int(round(h * scale)), int(round(w * scale))
    pad_top = (target - new_h) // 2
    pad_left = (target - new_w) // 2
    return scale, new_h, new_w, pad_top, pad_left


def letterbox_numpy(img: np.ndarray, target: int, fill: int = 0) -> tuple[np.ndarray, float, int, int]:
    """Host letterbox for a single decoded image [H, W, C] -> [target, target, C].

    cv2 (SIMD resize) when present; otherwise the fused native C letterbox,
    so the serving path also works in a no-OpenCV environment.
    """
    try:
        import cv2
    except ImportError:
        cv2 = None
    if cv2 is None and img.dtype == np.uint8:
        from lumen_tpu import native

        if native.available():
            return native.letterbox_u8(img, target, fill)
    if cv2 is None:
        raise RuntimeError("letterbox requires cv2 or the native host-ops library")

    h, w = img.shape[:2]
    scale, new_h, new_w, pad_top, pad_left = letterbox_params(h, w, target)
    resized = cv2.resize(img, (new_w, new_h), interpolation=cv2.INTER_LINEAR)
    out = np.full((target, target, img.shape[2]), fill, dtype=img.dtype)
    out[pad_top : pad_top + new_h, pad_left : pad_left + new_w] = resized
    return out, scale, pad_top, pad_left


def decode_image_bytes(payload: bytes, color: str = "rgb") -> np.ndarray:
    """Host-side decode to [H, W, 3] uint8 (cv2; PIL fallback for exotic
    formats)."""
    import cv2

    buf = np.frombuffer(payload, dtype=np.uint8)
    try:
        img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
        if img is None:
            from io import BytesIO

            from PIL import Image

            pil = Image.open(BytesIO(payload)).convert("RGB")
            img = np.asarray(pil)
            if color == "bgr":
                img = img[:, :, ::-1]
            return np.ascontiguousarray(img)
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 - normalize any decode failure
        raise ValueError(f"cannot decode image payload: {e}") from e
    if color == "rgb":
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img
