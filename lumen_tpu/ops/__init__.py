"""Compute kernels: attention (XLA + Pallas), image ops, NMS, CTC, sampling."""

from .attention import (
    attention,
    attention_cached,
    attention_reference,
    flash_attention,
    flash_attention_cache,
    record_flash_ab,
    flash_enabled,
    flash_for_seq,
    repeat_kv,
)
from .ctc import ctc_collapse, ctc_greedy_device, load_ctc_vocab
from .image import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    OPENAI_CLIP_MEAN,
    OPENAI_CLIP_STD,
    clip_preprocess,
    decode_image_bytes,
    letterbox_numpy,
    letterbox_params,
    normalize,
    resize_bilinear,
)
from .nms import nms_jax, nms_numpy
from .sampling import apply_repetition_penalty, greedy, sample, top_p_filter

__all__ = [
    "attention",
    "attention_cached",
    "attention_reference",
    "flash_attention",
    "flash_attention_cache",
    "record_flash_ab",
    "flash_enabled",
    "flash_for_seq",
    "repeat_kv",
    "ctc_greedy_device",
    "ctc_collapse",
    "load_ctc_vocab",
    "clip_preprocess",
    "decode_image_bytes",
    "letterbox_numpy",
    "letterbox_params",
    "normalize",
    "resize_bilinear",
    "OPENAI_CLIP_MEAN",
    "OPENAI_CLIP_STD",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "nms_jax",
    "nms_numpy",
    "greedy",
    "sample",
    "top_p_filter",
    "apply_repetition_penalty",
]
