"""Non-maximum suppression.

Two implementations with one semantics:

- :func:`nms_jax` — static-shape, jit-safe (fixed box count, returns a keep
  mask) so detection post-processing can stay on device inside a batched
  program;
- :func:`nms_numpy` — host variant for the CV-heavy paths, same greedy
  IoU-suppression semantics as the reference's pure-numpy NMS
  (``lumen_face/backends/onnxrt_backend.py:391-423``).

Boxes are ``[N, 4]`` as ``(x1, y1, x2, y2)``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _iou_matrix(boxes: jnp.ndarray) -> jnp.ndarray:
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(x2 - x1, 0) * jnp.maximum(y2 - y1, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(ix2 - ix1, 0) * jnp.maximum(iy2 - iy1, 0)
    union = area[:, None] + area[None, :] - inter
    return inter / jnp.maximum(union, 1e-9)


@functools.partial(jax.jit, static_argnames=("iou_threshold",))
def nms_jax(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    iou_threshold: float = 0.4,
) -> jnp.ndarray:
    """Greedy NMS as a keep-mask over N static boxes.

    Scan over boxes in score order: a box is kept iff no higher-scoring kept
    box overlaps it above the threshold. Invalid boxes should carry score
    -inf (they are never kept).
    """
    order = jnp.argsort(-scores)
    boxes_sorted = boxes[order]
    iou = _iou_matrix(boxes_sorted)
    n = boxes.shape[0]

    def body(i, keep):
        # Suppressed if any earlier kept box overlaps too much.
        overlap = (iou[i] > iou_threshold) & keep & (jnp.arange(n) < i)
        return keep.at[i].set(~overlap.any() & keep[i])

    keep_sorted = jax.lax.fori_loop(0, n, body, jnp.isfinite(scores[order]))
    # Map keep decisions back to original box order.
    keep = jnp.zeros((n,), bool).at[order].set(keep_sorted)
    return keep


def nms_numpy(boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.4) -> np.ndarray:
    """Host greedy NMS; returns kept indices sorted by descending score.

    Delegates to the native C core when available (GIL-free, no O(N) python
    loop); the numpy path below is the reference implementation and fallback.
    """
    if len(boxes) == 0:
        return np.empty((0,), np.int64)
    from lumen_tpu import native

    if native.available():
        return native.nms_f32(boxes, scores, iou_threshold)
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
    # Stable sort so score ties break deterministically (higher index first
    # after the reverse) and agree with the native C path's tie-break.
    order = scores.argsort(kind="stable")[::-1]
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / np.maximum(areas[i] + areas[order[1:]] - inter, 1e-9)
        order = order[1:][iou <= iou_threshold]
    return np.asarray(keep, np.int64)
