"""The ``tensor/raw`` wire format: pre-decoded tensors on the Infer payload.

The device does ~9k img/s/chip while gRPC c10 delivers tens of rps — and
the duty meters say the gap is host JPEG decode plus per-item Python
serialization (ROADMAP item 2). For fleet-internal callers and ingest
pipelines that ALREADY hold decoded pixels, re-encoding to JPEG so the
server can decode it again is pure waste. This module defines the
protocol that skips it, **with no proto change**:

- ``payload`` carries the tensor's raw C-contiguous bytes;
- ``payload_mime`` is ``tensor/raw``;
- two request-meta keys describe the buffer: ``dtype`` (numpy name,
  e.g. ``uint8``) and ``shape`` (``224x224x3``);
- each task that accepts tensors advertises its input spec in the
  capability ``extra`` map under ``tensor_input:<task>`` (e.g.
  ``uint8:224x224x3``, ``*`` = any extent), so a caller can validate
  before sending a byte.

Server-side the payload is materialized with one ``np.frombuffer`` —
no decode pool, no pickle, no copy. Client-side the tensor is
serialized through one ``memoryview`` pass (protobuf insists on
``bytes``, so exactly ONE copy happens, at proto construction — the
chunked path slices the memoryview so large tensors still copy once
total, not once per chunk).

Validation (:func:`validate_tensor_meta`) happens in the serving base
class BEFORE the handler: a mismatched dtype/shape/byte-length answers
INVALID_ARGUMENT with a message naming the advertised spec, and never
reaches the batcher, the cache, or the quarantine.

jax-free on purpose: imported by the serving base class and the client.
"""

from __future__ import annotations

import math
import struct
from dataclasses import dataclass

import numpy as np

#: ``payload_mime`` value that switches a request onto the tensor path.
TENSOR_MIME = "tensor/raw"
#: ``payload_mime`` value for a length-prefixed MULTI-tensor payload
#: (:func:`pack_bundle` / :func:`unpack_bundle`) — the KV-migration wire
#: format, one self-describing frame per tensor inside one payload.
BUNDLE_MIME = "tensor/bundle"
#: request-meta key: numpy dtype name of the payload buffer.
DTYPE_META = "dtype"
#: request-meta key: ``x``-separated tensor shape (commas also accepted).
SHAPE_META = "shape"
#: capability-extra key prefix advertising a task's tensor input spec.
TENSOR_INPUT_EXTRA = "tensor_input:"


@dataclass(frozen=True)
class TensorSpec:
    """What a task accepts on the tensor path: a dtype and a shape
    template where ``None`` means any extent (wire spelling ``*``)."""

    dtype: str
    shape: tuple[int | None, ...]

    def wire(self) -> str:
        dims = "x".join("*" if d is None else str(d) for d in self.shape)
        return f"{self.dtype}:{dims}"

    @classmethod
    def from_wire(cls, text: str) -> "TensorSpec":
        dtype, _, dims = text.partition(":")
        shape = tuple(
            None if d == "*" else int(d) for d in dims.split("x") if d
        )
        return cls(dtype, shape)


def _parse_shape(text: str) -> tuple[int, ...]:
    parts = [p for p in text.replace(",", "x").split("x") if p.strip()]
    try:
        shape = tuple(int(p) for p in parts)
    except ValueError:
        raise ValueError(
            f"meta {SHAPE_META!r} must be integers like '224x224x3'; got {text!r}"
        ) from None
    if not shape or any(d <= 0 for d in shape):
        raise ValueError(
            f"meta {SHAPE_META!r} must be positive dims; got {text!r}"
        )
    return shape


def validate_tensor_meta(
    meta: dict[str, str], payload_len: int, spec: TensorSpec
) -> tuple[np.dtype, tuple[int, ...]]:
    """Validate a ``tensor/raw`` request against the task's advertised
    spec. Returns ``(dtype, shape)`` on success; raises :class:`ValueError`
    with a precise, client-actionable message on any mismatch. Runs
    BEFORE the handler — an invalid tensor never touches the batcher."""
    dtype_name = meta.get(DTYPE_META)
    if not dtype_name:
        raise ValueError(
            f"tensor/raw payload requires the {DTYPE_META!r} meta key "
            f"(expected {spec.wire()!r})"
        )
    shape_text = meta.get(SHAPE_META)
    if not shape_text:
        raise ValueError(
            f"tensor/raw payload requires the {SHAPE_META!r} meta key "
            f"(expected {spec.wire()!r})"
        )
    try:
        dtype = np.dtype(dtype_name)
    except TypeError:
        raise ValueError(f"unknown tensor dtype {dtype_name!r}") from None
    if dtype != np.dtype(spec.dtype):
        raise ValueError(
            f"tensor dtype {dtype.name!r} does not match the advertised "
            f"input spec {spec.wire()!r}"
        )
    shape = _parse_shape(shape_text)
    if len(shape) != len(spec.shape) or any(
        want is not None and got != want for got, want in zip(shape, spec.shape)
    ):
        raise ValueError(
            f"tensor shape {'x'.join(map(str, shape))} does not match the "
            f"advertised input spec {spec.wire()!r}"
        )
    # math.prod: arbitrary precision — np.prod would wrap at int64 on
    # attacker-chosen huge dims and could equal a small payload length.
    expect = math.prod(shape) * dtype.itemsize
    if payload_len != expect:
        raise ValueError(
            f"tensor payload is {payload_len} bytes but dtype "
            f"{dtype.name} shape {'x'.join(map(str, shape))} needs {expect}"
        )
    return dtype, shape


def tensor_from_payload(payload: bytes, meta: dict[str, str]) -> np.ndarray:
    """Materialize the validated wire payload: one ``np.frombuffer``, no
    copy (the array is read-only, which every consumer tolerates)."""
    dtype = np.dtype(meta[DTYPE_META])
    shape = _parse_shape(meta[SHAPE_META])
    return np.frombuffer(payload, dtype=dtype).reshape(shape)


def tensor_payload(arr: "np.ndarray") -> tuple[memoryview, dict[str, str]]:
    """Client half: serialize an ndarray into ``(payload, meta)``. The
    payload is a flat byte memoryview over the array's own buffer — the
    single copy happens when protobuf materializes it into the request
    message, not here."""
    arr = np.ascontiguousarray(arr)
    meta = {
        DTYPE_META: arr.dtype.name,
        SHAPE_META: "x".join(str(d) for d in arr.shape),
    }
    return memoryview(arr).cast("B"), meta


# ---------------------------------------------------------------------------
# Multi-tensor bundles (``tensor/bundle``)
# ---------------------------------------------------------------------------
#
# One payload carrying N self-describing tensors, for protocols that move
# a STRUCTURE of arrays in one hop (KV page migration ships per-layer page
# stacks + the seen mask + the RNG key + prompt ids as one frame train).
# Layout, all little-endian:
#
#   magic  b"LTB1"
#   count  uint32
#   then per tensor, a length-prefixed frame:
#     name_len uint8 | dtype name utf-8 | ndim uint8 | dims int64[ndim]
#     | nbytes uint64 | raw C-contiguous bytes
#
# Validation mirrors :func:`validate_tensor_meta`: every reject names the
# tensor index and the exact mismatch, and byte lengths are checked with
# arbitrary-precision ``math.prod`` so attacker-chosen dims cannot wrap.

_BUNDLE_MAGIC = b"LTB1"
#: sanity bounds — a malformed count must fail fast, not allocate.
_BUNDLE_MAX_TENSORS = 4096
_BUNDLE_MAX_NDIM = 16


def _bundle_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype name, reaching for ``ml_dtypes`` lazily so
    bf16 KV pages round-trip on hosts where plain numpy cannot spell
    ``bfloat16`` (``jax.device_get`` of a bf16 pool yields exactly such
    arrays)."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    try:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    except (ImportError, AttributeError, TypeError):
        raise ValueError(f"tensor bundle: unknown dtype {name!r}") from None


def pack_bundle(arrays: "list[np.ndarray]") -> bytes:
    """Serialize ``arrays`` into one self-describing payload. Arrays are
    made C-contiguous (the one copy non-contiguous inputs pay); dtype
    names must round-trip through :func:`_bundle_dtype`."""
    if len(arrays) > _BUNDLE_MAX_TENSORS:
        raise ValueError(
            f"tensor bundle: {len(arrays)} tensors exceeds the "
            f"{_BUNDLE_MAX_TENSORS} cap"
        )
    parts = [_BUNDLE_MAGIC, struct.pack("<I", len(arrays))]
    for i, arr in enumerate(arrays):
        shape = np.shape(arr)
        # ascontiguousarray promotes 0-d to 1-d; reshape restores the
        # declared rank so scalars round-trip shape-exactly.
        arr = np.ascontiguousarray(arr).reshape(shape)
        name = arr.dtype.name.encode("utf-8")
        if len(name) > 255:
            raise ValueError(f"tensor bundle: tensor #{i} dtype name too long")
        if arr.ndim > _BUNDLE_MAX_NDIM:
            raise ValueError(
                f"tensor bundle: tensor #{i} has {arr.ndim} dims "
                f"(cap {_BUNDLE_MAX_NDIM})"
            )
        parts.append(struct.pack("<B", len(name)))
        parts.append(name)
        parts.append(struct.pack("<B", arr.ndim))
        parts.append(struct.pack(f"<{arr.ndim}q", *arr.shape))
        parts.append(struct.pack("<Q", arr.nbytes))
        parts.append(arr.tobytes())
    return b"".join(parts)


def unpack_bundle(buf: "bytes | memoryview") -> "list[np.ndarray]":
    """Parse a :func:`pack_bundle` payload back into arrays (zero-copy
    views over ``buf`` — read-only, like :func:`tensor_from_payload`).
    Raises :class:`ValueError` with a precise, frame-indexed message on
    any malformation; a valid prefix never masks trailing garbage."""
    view = memoryview(buf)
    if len(view) < 8:
        raise ValueError(
            f"tensor bundle: payload is {len(view)} bytes, shorter than "
            "the 8-byte header"
        )
    if bytes(view[:4]) != _BUNDLE_MAGIC:
        raise ValueError(
            f"tensor bundle: bad magic {bytes(view[:4])!r} "
            f"(expected {_BUNDLE_MAGIC!r})"
        )
    (count,) = struct.unpack("<I", view[4:8])
    if count > _BUNDLE_MAX_TENSORS:
        raise ValueError(
            f"tensor bundle: declares {count} tensors, cap is "
            f"{_BUNDLE_MAX_TENSORS}"
        )
    off = 8
    out: list[np.ndarray] = []
    for i in range(count):
        def need(n: int, what: str, _i=i) -> None:
            if off + n > len(view):
                raise ValueError(
                    f"tensor bundle: tensor #{_i} truncated in {what} "
                    f"(need {n} bytes at offset {off}, have {len(view) - off})"
                )

        need(1, "dtype length")
        name_len = view[off]
        off += 1
        need(name_len, "dtype name")
        name = bytes(view[off : off + name_len]).decode("utf-8", "replace")
        off += name_len
        dtype = _bundle_dtype(name)
        need(1, "ndim")
        ndim = view[off]
        off += 1
        if ndim > _BUNDLE_MAX_NDIM:
            raise ValueError(
                f"tensor bundle: tensor #{i} has {ndim} dims "
                f"(cap {_BUNDLE_MAX_NDIM})"
            )
        need(8 * ndim, "dims")
        shape = struct.unpack(f"<{ndim}q", view[off : off + 8 * ndim])
        off += 8 * ndim
        if any(d < 0 for d in shape):
            raise ValueError(
                f"tensor bundle: tensor #{i} has negative dim in "
                f"{'x'.join(map(str, shape))}"
            )
        need(8, "byte length")
        (nbytes,) = struct.unpack("<Q", view[off : off + 8])
        off += 8
        # math.prod: arbitrary precision, same wrap-proofing rationale as
        # validate_tensor_meta.
        expect = math.prod(shape) * dtype.itemsize
        if nbytes != expect:
            raise ValueError(
                f"tensor bundle: tensor #{i} declares {nbytes} bytes but "
                f"dtype {name} shape {'x'.join(map(str, shape))} needs {expect}"
            )
        need(nbytes, "tensor bytes")
        out.append(
            np.frombuffer(view[off : off + nbytes], dtype=dtype).reshape(shape)
        )
        off += nbytes
    if off != len(view):
        raise ValueError(
            f"tensor bundle: {len(view) - off} trailing byte(s) after the "
            f"last declared tensor"
        )
    return out
