"""Multi-tenant QoS: weighted-fair admission, priority lanes, per-tenant quotas.

At millions-of-users scale the single global admission queue was the last
unguarded failure mode in the serving stack: one tenant's bulk re-index
convoy — made *cheaper* to emit by the bulk streaming lane — fills the
FIFO ahead of every interactive user, and ``QueueFull`` sheds
indiscriminately. The device never overloads first; the *queue policy*
does. This module is the fix, in three mechanisms (all host-side, all
O(1) per request, deliberately jax-free so the serving base class and the
client can import it):

- **weighted-fair queuing** — :class:`WFQAdmissionQueue` is a drop-in for
  the :class:`queue.Queue` the micro-batcher admits through, but pops by
  *virtual-time WFQ* over per-``(tenant, lane)`` sub-queues instead of
  arrival order. Each flow's entries carry virtual finish tags
  (``max(V, last_tag) + 1/weight``); the pop takes the smallest head tag
  and advances ``V``. Tenants share service in proportion to their
  weights regardless of how fast they submit: a flooding tenant only ever
  stretches its OWN backlog. FIFO order is preserved within a flow, and
  with a single (default) tenant the schedule degenerates to exactly the
  old FIFO — which is why the WFQ queue can be the default
  (``LUMEN_QOS=0`` restores the plain queue).
- **priority lanes** — ``interactive`` > ``bulk``. A lane is part of the
  flow key; the bulk lane's weight is scaled down by
  ``LUMEN_QOS_BULK_SHARE`` (default 0.25), so bulk traffic — the bulk
  streaming lane and the ingest pipeline auto-tag it — fills idle
  capacity without displacing interactive requests. Under sustained
  pressure the **brownout ladder** degrades bulk first: at
  ``LUMEN_QOS_BROWNOUT_PCT`` queue occupancy the bulk share shrinks by
  ``LUMEN_QOS_BROWNOUT_FACTOR``; at ``LUMEN_QOS_BULK_SHED_PCT`` bulk
  admissions shed outright (``QueueFull`` with a retry hint) while
  interactive requests keep the remaining headroom — overload degrades
  bulk throughput gracefully instead of wedging everyone.
- **per-tenant token buckets** — :class:`TenantQuota` gates requests at
  the gRPC dispatch layer, BEFORE payload assembly, cache lookups and the
  decode pool: a rejection costs two dict lookups and a float refill
  (~10µs, same order as a breaker shed). ``LUMEN_QOS_TENANT_RPS`` sets
  the default refill rate (0 = unlimited, the default),
  ``LUMEN_QOS_TENANT_BURST`` the bucket depth, and
  ``LUMEN_QOS_RPS_<TENANT>`` / ``LUMEN_QOS_WEIGHT_<TENANT>`` override
  rate and WFQ weight per tenant. Sheds answer RESOURCE_EXHAUSTED-style
  with the ``lumen-retry-after-ms`` response-meta hint, which the shared
  client retry helper uses as its backoff floor.

Tenant identity rides the ``lumen-tenant`` gRPC request-metadata key (or
a ``tenant`` request-meta field for in-process/stub callers); unlabeled
traffic is the ``default`` tenant. Like the request deadline, the
identity crosses layers on a contextvar (:func:`activate` /
:func:`current_tenant`), so no signature between the gRPC handler and the
batcher submit grows a parameter.

The result cache joins in from the side: cache keys are tenant-scoped for
non-default tenants and the RAM tier evicts fair-share-first (see
:mod:`lumen_tpu.runtime.result_cache`), so one tenant's churn cannot
evict another's hot set.

Chaos-tested by ``bench.py --phase qos`` (tenant-A bulk flood vs
interactive tenants B/C: interactive p95 must stay within 2x of its
isolated baseline) and the ``tenant_flood`` fault point
(:mod:`lumen_tpu.testing.faults`) which forces a tenant's quota to read
as exhausted.
"""

from __future__ import annotations

import contextvars
import functools
import logging
import os
import queue as _stdlib_queue
import re
import threading
import time
import weakref
from collections import deque
from typing import Callable, Iterator

from .deadline import QueueFull
from .env import env_float
from .metrics import metrics

logger = logging.getLogger(__name__)

#: tenant id for unlabeled traffic
DEFAULT_TENANT = "default"
#: the two priority lanes (interactive outweighs bulk)
LANE_INTERACTIVE = "interactive"
LANE_BULK = "bulk"
LANES = (LANE_INTERACTIVE, LANE_BULK)

#: gRPC request-metadata key carrying the tenant id
TENANT_META_KEY = "lumen-tenant"
#: response-meta key carrying the server's retry hint on a shed
RETRY_AFTER_META = "lumen-retry-after-ms"


def retry_after_ms(seconds: float) -> str:
    """Format a retry hint for the ``lumen-retry-after-ms`` response-meta
    value: whole milliseconds, floored at 1 — the client drops a hint of
    ``<= 0``, so a sub-millisecond window must still round up to a real
    backoff floor. Every shed site (breaker, quota, QueueFull) emits
    through this one formatter so the contract can't drift per-site."""
    return str(max(1, int(seconds * 1000)))

QOS_ENV = "LUMEN_QOS"
TENANT_RPS_ENV = "LUMEN_QOS_TENANT_RPS"
TENANT_BURST_ENV = "LUMEN_QOS_TENANT_BURST"
BULK_SHARE_ENV = "LUMEN_QOS_BULK_SHARE"
BROWNOUT_PCT_ENV = "LUMEN_QOS_BROWNOUT_PCT"
BROWNOUT_FACTOR_ENV = "LUMEN_QOS_BROWNOUT_FACTOR"
BULK_SHED_PCT_ENV = "LUMEN_QOS_BULK_SHED_PCT"

#: fault point consulted by the quota gate: armed (optionally @matched on
#: the tenant id), the tenant's bucket reads as exhausted — deterministic
#: tenant-flood injection without generating real traffic.
TENANT_FLOOD_POINT = "tenant_flood"


def wfq_enabled() -> bool:
    """``LUMEN_QOS`` (default on): tenant-aware WFQ admission in front of
    every micro-batcher. ``0`` restores the single FIFO queue."""
    return os.environ.get(QOS_ENV, "1") != "0"


#: raw-env-string -> parsed-value memo for the knobs read on EVERY
#: admission (weights, shares, brownout thresholds). Re-parsing a float
#: and clamping it per enqueue is avoidable work on the hottest path;
#: keying on the raw string keeps live-env-change semantics exactly
#: (a changed value is a miss and re-parses). Reads/writes are single
#: dict ops (GIL-atomic); stale overwrites are idempotent.
_env_memo: dict[str, tuple[str | None, float | None]] = {}


def _memo_float(
    name: str,
    default: float | None,
    minimum: float | None = None,
    maximum: float | None = None,
) -> float | None:
    raw = os.environ.get(name)
    hit = _env_memo.get(name)
    if hit is not None and hit[0] == raw:
        return hit[1]
    val = env_float(name, default, minimum=minimum, maximum=maximum)
    if len(_env_memo) >= 4096:
        # Per-tenant knob names are derived from client-supplied tenant
        # ids; an id spray must not grow the memo without bound.
        _env_memo.clear()
    _env_memo[name] = (raw, val)
    return val


def bulk_share() -> float:
    """``LUMEN_QOS_BULK_SHARE``: WFQ weight multiplier for the bulk lane
    (default 0.25 — four interactive requests are served for every bulk
    one when both are backlogged)."""
    return _memo_float(BULK_SHARE_ENV, 0.25, minimum=0.001, maximum=1.0)


def brownout_pct() -> float:
    """``LUMEN_QOS_BROWNOUT_PCT``: queue occupancy (percent of
    ``max_queue``) where the brownout ladder's first rung engages and the
    bulk share shrinks (default 50)."""
    return _memo_float(BROWNOUT_PCT_ENV, 50.0, minimum=1.0, maximum=100.0)


def brownout_factor() -> float:
    """``LUMEN_QOS_BROWNOUT_FACTOR``: how much the bulk share shrinks
    under brownout (default 8 — a browned-out bulk lane gets 1/8th of its
    normal share)."""
    return _memo_float(BROWNOUT_FACTOR_ENV, 8.0, minimum=1.0)


def bulk_shed_pct() -> float:
    """``LUMEN_QOS_BULK_SHED_PCT``: queue occupancy where bulk admissions
    shed outright (default 85) — the remaining headroom is reserved for
    interactive traffic, which still sheds at 100 like before."""
    return _memo_float(BULK_SHED_PCT_ENV, 85.0, minimum=1.0, maximum=100.0)


_warned_brownout = False


def _warn_brownout_unbounded() -> None:
    """One-shot: brownout knobs are set but the admission queue is
    unbounded, so occupancy always reads 0% and the ladder's rungs can
    never engage — a silently inert protection is worse than a loud one."""
    global _warned_brownout
    if _warned_brownout:
        return
    if not any(
        os.environ.get(k)
        for k in (BROWNOUT_PCT_ENV, BROWNOUT_FACTOR_ENV, BULK_SHED_PCT_ENV)
    ):
        return
    _warned_brownout = True
    logger.warning(
        "brownout knobs (LUMEN_QOS_BROWNOUT_PCT / LUMEN_QOS_BULK_SHED_PCT) "
        "set but the admission queue is "
        "unbounded (LUMEN_BATCH_QUEUE_DEPTH unset/0): occupancy reads 0% "
        "and the brownout ladder never engages; set a queue depth to arm it"
    )


_ENV_SAFE = re.compile(r"[^A-Z0-9]+")


@functools.lru_cache(maxsize=1024)
def tenant_env_suffix(tenant: str) -> str:
    """Env-name fragment for a per-tenant override knob: uppercased, every
    non-alphanumeric run collapsed to ``_`` (tenant ``team-a`` reads
    ``LUMEN_QOS_RPS_TEAM_A``). Memoized — this runs per admission and per
    quota gate; the cache bound caps an id-spraying client's footprint."""
    return _ENV_SAFE.sub("_", tenant.upper())


def tenant_weight(tenant: str) -> float:
    """WFQ weight for ``tenant``: ``LUMEN_QOS_WEIGHT_<TENANT>`` override,
    default 1.0 (equal shares)."""
    w = _memo_float(f"LUMEN_QOS_WEIGHT_{tenant_env_suffix(tenant)}", 1.0, minimum=0.001)
    return w if w and w > 0 else 1.0


def tenant_rps(tenant: str) -> float:
    """Token-bucket refill rate for ``tenant``:
    ``LUMEN_QOS_RPS_<TENANT>`` override, else the
    ``LUMEN_QOS_TENANT_RPS`` default (0/unset = unlimited)."""
    override = _memo_float(f"LUMEN_QOS_RPS_{tenant_env_suffix(tenant)}", None, minimum=0.0)
    if override is not None:
        return override
    return _memo_float(TENANT_RPS_ENV, 0.0, minimum=0.0)


def tenant_burst(rps: float) -> float:
    """Bucket depth: ``LUMEN_QOS_TENANT_BURST`` when set, else 2x the
    refill rate (floored at 1 — a limited tenant can always send at least
    one request after idling)."""
    burst = _memo_float(TENANT_BURST_ENV, 0.0, minimum=0.0)
    if burst and burst > 0:
        return max(1.0, burst)
    return max(1.0, 2.0 * rps)


# -- request context ----------------------------------------------------------

_qos_ctx: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "lumen_request_qos", default=None
)


def activate(tenant: str | None, lane: str | None = None) -> contextvars.Token:
    """Install the request's QoS identity for the current context; the
    batcher's WFQ put and the result cache's tenant accounting read it
    from here. ``None`` INHERITS the ambient value for that slot (so
    ingest's ``qos_context(None, LANE_BULK)`` re-lanes a tenant-scoped
    caller's work without erasing the tenant — outside any scope the
    ambient is the default/interactive pair anyway). Returns the token
    for :func:`deactivate`."""
    ambient_tenant, ambient_lane = current_qos()
    t = tenant or ambient_tenant
    ln = lane if lane in LANES else ambient_lane
    return _qos_ctx.set((t, ln))


def deactivate(token: contextvars.Token) -> None:
    _qos_ctx.reset(token)


def current_qos() -> tuple[str, str]:
    """The ambient ``(tenant, lane)`` (defaults outside a request scope)."""
    ctx = _qos_ctx.get()
    return ctx if ctx is not None else (DEFAULT_TENANT, LANE_INTERACTIVE)


def current_tenant() -> str:
    return current_qos()[0]


def current_lane() -> str:
    return current_qos()[1]


class qos_context:
    """``with qos_context("team-a", LANE_BULK): ...`` — scoped identity for
    in-process callers (ingest pipeline, benches, tests)."""

    def __init__(self, tenant: str | None, lane: str | None = None):
        self.tenant, self.lane = tenant, lane
        self._token: contextvars.Token | None = None

    def __enter__(self) -> "qos_context":
        self._token = activate(self.tenant, self.lane)
        return self

    def __exit__(self, *exc) -> None:
        if self._token is not None:
            deactivate(self._token)


# -- weighted-fair admission queue -------------------------------------------


class _Flow:
    """One ``(tenant, lane)`` sub-queue: FIFO entries, each stamped with
    its virtual finish tag at enqueue time."""

    __slots__ = ("tenant", "lane", "entries", "last_tag")

    def __init__(self, tenant: str, lane: str):
        self.tenant = tenant
        self.lane = lane
        self.entries: deque[tuple[float, object]] = deque()
        self.last_tag = 0.0


#: bound on per-tenant stat cardinality in the gauges (an id-spraying
#: client must not grow the metrics payload without limit)
_MAX_TENANT_STATS = 64


class WFQAdmissionQueue:
    """Virtual-time weighted-fair queue, API-compatible with the subset of
    :class:`queue.Queue` the micro-batcher uses (``put`` / ``get`` /
    ``get_nowait`` / ``qsize`` plus the ``None`` close sentinel).

    **Schedule.** Enqueue stamps the entry with
    ``tag = max(V, flow.last_tag) + 1/weight`` where ``V`` is the queue's
    virtual time; dequeue pops the smallest head tag across flows and
    advances ``V`` to it. Weights: the tenant's
    (``LUMEN_QOS_WEIGHT_<TENANT>``, default 1.0) times the lane share
    (1.0 interactive, ``LUMEN_QOS_BULK_SHARE`` bulk, shrunk further by the
    brownout ladder). With one flow the schedule is plain FIFO; within a
    flow it always is.

    **Sentinel.** ``put(None)`` (the batcher's close signal) is *latched*,
    not queued: ``get`` returns it only once every sub-queue is empty —
    the documented close contract ("the sentinel lands after any
    already-submitted item") holds by construction rather than by
    enqueue order.

    **Brownout.** When ``max_queue`` is known (>0), occupancy drives the
    bulk lane's degradation: past ``LUMEN_QOS_BROWNOUT_PCT`` its weight
    shrinks by ``LUMEN_QOS_BROWNOUT_FACTOR``; past
    ``LUMEN_QOS_BULK_SHED_PCT`` bulk puts raise :class:`QueueFull`
    (tagged ``lane="bulk"``) so interactive traffic keeps the remaining
    headroom. Interactive admission is untouched — it sheds only at the
    batcher's own full-queue check, exactly as before.

    Flows are scanned linearly at pop time: tenant cardinality per batcher
    is tens, not thousands, and a linear scan beats heap rebuilds when
    brownout re-weights a lane mid-backlog.
    """

    def __init__(self, name: str = "wfq", max_queue: int = 0):
        self.name = name
        self.max_queue = max(0, max_queue)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._flows: dict[tuple[str, str], _Flow] = {}
        self._vtime = 0.0
        self._total = 0
        self._sentinel = False
        self.stats = {"admitted": 0, "dispatched": 0, "shed_bulk": 0, "brownouts": 0}
        self._tenant_admits: dict[str, int] = {}
        self._tenant_sheds: dict[str, int] = {}
        self._last_rung = 0  # last observed brownout level (event edges)
        #: controller floor on the ladder (None = occupancy-only): the
        #: autopilot descends/ascends the ladder from SLO burn by pinning
        #: this; occupancy can still push the effective rung HIGHER (a
        #: genuinely full queue must brown out even if burn looks fine).
        self._forced_rung: int | None = None
        if self.max_queue <= 0:
            _warn_brownout_unbounded()
        _register_queue(self)

    # -- occupancy / brownout ---------------------------------------------

    def qsize(self) -> int:
        with self._lock:
            return self._total

    def _occupancy_locked(self) -> float:
        if self.max_queue <= 0:
            return 0.0
        return 100.0 * self._total / self.max_queue

    def brownout_level(self) -> int:
        """0 = normal, 1 = bulk share shrunk, 2 = bulk shedding
        (occupancy-derived; :meth:`effective_rung` folds the forced floor
        in — that is what admissions actually use)."""
        with self._lock:
            return self._brownout_locked()

    def _brownout_locked(self) -> int:
        occ = self._occupancy_locked()
        if occ >= bulk_shed_pct():
            return 2
        if occ >= brownout_pct():
            return 1
        return 0

    def _effective_locked(self) -> int:
        forced = self._forced_rung
        level = self._brownout_locked()
        return level if forced is None else max(level, forced)

    def effective_rung(self) -> int:
        """The rung the NEXT admission will be judged by: the occupancy
        ladder with the controller's forced floor folded in. This is the
        single value the autopilot, ``/stats`` readers and the ladder
        itself must agree on (the ``brownout_rung`` gauge field)."""
        with self._lock:
            return self._effective_locked()

    def force_rung(self, level: int | None) -> None:
        """Pin the ladder's FLOOR to ``level`` (clamped 0-2); ``None`` (or
        0) returns control to occupancy alone. The autopilot's brownout
        loop actuates through here so descents driven by SLO burn use the
        exact same shed/share mechanics as occupancy-driven ones."""
        with self._cv:
            if level is None or level <= 0:
                self._forced_rung = None
            else:
                self._forced_rung = min(2, int(level))

    def _bump(self, table: dict[str, int], tenant: str) -> None:
        if tenant not in table and len(table) >= _MAX_TENANT_STATS:
            tenant = "_other"
        table[tenant] = table.get(tenant, 0) + 1

    # -- queue API ---------------------------------------------------------

    def put(self, entry, block: bool = True, timeout: float | None = None) -> None:
        """Enqueue under the ambient QoS identity. Raises
        :class:`QueueFull` for a bulk-lane entry while the brownout
        ladder's shed rung is engaged. (``block``/``timeout`` accepted for
        queue.Queue signature parity; admission is never capacity-blocked
        here — the batcher's own depth check sheds first.)"""
        if entry is None:
            with self._cv:
                self._sentinel = True
                self._cv.notify_all()
            return
        tenant, lane = current_qos()
        # Resolve every env-derived input BEFORE taking the lock: the
        # knob reads (memoized, but still dict lookups) must not
        # serialize concurrent admitters on the queue's condition lock.
        weight = tenant_weight(tenant)
        if lane == LANE_BULK:
            weight *= bulk_share()
        shed_pct, brown_pct = bulk_shed_pct(), brownout_pct()
        brown_factor = brownout_factor()
        shed_at: tuple[float, int] | None = None
        rung_change: tuple[int, int] | None = None
        with self._cv:
            occ = self._occupancy_locked()
            level = 2 if occ >= shed_pct else (1 if occ >= brown_pct else 0)
            forced = self._forced_rung
            if forced is not None and forced > level:
                level = forced
            if level != self._last_rung:
                rung_change = (self._last_rung, level)
                self._last_rung = level
            if lane == LANE_BULK and level >= 2:
                # Decision only under the lock; the counter bumps (which
                # take the process-global metrics lock) and the message
                # formatting happen outside — a flood fires this on every
                # bulk put, and the shed path must not serialize
                # concurrent admitters or the collector's get() behind
                # metrics contention.
                self.stats["shed_bulk"] += 1
                self._bump(self._tenant_sheds, tenant)
                shed_at = (occ, self._total)
            else:
                if lane == LANE_BULK and level == 1:
                    self.stats["brownouts"] += 1
                if lane == LANE_BULK and level >= 1:
                    weight /= brown_factor
                flow = self._flows.get((tenant, lane))
                if flow is None:
                    flow = self._flows[(tenant, lane)] = _Flow(tenant, lane)
                tag = max(self._vtime, flow.last_tag) + 1.0 / max(weight, 1e-9)
                flow.last_tag = tag
                flow.entries.append((tag, entry))
                self._total += 1
                self.stats["admitted"] += 1
                self._bump(self._tenant_admits, tenant)
                self._cv.notify()
        if rung_change is not None:
            # Rung EDGES only (0->1->2 and back), outside the lock: the
            # flight recorder tells the brownout story in a handful of
            # events, while the per-put level itself stays a gauge.
            from . import telemetry

            old, new = rung_change
            via = (
                f"autopilot floor {forced}" if forced is not None and new == forced
                else f"{occ:.0f}% queue occupancy"
            )
            telemetry.record_event(
                "brownout", self.name,
                f"brownout rung {old} -> {new} at {via}",
            )
        if shed_at is not None:
            occ, waiting = shed_at
            metrics.count("qos_bulk_sheds")
            metrics.count(f"qos_bulk_sheds:{self.name}")
            e = QueueFull(
                f"{self.name}: bulk lane browned out at "
                f"{occ:.0f}% queue occupancy "
                f"({waiting} waiting); interactive traffic keeps "
                "the remaining headroom"
            )
            e.lane = LANE_BULK
            e.tenant = tenant
            raise e

    def _pop_locked(self):
        """Smallest-head-tag pop; caller holds the lock and has checked
        ``self._total > 0``."""
        best_key = None
        best_tag = None
        for key, flow in self._flows.items():
            if not flow.entries:
                continue
            tag = flow.entries[0][0]
            if best_tag is None or tag < best_tag:
                best_tag, best_key = tag, key
        flow = self._flows[best_key]
        tag, entry = flow.entries.popleft()
        self._vtime = max(self._vtime, tag)
        self._total -= 1
        self.stats["dispatched"] += 1
        if not flow.entries and flow.last_tag <= self._vtime:
            # A drained flow whose tags can no longer influence the
            # schedule is dropped — tenant churn must not grow the flow
            # table without bound.
            del self._flows[best_key]
        return entry

    def get(self, block: bool = True, timeout: float | None = None):
        """Pop the WFQ-next entry; returns the ``None`` sentinel only when
        every sub-queue is empty. Raises :class:`queue.Empty` on timeout
        (or immediately when ``block`` is false), like the stdlib queue."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                if self._total:
                    return self._pop_locked()
                if self._sentinel:
                    self._sentinel = False
                    return None
                if not block:
                    raise _stdlib_queue.Empty
                if deadline is None:
                    self._cv.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise _stdlib_queue.Empty
                    self._cv.wait(timeout=remaining)

    def get_nowait(self):
        return self.get(block=False)

    # -- telemetry ---------------------------------------------------------

    def gauges(self) -> dict:
        with self._lock:
            out = {
                **self.stats,
                "queued": self._total,
                "brownout": self._brownout_locked(),
                # The rung admissions are ACTUALLY judged by (occupancy
                # ladder + the autopilot's forced floor) — the one value
                # the controller, dashboards and the ladder share.
                "brownout_rung": self._effective_locked(),
                "forced_rung": -1 if self._forced_rung is None else self._forced_rung,
                "occupancy_pct": round(self._occupancy_locked(), 1),
            }
            lane_totals = {LANE_INTERACTIVE: 0, LANE_BULK: 0}
            per_tenant: dict[str, int] = {}
            for (tenant, lane), flow in self._flows.items():
                n = len(flow.entries)
                lane_totals[lane] = lane_totals.get(lane, 0) + n
                # Same 64-id cardinality cap as the admit/shed tables: the
                # flow table itself is bounded by queue depth, but the
                # gauge payload must stay bounded even when the queue is
                # unbounded and an id-spraying client parks one item per
                # fabricated tenant.
                if tenant not in per_tenant and len(per_tenant) >= _MAX_TENANT_STATS:
                    tenant = "_other"
                per_tenant[tenant] = per_tenant.get(tenant, 0) + n
            out["queued_interactive"] = lane_totals[LANE_INTERACTIVE]
            out["queued_bulk"] = lane_totals[LANE_BULK]
            for tenant, n in sorted(per_tenant.items()):
                out[f"queued:{tenant}"] = n
            for tenant, n in sorted(self._tenant_admits.items()):
                out[f"admitted:{tenant}"] = n
            for tenant, n in sorted(self._tenant_sheds.items()):
                out[f"shed:{tenant}"] = n
        return out


# -- per-tenant token buckets -------------------------------------------------


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, tokens: float, last: float):
        self.tokens = tokens
        self.last = last


class TenantQuota:
    """Per-tenant token buckets gating the gRPC dispatch layer.

    ``gate(tenant)`` refills the tenant's bucket from its resolved rate
    (``LUMEN_QOS_RPS_<TENANT>`` else ``LUMEN_QOS_TENANT_RPS``; 0 =
    unlimited, the default) and spends one token, answering
    ``(admitted, retry_after_s)`` — the hint is exactly when the next
    token lands, so a shed client backs off proportionally instead of
    stampeding. O(1): two env/dict lookups and a float multiply; the
    whole point is that a quota rejection costs ~10µs, not a decode or a
    batch slot. An unlimited tenant bypasses the shared lock entirely and
    keeps no per-tenant state — admit/shed accounting exists only for
    rate-limited traffic, so the unconfigured default adds zero contention
    to the dispatch path. The ``tenant_flood`` fault point forces a
    tenant's bucket to read empty for deterministic chaos tests."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: dict[str, _Bucket] = {}
        self.stats: dict[str, dict[str, int]] = {}
        ref = weakref.ref(self)

        def _gauges() -> dict:
            q = ref()
            return {} if q is None else q.gauges()

        self._gauge_fn = _gauges
        metrics.register_gauges("qos-quota", _gauges)

    def _capped_locked(self, tenant: str) -> str:
        """Accounting identity for ``tenant``, bounded at
        ``_MAX_TENANT_STATS`` distinct ids: an id-spraying client must not
        grow the bucket table, the stats dict, the gauge payload, or the
        metrics counter registry — overflow ids collapse onto the shared
        ``_other`` identity (and hence one shared bucket, which
        collectively rate-limits the spray). Caller holds the lock."""
        if tenant in self._buckets or tenant in self.stats:
            return tenant
        if (
            len(self._buckets) >= _MAX_TENANT_STATS
            or len(self.stats) >= _MAX_TENANT_STATS
        ):
            return "_other"
        return tenant

    def gate(self, tenant: str) -> tuple[bool, float]:
        """Admit or shed one request for ``tenant``. Returns
        ``(admitted, retry_after_s)``; the hint is meaningful only when
        shed. An unlimited tenant (no resolved rate, no armed flood — the
        default deployment) returns on a lock-free fast path with no
        per-tenant state: the gate sits on EVERY service's dispatch path,
        including all bulk fan-out workers, and an unconfigured quota must
        not become a process-wide serialization point just for telemetry.
        Rate-limited tenants take ONE acquisition of the shared lock —
        identity capping, the bucket update and the stat bump share a
        single critical section (metrics counters land outside it)."""
        from ..testing.faults import faults  # free when disarmed

        rate = tenant_rps(tenant)
        flood = faults.fires(TENANT_FLOOD_POINT, tenant)
        if rate <= 0 and not flood:
            return True, 0.0
        if flood and rate <= 0:
            rate = 1.0  # armed flood on an unlimited tenant: 1s hint
        burst = tenant_burst(rate)
        now = self._clock()
        with self._lock:
            tenant = self._capped_locked(tenant)
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = _Bucket(burst, now)
            else:
                bucket.tokens = min(
                    burst, bucket.tokens + (now - bucket.last) * rate
                )
                bucket.last = now
            if flood:
                bucket.tokens = 0.0
            if bucket.tokens >= 1.0:
                bucket.tokens -= 1.0
                admitted = True
                retry_after = 0.0
            else:
                admitted = False
                retry_after = (1.0 - bucket.tokens) / rate
            self.stats.setdefault(tenant, {"admits": 0, "sheds": 0})[
                "admits" if admitted else "sheds"
            ] += 1
        if not admitted:
            metrics.count("qos_quota_sheds")
            metrics.count(f"qos_quota_sheds:{tenant}")
            from . import telemetry

            telemetry.record_event(
                "qos_shed", tenant,
                f"tenant over its request-rate quota; next token in "
                f"{retry_after:.2f}s",
                min_interval_s=1.0,
            )
        return admitted, retry_after

    def active(self) -> bool:
        return bool(self.stats)

    def stats_snapshot(self) -> dict[str, dict[str, int]]:
        """Point-in-time copy of the per-tenant admit/shed totals, taken
        under the lock — request threads insert first-seen tenants
        concurrently, and iterating the live dict would intermittently
        blow up a metrics scrape with 'dict changed size'."""
        with self._lock:
            return {tenant: dict(s) for tenant, s in self.stats.items()}

    def gauges(self) -> dict:
        with self._lock:
            tokens = {t: b.tokens for t, b in self._buckets.items()}
        out: dict[str, float] = {}
        for tenant, s in sorted(self.stats_snapshot().items()):
            out[f"admits:{tenant}"] = s["admits"]
            out[f"sheds:{tenant}"] = s["sheds"]
        for tenant, tok in sorted(tokens.items()):
            out[f"tokens:{tenant}"] = round(tok, 2)
        return out

    def close(self) -> None:
        metrics.unregister_gauges("qos-quota", self._gauge_fn)


# -- process-wide state -------------------------------------------------------

_quota: TenantQuota | None = None
_quota_lock = threading.Lock()

#: live WFQ queues by batcher name (weakrefs: the metrics/status surface
#: must not pin a closed batcher's queue)
_wfq_registry: dict[str, "weakref.ref[WFQAdmissionQueue]"] = {}
_wfq_lock = threading.Lock()


def _register_queue(q: WFQAdmissionQueue) -> None:
    with _wfq_lock:
        _wfq_registry[q.name] = weakref.ref(q)


def _live_queues() -> Iterator[WFQAdmissionQueue]:
    with _wfq_lock:
        refs = list(_wfq_registry.items())
    for name, ref in refs:
        q = ref()
        if q is None:
            with _wfq_lock:
                if _wfq_registry.get(name) is ref:
                    del _wfq_registry[name]
            continue
        yield q


def live_queues() -> list[WFQAdmissionQueue]:
    """Every live WFQ admission queue in the process — the autopilot's
    brownout loop actuates the whole set (one ladder policy per process,
    applied per queue so new batchers pick the floor up on the next
    tick)."""
    return list(_live_queues())


def get_quota() -> TenantQuota:
    """The process-wide quota gate (lazily built)."""
    global _quota
    if _quota is None:
        with _quota_lock:
            if _quota is None:
                _quota = TenantQuota()
    return _quota


def reset_quota() -> None:
    """Drop the shared quota state (tests); the next :func:`get_quota`
    rebuilds from the current env."""
    global _quota
    with _quota_lock:
        q, _quota = _quota, None
    if q is not None:
        q.close()


def status() -> dict:
    """Compact live QoS state for the hub's ``lumen-qos-status`` Health
    trailing metadata: per-admission-queue occupancy/brownout and the
    quota gate's per-tenant admit/shed totals. ``{}`` when nothing QoS has
    happened yet (the key is then omitted)."""
    out: dict = {}
    queues = {}
    for q in _live_queues():
        queues[q.name] = {
            "queued": q.qsize(),
            "brownout": q.brownout_level(),
            "rung": q.effective_rung(),
            "shed_bulk": q.stats["shed_bulk"],
        }
    if queues:
        out["wfq"] = queues
    with _quota_lock:
        quota = _quota
    if quota is not None and quota.active():
        out["quota"] = dict(sorted(quota.stats_snapshot().items()))
    return out


def service_extra(*prefixes: str) -> str:
    """One-line QoS summary for a service's capability ``extra["qos"]``:
    whether WFQ admission is on, the lane order, and the brownout level of
    this service's admission queues (batcher names led by any of
    ``prefixes`` — a clip+bioclip hub passes both manager prefixes)."""
    import json

    brown = {
        q.name: q.brownout_level()
        for q in _live_queues()
        if any(q.name.startswith(p) for p in prefixes)
    }
    out = {
        "wfq": "on" if wfq_enabled() else "off",
        "lanes": f"{LANE_INTERACTIVE}>{LANE_BULK}",
    }
    if brown:
        out["brownout"] = max(brown.values())
    return json.dumps(out, sort_keys=True, separators=(",", ":"))
