"""Per-task latency histograms and counters.

The reference's only observability is a per-request ``lat_ms`` response
field (SURVEY.md §5 "Tracing/profiling: none"); here every dispatch also
lands in a process-global registry with log-scale latency histograms, so
operators get p50/p90/p99 per task without scraping response metadata.
Snapshots are exported by the serving server's HTTP metrics endpoint
(``lumen_tpu.serving.observability``) in JSON and Prometheus text formats.
"""

from __future__ import annotations

import math
import threading
import time
from bisect import bisect_left
from typing import Callable, Iterator


def _telemetry():
    """Lazy handle on :mod:`lumen_tpu.utils.telemetry` — resolved at
    first use (telemetry imports THIS module at its top level, so the
    reverse edge must not be an import-time one) and cached."""
    global _telemetry_mod
    if _telemetry_mod is None:
        from . import telemetry

        _telemetry_mod = telemetry
    return _telemetry_mod


_telemetry_mod = None


def _default_bounds() -> list[float]:
    """Log-spaced latency bucket upper bounds in ms: 0.1ms .. ~100s."""
    return [0.1 * (10 ** (i / 6)) for i in range(37)]  # x10 every 6 buckets


class LatencyHistogram:
    """Thread-safe fixed-bucket histogram (ms)."""

    def __init__(self, bounds: list[float] | None = None):
        self.bounds = bounds if bounds is not None else _default_bounds()
        self.counts = [0] * (len(self.bounds) + 1)  # +1 overflow bucket
        self.total = 0
        self.sum_ms = 0.0
        self.min_ms = math.inf
        self.max_ms = 0.0
        self._lock = threading.Lock()

    def observe(self, ms: float) -> None:
        idx = bisect_left(self.bounds, ms)
        with self._lock:
            self.counts[idx] += 1
            self.total += 1
            self.sum_ms += ms
            self.min_ms = min(self.min_ms, ms)
            self.max_ms = max(self.max_ms, ms)

    def percentile(self, q: float) -> float:
        """Approximate quantile (bucket upper bound); 0.0 when empty."""
        with self._lock:
            if self.total == 0:
                return 0.0
            rank = q * self.total
            seen = 0
            for i, c in enumerate(self.counts):
                seen += c
                if seen >= rank:
                    return self.bounds[i] if i < len(self.bounds) else self.max_ms
            return self.max_ms

    def cumulative(self) -> list[tuple[float, int]]:
        """``(upper_bound_ms, cumulative_count)`` pairs ending with
        ``(inf, total)`` — the Prometheus histogram ``_bucket`` contract
        (cumulative ``le`` buckets), not the internal per-bucket counts."""
        return self.exposition()[0]

    def exposition(self) -> tuple[list[tuple[float, int]], int, float]:
        """``(cumulative_buckets, total, sum_ms)`` from ONE locked read:
        the exposition format requires ``_bucket{le="+Inf"}`` == ``_count``
        within a scrape, so buckets and totals must not come from two
        reads with observes landing in between."""
        with self._lock:
            counts = list(self.counts)
            total = self.total
            sum_ms = self.sum_ms
        out: list[tuple[float, int]] = []
        seen = 0
        for bound, n in zip(self.bounds, counts):
            seen += n
            out.append((bound, seen))
        out.append((math.inf, total))
        return out, total, sum_ms

    def snapshot(self) -> dict:
        with self._lock:
            total, s = self.total, self.sum_ms
            mn = 0.0 if math.isinf(self.min_ms) else self.min_ms
            mx = self.max_ms
        return {
            "count": total,
            "sum_ms": round(s, 3),
            "mean_ms": round(s / total, 3) if total else 0.0,
            "min_ms": round(mn, 3),
            "max_ms": round(mx, 3),
            "p50_ms": round(self.percentile(0.50), 3),
            "p90_ms": round(self.percentile(0.90), 3),
            "p99_ms": round(self.percentile(0.99), 3),
        }


class MetricsRegistry:
    """Task name -> latency histogram + ok/error counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hist: dict[str, LatencyHistogram] = {}
        self._errors: dict[str, int] = {}
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, Callable[[], dict]] = {}
        self._provider_errors_warned: set[str] = set()
        self.started_at = time.time()

    def register_gauges(self, provider: str, fn: Callable[[], dict]) -> None:
        """Attach a named callable returning ``{gauge_name: number}``,
        sampled at snapshot time. Batchers and decode schedulers use this
        to expose live state (queue depth, pool occupancy, padding waste)
        that per-request latency histograms can't show.

        Providers should close over a ``weakref`` to their component (see
        the batcher) — the process-global registry must not be what keeps
        a dropped component's weights alive. Re-registering a name
        replaces the previous provider (last writer wins)."""
        with self._lock:
            self._gauges[provider] = fn

    def unregister_gauges(self, provider: str, fn: Callable | None = None) -> None:
        """Remove a provider. Pass the registered ``fn`` to make removal
        ownership-guarded: if a newer same-name registration replaced
        yours, your close() must not delete the live component's gauges."""
        with self._lock:
            if fn is None or self._gauges.get(provider) is fn:
                self._gauges.pop(provider, None)

    def observe(self, task: str, ms: float) -> None:
        hist = self._hist.get(task)
        if hist is None:
            with self._lock:
                hist = self._hist.setdefault(task, LatencyHistogram())
        hist.observe(ms)
        # Tee into the rolling-window capacity layer: the cumulative
        # histogram above answers "since boot", the ring answers "the
        # last N seconds" (and feeds the SLO burn engine). No-op (one
        # cached env check) under LUMEN_TELEMETRY=0.
        _telemetry().observe(task, ms)

    def count_error(self, task: str) -> None:
        with self._lock:
            self._errors[task] = self._errors.get(task, 0) + 1
        _telemetry().count_error(task)

    def count(self, name: str, n: int = 1) -> None:
        """Bump a named event counter (monotonic). The resilience layer
        records load sheds, deadline drops, retries, and degraded-service
        recoveries here — overload behavior must be observable, not
        inferred from latency percentiles after the fact."""
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n
        _telemetry().count(name, n)

    def counter_value(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        with self._lock:
            hists = dict(self._hist)
            errors = dict(self._errors)
            counters = dict(self._counters)
            providers = dict(self._gauges)
        tasks = {
            name: {**h.snapshot(), "errors": errors.get(name, 0)}
            for name, h in hists.items()
        }
        # Tasks that only ever failed still belong in the table (a
        # 100%-failing task must not be invisible to consumers).
        empty = LatencyHistogram(bounds=[]).snapshot()
        for name, n in errors.items():
            if name not in tasks:
                tasks[name] = {**empty, "errors": n}
        gauges: dict[str, dict] = {}
        for name, fn in sorted(providers.items()):
            try:
                vals = fn() or {}
            except Exception:  # noqa: BLE001 - metrics must never take down serving
                # One bad provider is skipped, never a 500 for the whole
                # scrape — but silently is how a dashboard goes dark:
                # log it once per provider name and keep a counter so
                # the failure itself is observable.
                self.count("gauge_provider_errors")
                with self._lock:
                    first = name not in self._provider_errors_warned
                    self._provider_errors_warned.add(name)
                if first:
                    import logging

                    logging.getLogger("lumen_tpu.metrics").exception(
                        "gauge provider %r raised; skipping it in this and "
                        "future snapshots until it behaves", name,
                    )
                continue
            vals = {
                k: v for k, v in vals.items()
                # bools pass isinstance(int) but render as True/False,
                # which breaks the whole Prometheus scrape parse
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
            if vals:
                gauges[name] = vals
        out = {
            "uptime_s": round(time.time() - self.started_at, 1),
            "tasks": dict(sorted(tasks.items())),
        }
        if counters:
            out["counters"] = dict(sorted(counters.items()))
        if gauges:
            out["gauges"] = gauges
        return out

    _probe_warned = False

    @staticmethod
    def _log_probe_failure_once(msg: str) -> None:
        if not MetricsRegistry._probe_warned:
            MetricsRegistry._probe_warned = True
            import logging

            logging.getLogger("lumen_tpu.metrics").warning(msg)

    @staticmethod
    def device_memory() -> dict[str, dict[str, int]]:
        """Per-device memory stats (HBM accounting: params + KV caches +
        live buffers). TPU backends report bytes_in_use/bytes_limit via
        PJRT; backends without stats (CPU) yield empty dicts."""
        try:
            import sys

            jax = sys.modules.get("jax")
            if jax is None:
                return {}  # jax never imported: nothing to report
            from jax._src import xla_bridge

            backends = getattr(xla_bridge, "_backends", None)
            if backends is None:
                # Private attribute moved in a jax upgrade: degrade to
                # empty but say so once instead of silently vanishing.
                MetricsRegistry._log_probe_failure_once(
                    "jax._src.xla_bridge._backends not found; "
                    "device_memory metrics disabled"
                )
                return {}
            if not backends:
                # Metrics must be side-effect-free: jax.devices() would
                # INITIALIZE a backend (seconds of init — and on a TPU
                # host, a chip claim) from inside the metrics HTTP thread
                # of a server that may never use jax (e.g. echo).
                return {}

            out = {}
            for dev in jax.devices():
                stats = getattr(dev, "memory_stats", lambda: None)() or {}
                out[str(dev.id)] = {
                    k: int(v)
                    for k, v in stats.items()
                    if isinstance(v, (int, float)) and "bytes" in k
                }
            return out
        except Exception:  # noqa: BLE001 - metrics must never take down serving
            return {}

    @staticmethod
    def _le(bound: float) -> str:
        return "+Inf" if math.isinf(bound) else f"{bound:.6g}"

    def prometheus_lines(self) -> Iterator[str]:
        """Prometheus text exposition of the same data. Latency is a real
        cumulative histogram (``le``-labeled ``_bucket`` series plus
        ``_sum``/``_count``) — scrapeable by an actual Prometheus/Grafana
        stack (``histogram_quantile()`` works server-side), unlike the
        snapshot-only quantile gauges this replaced, which could not be
        aggregated across instances or re-quantiled over time ranges.
        ``/metrics.json`` keeps the p50/p90/p99 snapshot shape."""
        snap = self.snapshot()
        with self._lock:
            hists = dict(self._hist)
        yield "# TYPE lumen_task_requests_total counter"
        for name, s in snap["tasks"].items():
            yield f'lumen_task_requests_total{{task="{name}"}} {s["count"]}'
        yield "# TYPE lumen_task_errors_total counter"
        for name, s in snap["tasks"].items():
            yield f'lumen_task_errors_total{{task="{name}"}} {s["errors"]}'
        yield "# TYPE lumen_task_latency_ms histogram"
        for name, s in snap["tasks"].items():
            hist = hists.get(name)
            if hist is not None:
                # Buckets + sum + count from ONE locked read: an observe
                # landing mid-scrape must not make le="+Inf" disagree
                # with _count (an inconsistent histogram breaks
                # OpenMetrics validation and bucket-based rate math).
                buckets, total, sum_ms = hist.exposition()
                for bound, cum in buckets:
                    yield (
                        f'lumen_task_latency_ms_bucket{{task="{name}",'
                        f'le="{self._le(bound)}"}} {cum}'
                    )
                yield f'lumen_task_latency_ms_sum{{task="{name}"}} {round(sum_ms, 3)}'
                yield f'lumen_task_latency_ms_count{{task="{name}"}} {total}'
            else:
                # Error-only task: no histogram yet, but the series must
                # still be well-formed (a +Inf bucket is mandatory).
                yield f'lumen_task_latency_ms_bucket{{task="{name}",le="+Inf"}} 0'
                yield f'lumen_task_latency_ms_sum{{task="{name}"}} 0.0'
                yield f'lumen_task_latency_ms_count{{task="{name}"}} 0'
        if snap.get("counters"):
            yield "# TYPE lumen_events_total counter"
            for name, val in snap["counters"].items():
                yield f'lumen_events_total{{event="{name}"}} {val}'
        if snap.get("gauges"):
            yield "# TYPE lumen_component_gauge gauge"
            for provider, vals in snap["gauges"].items():
                for key, val in vals.items():
                    yield (
                        f'lumen_component_gauge{{provider="{provider}",'
                        f'name="{key}"}} {val}'
                    )
        mem = self.device_memory()
        if any(mem.values()):
            yield "# TYPE lumen_device_memory_bytes gauge"
            for dev_id, stats in mem.items():
                for key, val in stats.items():
                    yield f'lumen_device_memory_bytes{{device="{dev_id}",kind="{key}"}} {val}'


#: process-global registry used by the serving layer
metrics = MetricsRegistry()
