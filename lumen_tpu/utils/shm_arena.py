"""Parent-owned shared-memory slot arena for the process decode pool.

Decoded pixels produced in a worker process reach the parent without a
pickle copy by landing in a ``multiprocessing.shared_memory`` slot the
PARENT allocated: the parent picks a slot, ships its *name* with the
task, the worker attaches and writes, and the parent maps a numpy view
over the same pages. The batcher's collector then stacks straight from
that view — the only pixel copy on the whole hop is the one the
collector was already making into its staging arena.

Design rules (the same ones the PR 5 pinned staging arenas follow):

- **Slots are recycled, not churned.** Capacities are power-of-two size
  classes with a per-class free list, so steady-state traffic of one
  image geometry reuses the same few segments forever — zero
  ``shm_open``/``mmap`` on the hot path.
- **The parent owns every lifecycle.** Workers only ever attach; they
  never create or unlink. Whatever a worker does — including dying
  mid-write with SIGKILL — cleanup is one process's job. ``close()``
  unlinks everything, and a ``weakref.finalize`` (which doubles as an
  atexit hook) backstops a dropped or crashed parent so ``/dev/shm``
  never accumulates orphans.
- **Accounting must balance.** ``acquired - released`` is the number of
  live checkouts; at drain it is zero, and the gauges make that an
  assertable invariant rather than a hope.

A soft byte budget (default 256 MiB) bounds arena growth under a
payload flood: past it, ``acquire`` returns ``None`` and the caller
degrades to the pickled spill path (correct, just not zero-copy).
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import weakref
from multiprocessing import shared_memory

logger = logging.getLogger(__name__)

#: smallest slot: one size class covers all thumbnail-ish outputs.
MIN_SLOT_BYTES = 1 << 16


class ArenaSlot:
    """One checked-out shared-memory slot. ``name`` is what crosses the
    process boundary; ``view(shape, dtype)`` maps the decoded result."""

    __slots__ = ("name", "capacity", "_shm", "_arena", "_released")

    def __init__(self, arena: "ShmArena", shm: shared_memory.SharedMemory, capacity: int):
        self._arena = arena
        self._shm = shm
        self.name = shm.name
        self.capacity = capacity
        self._released = False

    def view(self, shape, dtype, offset: int = 0):
        """Map ``shape``/``dtype`` over the slot's pages starting at byte
        ``offset`` — several arrays can pack into one lease (the KV spill
        tier lays a whole page export out back to back in one slot)."""
        import numpy as np

        return np.ndarray(
            shape, dtype=np.dtype(dtype), buffer=self._shm.buf, offset=offset
        )

    @property
    def buf(self):
        """The slot's raw buffer (memoryview) — checksum/packing helpers
        read it directly instead of materializing a typed view."""
        return self._shm.buf

    def release(self) -> None:
        """Return the slot to its free list (idempotent — a finally block
        and a safety finalizer may both call it)."""
        if self._released:
            return
        self._released = True
        self._arena._release(self)


class ShmArena:
    def __init__(self, name: str = "decode", max_bytes: int = 256 << 20):
        self.name = name
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._free: dict[int, list[shared_memory.SharedMemory]] = {}
        #: every segment ever created, free or checked out — the one map
        #: cleanup walks. Shared with the finalizer closure, NOT self:
        #: a finalize callback holding self would keep the arena alive.
        self._segments: dict[str, shared_memory.SharedMemory] = {}
        self._bytes = 0
        self._seq = itertools.count()
        self._acquired = 0
        self._released = 0
        self._denied = 0
        self._closed = False
        # weakref.finalize registers an atexit hook too: GC'd arena OR
        # interpreter exit, either way the segments are unlinked exactly
        # once. The shared mutable dict is emptied by close(), so a
        # later finalize run finds nothing left to do.
        self._finalizer = weakref.finalize(self, ShmArena._unlink_all, self._segments)

    @staticmethod
    def _unlink_all(segments: dict[str, shared_memory.SharedMemory]) -> None:
        for seg in list(segments.values()):
            try:
                seg.close()
                seg.unlink()
            except Exception:  # noqa: BLE001 - cleanup keeps going regardless
                pass
        segments.clear()

    @staticmethod
    def _capacity_for(nbytes: int) -> int:
        cap = MIN_SLOT_BYTES
        while cap < nbytes:
            cap <<= 1
        return cap

    def acquire(self, nbytes: int) -> ArenaSlot | None:
        """A slot of at least ``nbytes`` capacity, or ``None`` when the
        arena is closed or the byte budget would be exceeded (caller
        falls back to the non-shm path)."""
        cap = self._capacity_for(max(1, nbytes))
        with self._lock:
            if self._closed:
                return None
            free = self._free.get(cap)
            if free:
                shm = free.pop()
            else:
                if self._bytes + cap > self.max_bytes:
                    self._denied += 1
                    return None
                name = f"lumendec_{self.name}_{os.getpid()}_{next(self._seq)}"
                try:
                    shm = shared_memory.SharedMemory(name=name, create=True, size=cap)
                except Exception as e:  # noqa: BLE001 - no /dev/shm, exotic platform
                    logger.warning("shm arena allocation failed (%s); spilling", e)
                    self._denied += 1
                    return None
                self._segments[shm.name] = shm
                self._bytes += cap
            self._acquired += 1
        return ArenaSlot(self, shm, cap)

    def _release(self, slot: ArenaSlot) -> None:
        with self._lock:
            self._released += 1
            if self._closed or slot.name not in self._segments:
                # Closed mid-flight: the finalizer/close already unlinked
                # (or will); do not resurrect the segment into a free list.
                return
            self._free.setdefault(slot.capacity, []).append(slot._shm)

    def live(self) -> int:
        with self._lock:
            return self._acquired - self._released

    def stats(self) -> dict:
        with self._lock:
            return {
                "segments": len(self._segments),
                "bytes": self._bytes,
                "acquired": self._acquired,
                "recycled": self._released,
                "live": self._acquired - self._released,
                "denied": self._denied,
            }

    def close(self) -> None:
        """Unlink every segment now (idempotent). Live views become
        invalid — callers drain before closing, same contract as the
        decode pool's own close."""
        with self._lock:
            self._closed = True
            self._free.clear()
            self._bytes = 0
        self._unlink_all(self._segments)
