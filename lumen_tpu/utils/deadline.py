"""Request-deadline propagation + admission-control error types.

The gRPC layer knows each request's deadline (``context.time_remaining()``)
but the device-batching layer — where the expensive work happens — did not:
a request whose client had already hung up would still burn a TPU batch
slot. This module is the thin, dependency-free bridge between the two:

- the serving layer stashes the absolute (monotonic-clock) deadline in a
  :mod:`contextvars` variable before invoking a task handler,
- :class:`~lumen_tpu.runtime.batcher.MicroBatcher` reads it at ``submit``
  time and drops expired entries *before* the device call.

It also owns the two overload exceptions (:class:`QueueFull`,
:class:`DeadlineExpired`) shared across layers. They live here — not in the
batcher — because ``runtime.batcher`` imports jax and the serving base
class must stay importable without it (the echo service serves jax-free).
"""

from __future__ import annotations

import contextvars
import time


class QueueFull(RuntimeError):
    """Admission control shed the request: the batcher queue is at its
    configured depth limit. Maps to a RESOURCE_EXHAUSTED-style wire error
    (retry with backoff); deliberately NOT a subclass of queue.Full so a
    stdlib except clause can't swallow it silently."""


class DeadlineExpired(RuntimeError):
    """The request's deadline passed before (or while) it waited for a
    device slot; the batch executed without it."""


class PreemptionShed(QueueFull):
    """A live VLM decode row was evicted by KV-pool exhaustion and could
    not be spilled/resumed (spill tier disabled, ledger full, or the
    spill path itself failed on a sampled mid-stream row, where a
    restart would splice a fresh draw onto already-delivered tokens).
    Subclasses :class:`QueueFull` so the whole overload machinery applies
    unchanged: the serving layer maps it to RESOURCE_EXHAUSTED and
    surfaces ``retry_after_s`` — the engine's drain estimate — as the
    ``lumen-retry-after-ms`` hint, which floors client backoff
    (``utils/retry.py``)."""


class PoisonInput(RuntimeError):
    """The input was isolated as the cause of a batch failure (batch
    bisection), or its fingerprint is quarantined from a previous
    isolation. Maps to an INVALID_ARGUMENT-style wire error: the payload —
    not the server — is broken, and retrying it is pointless. Lives here
    (not in the batcher or the quarantine registry) for the same reason as
    :class:`QueueFull`: the jax-free serving base class must be able to
    catch it."""


class WatchdogTimeout(RuntimeError):
    """A dispatched batch exceeded the batch watchdog budget
    (``LUMEN_BATCH_WATCHDOG_S``): the device call (or its fetch) is
    presumed wedged. Pending futures are failed with this, and the batcher
    refuses new work — an operator (or the circuit breaker's recovery
    handoff) must reload the service."""


_deadline: contextvars.ContextVar[float | None] = contextvars.ContextVar(
    "lumen_request_deadline", default=None
)


def set_deadline(deadline: float | None) -> contextvars.Token:
    """Install an absolute ``time.monotonic()`` deadline for the current
    context (``None`` clears). Returns the token for :func:`reset`."""
    return _deadline.set(deadline)


def reset(token: contextvars.Token) -> None:
    _deadline.reset(token)


def get_deadline() -> float | None:
    return _deadline.get()


def remaining() -> float | None:
    """Seconds until the current context's deadline; ``None`` when no
    deadline is set. May be negative (already expired)."""
    d = _deadline.get()
    return None if d is None else d - time.monotonic()


def expired(deadline: float | None = None) -> bool:
    d = _deadline.get() if deadline is None else deadline
    return d is not None and time.monotonic() >= d
