"""Decode-owner propagation for disaggregated prefill/decode serving.

When a federation front tier routes a generation request to a
prefill-lane host, it pins the request's DECODE to the decode-lane peer
the hash ring chose and says so in the ``lumen-decode-owner`` gRPC
request-metadata key. That name has to travel from the gRPC dispatch
layer down to the VLM manager's request construction without growing a
parameter on every signature in between — the same contextvar pattern
the request deadline and QoS identity use (:mod:`.deadline`,
:mod:`.qos`).

Off by default: :func:`enabled` stays False until the server boots with
a federation attached (:func:`enable`), so the single-host dispatch path
never even scans request metadata for the key — the unconfigured path
stays byte-identical.
"""

from __future__ import annotations

import contextvars

#: gRPC request-metadata key naming the decode-lane peer that owns this
#: request's decode phase (``host:port``, the peer's federation name).
#: Attached by the front tier only when it forwards to a DIFFERENT peer
#: than the owner; absent means "decode where you prefill".
DECODE_OWNER_META = "lumen-decode-owner"

_owner: contextvars.ContextVar["str | None"] = contextvars.ContextVar(
    "lumen_decode_owner", default=None
)

_enabled = False


def enable() -> None:
    """Turn on metadata scanning (server boot, federation attached)."""
    global _enabled
    _enabled = True


def enabled() -> bool:
    return _enabled


def activate(owner: "str | None") -> contextvars.Token:
    """Bind the request's decode owner for the current dispatch scope."""
    return _owner.set(owner or None)


def deactivate(token: contextvars.Token) -> None:
    _owner.reset(token)


def current() -> "str | None":
    """The decode-lane owner pinned to the current request, or None."""
    return _owner.get()
