"""Host-side image decode: the jax-free half of the host lane.

Every function here runs on host CPU with cv2/PIL only — **no jax
import anywhere in this module's import graph**. That is a load-bearing
property, not a style choice: the process-parallel decode pool
(:mod:`lumen_tpu.runtime.decode_pool`) spawns worker processes whose
entire job is running these functions, and a worker that imported jax
would pay seconds of startup, grab backend memory it never uses, and
race the parent for the accelerator. Workers import THIS module and
nothing heavier.

Two layers live here:

1. **The decode primitives** (``decode_image_bytes``,
   ``decode_image_bytes_scaled``, ``letterbox_numpy``, ...), moved from
   ``lumen_tpu/ops/image.py`` (which re-exports them unchanged — that
   module is the device-side preprocessing home and imports jax at
   module level, so it cannot be the worker-side import).

2. **Named decode specs**: picklable-by-name decode/preprocess recipes
   (``spec name + params dict`` instead of a bound method), so the same
   call crosses a process boundary by reference. Workers resolve the
   name in their own interpreter; the parent never pickles a callable
   or a decoded pixel buffer — outputs land in a shared-memory slot the
   parent handed over (see :mod:`lumen_tpu.utils.shm_arena`).

Thread mode runs the exact same spec functions, so thread- and
process-decoded tensors are bitwise identical by construction.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable

import numpy as np

logger = logging.getLogger(__name__)


def letterbox_params(h: int, w: int, target: int) -> tuple[float, int, int, int, int]:
    """Aspect-preserving resize-with-padding geometry (host-side helper).

    Returns ``(scale, new_h, new_w, pad_top, pad_left)``; the inverse maps
    detector boxes back to original coordinates (reference face pipeline,
    ``lumen_face/backends/onnxrt_backend.py:749-808``).
    """
    scale = min(target / h, target / w)
    new_h, new_w = int(round(h * scale)), int(round(w * scale))
    pad_top = (target - new_h) // 2
    pad_left = (target - new_w) // 2
    return scale, new_h, new_w, pad_top, pad_left


def letterbox_numpy(img: np.ndarray, target: int, fill: int = 0) -> tuple[np.ndarray, float, int, int]:
    """Host letterbox for a single decoded image [H, W, C] -> [target, target, C].

    cv2 (SIMD resize) when present; otherwise the fused native C letterbox,
    so the serving path also works in a no-OpenCV environment.
    """
    try:
        import cv2
    except ImportError:
        cv2 = None
    if cv2 is None and img.dtype == np.uint8:
        from lumen_tpu import native

        if native.available():
            return native.letterbox_u8(img, target, fill)
    if cv2 is None:
        raise RuntimeError("letterbox requires cv2 or the native host-ops library")

    h, w = img.shape[:2]
    scale, new_h, new_w, pad_top, pad_left = letterbox_params(h, w, target)
    resized = cv2.resize(img, (new_w, new_h), interpolation=cv2.INTER_LINEAR)
    out = np.full((target, target, img.shape[2]), fill, dtype=img.dtype)
    out[pad_top : pad_top + new_h, pad_left : pad_left + new_w] = resized
    return out, scale, pad_top, pad_left


#: result-cache namespace qualifier for the scaled-decode generation.
#: Decode resolution changes result numerics (resampling, thresholded
#: detections): disk-tier entries computed under one decode policy must
#: not answer for another across deploys. Bump when the policy changes.
DECODE_POLICY = "sd1"


def probe_image_size(payload: bytes) -> tuple[int, int] | None:
    """Header-only (h, w) probe — no pixel decode. PIL reads just the
    container header lazily; anything unprobeable returns None (the caller
    falls back to a full decode)."""
    try:
        from io import BytesIO

        from PIL import Image

        with Image.open(BytesIO(payload)) as im:
            w, h = im.size
        return (int(h), int(w))
    except Exception:  # noqa: BLE001 - probe is best-effort by contract
        return None


def _factor_from_hw(hw: tuple[int, int] | None, max_edge: int) -> int:
    """Largest scaled-decode factor in {2, 4, 8} that keeps BOTH decoded
    dims >= ``max_edge`` (downstream resizes — square squash or letterbox
    — must only ever downscale). 1 = decode full; engages only when the
    target edge is <= half the source edge."""
    if hw is None or max_edge <= 0:
        return 1
    short = min(hw)
    factor = 1
    while factor < 8 and short // (factor * 2) >= max_edge:
        factor *= 2
    return factor


def _reduced_decode_factor(payload: bytes, max_edge: int) -> int:
    """Header probe + :func:`_factor_from_hw`; an unprobeable payload
    decodes full."""
    if max_edge <= 0:
        return 1
    return _factor_from_hw(probe_image_size(payload), max_edge)


def decode_image_bytes(
    payload: bytes, color: str = "rgb", max_edge: int | None = None, _factor: int | None = None
) -> np.ndarray:
    """Host-side decode to [H, W, 3] uint8 (cv2; PIL fallback for exotic
    formats).

    ``max_edge`` opts into SCALED decode: when the image is at least 2x
    oversized for the target edge, the JPEG is decoded directly at 1/2,
    1/4 or 1/8 scale (cv2 ``IMREAD_REDUCED_COLOR_*`` / PIL ``draft``) —
    the IDCT runs on a fraction of the blocks, cutting decode cost ~4x on
    typical photos. Both decoded dims stay >= ``max_edge``, so downstream
    resize/letterbox to the target only ever downscales. Callers that
    must map coordinates back to the original frame use
    :func:`decode_image_bytes_scaled` instead (``_factor`` lets it reuse
    its one header probe instead of probing twice)."""
    import cv2

    if _factor is not None:
        factor = _factor
    else:
        factor = _reduced_decode_factor(payload, max_edge) if max_edge else 1
    flag = {1: cv2.IMREAD_COLOR, 2: cv2.IMREAD_REDUCED_COLOR_2,
            4: cv2.IMREAD_REDUCED_COLOR_4, 8: cv2.IMREAD_REDUCED_COLOR_8}[factor]
    buf = np.frombuffer(payload, dtype=np.uint8)
    try:
        img = cv2.imdecode(buf, flag)
        if img is None:
            from io import BytesIO

            from PIL import Image

            pil = Image.open(BytesIO(payload))
            if factor > 1:
                # draft() is JPEG-only and advisory; for other formats it
                # is a no-op and the full-size image decodes (correct,
                # just not reduced).
                pil.draft("RGB", (pil.size[0] // factor, pil.size[1] // factor))
            pil = pil.convert("RGB")
            img = np.asarray(pil)
            if color == "bgr":
                img = img[:, :, ::-1]
            return np.ascontiguousarray(img)
    except ValueError:
        raise
    except Exception as e:  # noqa: BLE001 - normalize any decode failure
        raise ValueError(f"cannot decode image payload: {e}") from e
    if color == "rgb":
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return img


def decode_image_bytes_scaled(
    payload: bytes, color: str = "rgb", max_edge: int | None = None
) -> tuple[np.ndarray, float, tuple[int, int]]:
    """Scaled decode WITH provenance: returns ``(img, decode_scale,
    orig_hw)`` where ``decode_scale = decoded_edge / original_edge``
    (1.0 = full decode). Callers that report coordinates (face boxes,
    OCR quads) fold ``decode_scale`` into their letterbox unmap so
    results stay in ORIGINAL image coordinates."""
    hw = probe_image_size(payload) if max_edge else None
    factor = _factor_from_hw(hw, max_edge) if max_edge else 1
    img = decode_image_bytes(payload, color=color, max_edge=max_edge, _factor=factor)
    if hw is None or min(hw) <= 0:
        return img, 1.0, img.shape[:2]
    # Long-edge ratio: robust to decoders that apply a 90-degree EXIF
    # rotation the header probe doesn't see; orig_hw is then derived from
    # the DECODED orientation so callers unclip against consistent axes.
    scale = max(img.shape[:2]) / max(hw)
    if scale >= 0.999:  # full decode (or non-reducible format)
        return img, 1.0, img.shape[:2]
    h, w = img.shape[:2]
    return img, scale, (round(h / scale), round(w / scale))


# ---------------------------------------------------------------------------
# Named decode specs: process-safe decode/preprocess recipes
# ---------------------------------------------------------------------------

#: spec fn(payload, params) -> ndarray OR (ndarray, extras) where extras is
#: a small picklable tuple of per-item provenance (scales, original dims,
#: error strings) that rides the result queue next to the pixels.
DecodeSpec = Callable[[bytes, dict], "np.ndarray | tuple[np.ndarray, tuple]"]

_SPECS: dict[str, DecodeSpec] = {}
_SPEC_EST: dict[str, Callable[[bytes, dict], int]] = {}

#: slot-size guess when the image header is unprobeable: big enough for a
#: full-decode 12 MP photo class; larger outputs take the pickled spill
#: path (correct, just not zero-copy) and are counted by the pool.
DEFAULT_EST_NBYTES = 16 << 20


def register_decode_spec(
    name: str,
    fn: DecodeSpec,
    est_nbytes: Callable[[bytes, dict], int] | None = None,
) -> None:
    """Register a named decode recipe. ``est_nbytes(payload, params)``
    sizes the shared-memory slot BEFORE the decode runs (the parent
    allocates, the worker writes); an estimate that comes in low is safe
    — the worker falls back to returning the array pickled ("spill")."""
    _SPECS[name] = fn
    if est_nbytes is not None:
        _SPEC_EST[name] = est_nbytes


def resolve_decode_spec(name: str) -> DecodeSpec:
    fn = _SPECS.get(name)
    if fn is None:
        raise KeyError(
            f"unknown decode spec {name!r} (registered: {sorted(_SPECS)})"
        )
    return fn


def spec_est_nbytes(name: str, payload: bytes, params: dict) -> int:
    est = _SPEC_EST.get(name)
    if est is None:
        return DEFAULT_EST_NBYTES
    try:
        return max(1, int(est(payload, params)))
    except Exception:  # noqa: BLE001 - a sizing guess must never fail a decode
        return DEFAULT_EST_NBYTES


def _est_fixed_square(payload: bytes, params: dict) -> int:
    size = int(params["size"])
    return size * size * 3


def _est_probe(payload: bytes, params: dict) -> int:
    """Decoded-size estimate from the image header: dims over the scaled
    decode factor, plus a row of slack for the decoder's rounding. The
    header probe here duplicates the one the decode itself runs (~0.1 ms
    against a 10-50 ms decode) — the price of parent-side allocation."""
    hw = probe_image_size(payload if isinstance(payload, bytes) else bytes(payload))
    if hw is None:
        return DEFAULT_EST_NBYTES
    max_edge = int(params.get("max_edge") or 0)
    f = _factor_from_hw(hw, max_edge) if max_edge else 1
    h, w = hw
    return (h // f + 2) * (w // f + 2) * 3


def _spec_decode(payload: bytes, params: dict) -> np.ndarray:
    return decode_image_bytes(
        payload, color=params.get("color", "rgb"),
        max_edge=params.get("max_edge"),
    )


def _spec_decode_scaled(payload: bytes, params: dict):
    img, scale, orig_hw = decode_image_bytes_scaled(
        payload, color=params.get("color", "rgb"),
        max_edge=params.get("max_edge"),
    )
    return img, (float(scale), int(orig_hw[0]), int(orig_hw[1]))


def _spec_clip_resize(payload: bytes, params: dict) -> np.ndarray:
    """CLIP's serving decode: scaled decode + square squash to the tower
    input (the former ``CLIPManager._decode_resize``, spec-ified so it can
    run in a decode worker process)."""
    import cv2

    size = int(params["size"])
    img = decode_image_bytes(payload, color="rgb", max_edge=size)
    return cv2.resize(img, (size, size), interpolation=cv2.INTER_LINEAR)


def _spec_vlm_canvas(payload: bytes, params: dict) -> np.ndarray:
    """VLM's serving decode: scaled decode + pad-to-square letterbox onto
    the vision-tower canvas (the former ``VLMManager._decode_canvas``)."""
    import cv2

    size = int(params["size"])
    img = decode_image_bytes(payload, color="rgb", max_edge=size)
    h, w = img.shape[:2]
    scale = size / max(h, w)
    nh, nw = max(1, round(h * scale)), max(1, round(w * scale))
    resized = cv2.resize(img, (nw, nh), interpolation=cv2.INTER_LINEAR)
    canvas = np.zeros((size, size, 3), np.uint8)
    canvas[:nh, :nw] = resized
    return canvas


def _spec_photo(payload: bytes, params: dict):
    """The photo-ingest producer decode (mirrors
    ``PhotoIngestPipeline._decode`` for byte items): scaled decode with
    provenance and the per-item error-record policy. extras =
    ``(decode_scale, orig_h, orig_w, error_or_None)``."""
    max_edge = int(params.get("max_edge") or 0)
    try:
        if max_edge:
            img, dscale, orig_hw = decode_image_bytes_scaled(
                payload, color="rgb", max_edge=max_edge
            )
        else:
            img, dscale, orig_hw = decode_image_bytes(payload, color="rgb"), 1.0, None
        if img.ndim != 3 or img.shape[2] != 3:
            raise ValueError(f"expected HWC RGB image, got shape {img.shape}")
    except ValueError as e:
        if params.get("on_error") != "record":
            raise
        # Placeholder keeps batch shapes static; stages skip real work.
        return np.zeros((8, 8, 3), np.uint8), (1.0, 8, 8, str(e))
    oh, ow = orig_hw if orig_hw is not None else img.shape[:2]
    return img, (float(dscale), int(oh), int(ow), None)


def _spec_test_kill(payload: bytes, params: dict) -> np.ndarray:
    """Fault-injection spec (tests only): dies mid-decode exactly like a
    segfaulting image codec would — no cleanup, no exception."""
    import signal

    os.kill(os.getpid(), signal.SIGKILL)
    raise AssertionError("unreachable")  # pragma: no cover


def _spec_test_sleep(payload: bytes, params: dict) -> np.ndarray:
    time.sleep(float(params.get("s", 0.05)))
    return np.frombuffer(payload, np.uint8).copy()


register_decode_spec("decode", _spec_decode, _est_probe)
register_decode_spec("decode_scaled", _spec_decode_scaled, _est_probe)
register_decode_spec("clip_resize", _spec_clip_resize, _est_fixed_square)
register_decode_spec("vlm_canvas", _spec_vlm_canvas, _est_fixed_square)
register_decode_spec("photo", _spec_photo, _est_probe)
register_decode_spec("_test_kill", _spec_test_kill, lambda p, _: max(1, len(p)))
register_decode_spec("_test_sleep", _spec_test_sleep, lambda p, _: max(1, len(p)))


# ---------------------------------------------------------------------------
# Process-worker entry points
# ---------------------------------------------------------------------------

_WORKER_SEGMENTS: dict[str, Any] = {}


def proc_worker_init() -> None:
    """Worker-process initializer: cv2's internal thread pool is pinned to
    one thread — parallelism comes from the PROCESS pool; N workers each
    spawning cv2's own per-core threads would oversubscribe the host."""
    try:
        import cv2

        cv2.setNumThreads(1)
    except Exception:  # noqa: BLE001 - cv2 may be absent (PIL-only envs)
        pass


def _attach_segment(name: str):
    """Attach (and cache) a parent-created shared-memory segment, by
    direct mmap of its ``/dev/shm`` backing file where possible. The
    PARENT owns the lifecycle; going through
    ``multiprocessing.shared_memory`` here would enroll the segment in
    THIS process's resource tracker, which 'helpfully' unlinks tracked
    segments when the worker exits (bpo-38119) and would kill every
    sibling's slot — so the fallback path explicitly unregisters."""
    buf = _WORKER_SEGMENTS.get(name)
    if buf is None:
        import mmap

        path = f"/dev/shm/{name}"
        if os.path.exists(path):
            fd = os.open(path, os.O_RDWR)
            try:
                buf = mmap.mmap(fd, os.fstat(fd).st_size)
            finally:
                os.close(fd)
        else:  # pragma: no cover - non-Linux shm layout
            from multiprocessing import resource_tracker, shared_memory

            seg = shared_memory.SharedMemory(name=name)
            try:
                resource_tracker.unregister(seg._name, "shared_memory")
            except Exception:  # noqa: BLE001 - best-effort
                pass
            buf = seg.buf
        _WORKER_SEGMENTS[name] = buf
    return buf


def proc_decode_task(
    spec: str,
    payload: bytes,
    params: dict | None,
    slot_name: str | None,
    capacity: int,
    deadline: float | None,
):
    """One decode in a worker process. Returns a small picklable tuple:

    - ``("deadline", t_pc, t_mono)`` — expired while queued (the worker-
      side twin of the thread pool's pre-run deadline gate);
    - ``("shm", shape, dtype_str, extras, t0_pc, t1_pc, t0_mono, t1_mono)``
      — pixels are in the parent's slot, ONLY metadata crosses the pipe;
    - ``("raw", array, extras, ...timings)`` — no slot / output larger
      than the slot: the array itself is pickled back (the spill path).

    Timings are absolute ``perf_counter`` / ``monotonic`` stamps; on
    Linux both are CLOCK_MONOTONIC and therefore directly comparable
    across processes, which is what lets the parent stitch ``decode.*``
    trace spans and duty-meter credit with thread-mode fidelity.
    """
    t0_pc, t0_mono = time.perf_counter(), time.monotonic()
    if deadline is not None and t0_mono >= deadline:
        return ("deadline", t0_pc, t0_mono)
    fn = resolve_decode_spec(spec)
    out = fn(payload, dict(params) if params else {})
    extras = None
    if isinstance(out, tuple):
        out, extras = out
    arr = np.ascontiguousarray(out)
    t1_pc, t1_mono = time.perf_counter(), time.monotonic()
    if slot_name is not None and arr.nbytes <= capacity:
        buf = _attach_segment(slot_name)
        dst = np.frombuffer(buf, np.uint8, count=arr.nbytes)
        dst[:] = arr.view(np.uint8).reshape(-1)
        return ("shm", arr.shape, arr.dtype.str, extras, t0_pc, t1_pc, t0_mono, t1_mono)
    return ("raw", arr, extras, t0_pc, t1_pc, t0_mono, t1_mono)


def worker_main() -> None:  # pragma: no cover - exercised via subprocess
    """Entry point of one decode worker process (spawned by the pool as
    ``python -c "from lumen_tpu.utils.host_decode import worker_main;
    worker_main()"``). Speaks a length-prefixed pickle protocol over
    stdin/stdout: each request is a :func:`proc_decode_task` argument
    tuple, each response its return tuple (exceptions cross as
    ``("error", type_name, message)``). ``None`` — or EOF — shuts the
    worker down.

    This replaces ``multiprocessing``'s own worker bootstrapping on
    purpose: a spawn/forkserver child re-imports the parent's
    ``__main__`` (for a server launched as ``python -m
    lumen_tpu.serving.server`` that means jax, grpc and a model config
    per worker), while this entry imports exactly this jax-free module.
    """
    import pickle
    import struct
    import sys

    inp = sys.stdin.buffer
    # Claim the protocol fd, then point fd 1 at stderr: a stray print()
    # inside some codec must corrupt a log line, not the wire protocol.
    out = os.fdopen(os.dup(1), "wb")
    os.dup2(2, 1)
    sys.stdout = sys.stderr
    proc_worker_init()
    while True:
        hdr = inp.read(8)
        if len(hdr) < 8:
            return
        (n,) = struct.unpack("<Q", hdr)
        task = pickle.loads(inp.read(n))
        if task is None:
            return
        try:
            res = proc_decode_task(*task)
        except BaseException as e:  # noqa: BLE001 - every verdict crosses the pipe
            res = ("error", type(e).__name__, str(e))
        blob = pickle.dumps(res, protocol=pickle.HIGHEST_PROTOCOL)
        out.write(struct.pack("<Q", len(blob)))
        out.write(blob)
        out.flush()
