"""Shared retry helper: exponential backoff + full jitter + retryable predicate.

Transient-failure policy for the whole stack (hub model downloads, client
stream setup, degraded-service recovery). One implementation so every call
site gets the same discipline — capped exponential backoff with *full*
jitter (delay drawn uniformly from ``[0, cap]``), the AWS-architecture-blog
shape that de-correlates retry storms from thousands of clients hitting the
same recovering backend at once. The reference has no retry layer at all:
one failed snapshot download aborts its server run.

Every retry is visible: attempts land on the process-global metrics
registry as ``retries`` (aggregate) and ``retries:{scope}`` counters, so an
operator can tell "the hub is quietly re-fetching flaky artifacts" from a
dashboard instead of log archaeology.

Server retry hints: when the failure itself says when to come back — a
QoS quota or queue shed carrying ``lumen-retry-after-ms`` trailing meta,
surfaced by callers as a ``retry_after_s`` attribute on the raised
exception — that hint becomes the backoff *floor*: the jittered delay is
taken as usual but never undershoots what the server asked for, so a
shed fleet converges on the server's drain estimate instead of
re-knocking at full-jitter random."""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Type

from .env import env_float
from .metrics import metrics

logger = logging.getLogger(__name__)

#: What callers may pass as the retryable spec: exception classes or a
#: predicate over the raised instance.
Retryable = "tuple[Type[BaseException], ...] | Type[BaseException] | Callable[[BaseException], bool]"


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff schedule. ``attempts`` counts the first try too (1 = no
    retries); ``attempts=0`` means retry without bound (recovery loops cap
    themselves elsewhere)."""

    attempts: int = 3
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    jitter: bool = True

    def delay(self, attempt: int, rng: random.Random | None = None) -> float:
        """Backoff before retry number ``attempt`` (0-based)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2.0 ** attempt))
        if not self.jitter:
            return cap
        return (rng or random).uniform(0.0, cap)

    def delays(self, rng: random.Random | None = None) -> Iterator[float]:
        attempt = 0
        while True:
            yield self.delay(attempt, rng)
            attempt += 1


def policy_from_env(prefix: str, default: RetryPolicy) -> RetryPolicy:
    """Env-tunable policy: ``LUMEN_{PREFIX}_RETRIES`` (extra attempts past
    the first), ``LUMEN_{PREFIX}_BACKOFF_S``, ``LUMEN_{PREFIX}_BACKOFF_MAX_S``.
    Malformed values degrade to the default (same policy as every other
    env knob in the stack: a typo'd override must not crash serving)."""

    def _num(name: str, fallback: float) -> float:
        return env_float(name, fallback)

    retries = _num(f"LUMEN_{prefix}_RETRIES", default.attempts - 1)
    return RetryPolicy(
        attempts=max(1, int(retries) + 1),
        base_delay_s=max(0.0, _num(f"LUMEN_{prefix}_BACKOFF_S", default.base_delay_s)),
        max_delay_s=max(0.0, _num(f"LUMEN_{prefix}_BACKOFF_MAX_S", default.max_delay_s)),
        jitter=default.jitter,
    )


def retry_after_hint(exc: BaseException) -> float | None:
    """The server-provided retry-after hint riding ``exc`` (seconds), or
    None. The convention: any layer that learns when the server wants the
    caller back (the client parsing ``lumen-retry-after-ms`` response
    meta, the batcher stamping its drain estimate on a ``QueueFull``)
    sets ``retry_after_s`` on the exception it raises."""
    hint = getattr(exc, "retry_after_s", None)
    if hint is None:
        return None
    try:
        hint = float(hint)
    except (TypeError, ValueError):
        return None
    return hint if hint > 0 else None


def _is_retryable(exc: BaseException, spec) -> bool:
    if callable(spec) and not isinstance(spec, type):
        try:
            return bool(spec(exc))
        except Exception:  # noqa: BLE001 - a broken predicate must not mask the error
            return False
    return isinstance(exc, spec)


def retry_call(
    fn: Callable,
    *args,
    policy: RetryPolicy | None = None,
    retryable=Exception,
    scope: str = "",
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: random.Random | None = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying on retryable failures.

    ``retryable`` is an exception class/tuple or a predicate; anything else
    propagates immediately (an auth failure or a missing manifest will not
    get better by waiting). ``sleep`` and ``rng`` are injectable so tests
    run deterministic and clock-free.
    """
    policy = policy or RetryPolicy()
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 - filtered by the predicate below
            last_try = policy.attempts > 0 and attempt >= policy.attempts - 1
            if last_try or not _is_retryable(e, retryable):
                raise
            delay = policy.delay(attempt, rng)
            hint = retry_after_hint(e)
            if hint is not None and delay < hint:
                # The server said when to come back: its hint floors the
                # backoff. A jittered overshoot (up to 25% past the hint)
                # de-correlates a fleet shed at the same instant with the
                # same hint — clamping everyone to exactly the hint would
                # resynchronize the stampede on the token-arrival time.
                delay = hint * (1.0 + 0.25 * (rng or random).random())
            metrics.count("retries")
            if scope:
                metrics.count(f"retries:{scope}")
            logger.warning(
                "%s failed (attempt %d/%s): %s; retrying in %.2fs",
                scope or getattr(fn, "__name__", "call"),
                attempt + 1,
                policy.attempts or "inf",
                e,
                delay,
            )
            if on_retry is not None:
                on_retry(attempt, e, delay)
            if delay > 0:
                sleep(delay)
            attempt += 1


def retrying(policy: RetryPolicy | None = None, retryable=Exception, scope: str = ""):
    """Decorator form of :func:`retry_call`."""

    def deco(fn: Callable) -> Callable:
        def wrapped(*args, **kwargs):
            return retry_call(
                fn, *args, policy=policy, retryable=retryable, scope=scope, **kwargs
            )

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped

    return deco
