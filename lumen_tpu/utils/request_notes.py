"""Per-request annotation bridge between the serving and cache layers.

The gRPC layer surfaces a "served from cache" flag in response trailing
metadata, but the cache lookup happens layers below (inside a manager
method, before the decode pool). Same pattern as
:mod:`~lumen_tpu.utils.deadline`: a :mod:`contextvars` variable carries the
cross-layer fact so no signature in between grows a flag. The serving base
class opens a note scope around each task handler; the result cache marks
``hit`` / ``coalesced`` when it answers without a fresh computation; the
quarantine registry marks ``quarantined`` when it rejects a known-poison
payload up front; the service folds the marks into the response ``meta``
(including error responses — a quarantine rejection is an error that
carries its ``quarantined`` note).

Dependency-free on purpose — imported by ``serving.base_service``, which
must not drag in the jax-importing ``runtime`` package.
"""

from __future__ import annotations

import contextvars

_notes: contextvars.ContextVar[dict | None] = contextvars.ContextVar(
    "lumen_request_notes", default=None
)


def begin_notes() -> contextvars.Token:
    """Open a fresh note scope for the current (request) context."""
    return _notes.set({})


def end_notes(token: contextvars.Token) -> dict:
    """Close the scope and return the collected marks (``hit`` /
    ``coalesced`` / ``quarantined`` keys, present when they happened)."""
    marks = _notes.get() or {}
    _notes.reset(token)
    return marks


def current() -> dict:
    """Copy of the current scope's marks (empty outside a scope)."""
    return dict(_notes.get() or {})


def mark(kind: str) -> None:
    """Record a fact about the current request; no-op outside a scope."""
    marks = _notes.get()
    if marks is not None:
        marks[kind] = True
