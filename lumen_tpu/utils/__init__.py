from .logger import setup_logging, get_logger

__all__ = ["setup_logging", "get_logger"]
