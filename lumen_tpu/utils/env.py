"""Shared env-knob parsing: numeric ``LUMEN_*`` reads with loud typos.

Every layer of the stack reads tuning knobs from the environment, and the
house policy is *degrade, don't crash*: a malformed value falls back to
the knob's default. The failure mode of that policy, hand-rolled per call
site, is **silence** — ``LUMEN_BATCH_QUEUE_DEPTH=64O`` (a letter O) used
to read as "unbounded queue" without a word, which is an operator trap:
the protective knob you set is simply not there. These helpers keep the
degrade-to-default contract but WARN, once per knob name, when the value
could not be parsed — so a typo shows up in the boot log instead of in an
incident review.

``None`` is a legal default (for knobs whose unset state means "derive it
elsewhere", e.g. ``LUMEN_BATCH_WINDOW_MS``). Clamping to ``minimum`` /
``maximum`` is applied to *parsed* values only — the default is returned
as given, since each call site already picked a safe one.

Dependency-free on purpose (imported by the jax-free serving base class
and the client).
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

_warned: set[str] = set()
_warned_lock = threading.Lock()


def _warn_once(name: str, raw: str, default) -> None:
    with _warned_lock:
        if name in _warned:
            return
        _warned.add(name)
    logger.warning(
        "malformed env knob %s=%r; using default %r", name, raw, default
    )


def _reset_warnings() -> None:
    """Test hook: forget which knobs already warned."""
    with _warned_lock:
        _warned.clear()


def _clamp(value, minimum, maximum):
    if minimum is not None and value < minimum:
        value = minimum
    if maximum is not None and value > maximum:
        value = maximum
    return value


def env_int(
    name: str,
    default: int | None,
    minimum: int | None = None,
    maximum: int | None = None,
) -> int | None:
    """``int(os.environ[name])`` with the degrade-don't-crash contract:
    unset -> ``default`` (silently), malformed -> ``default`` with a
    one-shot warning naming the knob and the bad value."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return _clamp(int(raw), minimum, maximum)
    except ValueError:
        _warn_once(name, raw, default)
        return default


def env_float(
    name: str,
    default: float | None,
    minimum: float | None = None,
    maximum: float | None = None,
) -> float | None:
    """Float twin of :func:`env_int` (same unset/malformed semantics)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return _clamp(float(raw), minimum, maximum)
    except ValueError:
        _warn_once(name, raw, default)
        return default


def env_list(name: str, default: tuple[str, ...] = ()) -> tuple[str, ...]:
    """Comma-list twin of :func:`env_int`: split on commas, strip
    whitespace, drop empty entries. Unset -> ``default``. There is no
    malformed shape for a string list, so no warning path — entry-level
    validation (e.g. ``host:port`` syntax) belongs to the caller, which
    knows what an entry means."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return tuple(part for part in (p.strip() for p in raw.split(",")) if part)


def env_choice(
    name: str,
    default: str | None,
    choices: tuple[str, ...],
) -> str | None:
    """Enum twin of :func:`env_int`: the value must be one of ``choices``
    (matched case-insensitively, returned in the canonical spelling);
    unset -> ``default`` silently, anything else -> ``default`` with a
    one-shot warning naming the knob, the bad value and the legal set."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    lowered = raw.strip().lower()
    for choice in choices:
        if lowered == choice.lower():
            return choice
    with _warned_lock:
        first = name not in _warned
        _warned.add(name)
    if first:
        logger.warning(
            "malformed env knob %s=%r (expected one of %s); using default %r",
            name, raw, "|".join(choices), default,
        )
    return default
