"""Capacity telemetry: rolling windows, duty cycles, SLO burn, flight recorder.

PR 6 gave the process cumulative histograms and per-request traces; what
it could NOT answer is the set of questions the next ROADMAP items hinge
on: *what fraction of each replica's wall-clock is the device actually
busy right now*, *how much HBM headroom is left*, *is the host lane or
the device the wall this minute* — and after an hour of traffic the
since-boot ``p99_ms`` in ``/metrics.json`` is immovable, so "right now"
is exactly what the old surface cannot say. This module is the
always-on measurement layer that makes those questions answerable from a
single HTTP probe:

- **rolling windows** — every rate/quantile/utilization here lives in a
  ring of time buckets (``LUMEN_TELEMETRY_BUCKET_S`` wide,
  ``LUMEN_TELEMETRY_RETAIN_S`` of history), so ``GET /stats?window=N``
  reports "the last N seconds", not "since boot".
  :class:`RollingCounter` (windowed event totals/rates),
  :class:`RollingHistogram` (windowed latency quantiles) and
  :class:`DutyMeter` (busy-time accounting) share the bucket mechanics.
- **duty cycles** — components report *busy intervals*
  (:func:`busy`): the micro-batcher reports each batch's
  dispatch→settle interval per replica (``device:{batcher}``, the same
  envelope its ``batch.device`` trace spans cover, so span-derived and
  windowed duty agree), the decode pool reports per-task run time
  (``decode:{pool}``, capacity = worker count). A duty fraction is
  ``busy_s / (window * capacity)``.
- **SLO burn-rate engine** — :class:`SLOEngine` reads per-task latency
  objectives from ``LUMEN_SLO_<TASK>_P95_MS`` knobs and an availability
  objective from ``LUMEN_SLO_AVAILABILITY``, tracks good/slow/error
  counts in rolling windows, and reports multi-window (5m/1h)
  error-budget burn rates. Burn > 1 on the short window flips the task
  to ``breach`` (counted on ``slo_breaches`` / ``slo_breaches:{task}``,
  recorded as an ``slo_breach`` flight-recorder event, surfaced in the
  router's ``lumen-slo-status`` Health trailing metadata); burn falling
  back under 1 recovers it.
- **incident flight recorder** — :func:`record_event` appends bounded
  structured operational events (sheds, breaker transitions, replica
  down/revive, quarantine adds, watchdog fires, brownout rung changes,
  recovery swaps) carrying timestamp/tenant/trace-id. Trigger kinds
  (breaker open, replica down, SLO breach) automatically capture an
  **incident bundle**: the recent event window, retained request traces
  (ids + bodies), a device-memory snapshot, and the gauge/counter
  surface — the post-mortem context that is gone by the time a human
  looks, served from the sidecar as ``GET /incidents``.

**Overhead contract** (same discipline as the PR 6 trace layer): the
per-request cost with all telemetry knobs unset is one cached env check
plus one rolling-histogram observe — tier-1 asserts <2µs/request.
Everything else is per-*batch* or per-*event*, and all retention is
bounded (rings, name caps, event/incident caps). ``LUMEN_TELEMETRY=0``
turns the rolling feed into a pure no-op.

Deliberately jax-free (stdlib + ``utils.metrics``/``utils.trace``): the
serving base class, the router and the client import this without a
backend. :mod:`lumen_tpu.runtime.telemetry` is the runtime-side façade,
like ``runtime/qos.py`` and ``runtime/trace.py``.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Callable

from .env import env_float, env_int
from .metrics import MetricsRegistry, metrics

logger = logging.getLogger(__name__)

TELEMETRY_ENV = "LUMEN_TELEMETRY"
BUCKET_ENV = "LUMEN_TELEMETRY_BUCKET_S"
RETAIN_ENV = "LUMEN_TELEMETRY_RETAIN_S"
EVENTS_RING_ENV = "LUMEN_EVENTS_RING"
INCIDENTS_MAX_ENV = "LUMEN_INCIDENTS_MAX"
INCIDENT_COOLDOWN_ENV = "LUMEN_INCIDENT_COOLDOWN_S"
SLO_AVAILABILITY_ENV = "LUMEN_SLO_AVAILABILITY"

#: per-task latency objective knob shape: ``LUMEN_SLO_<TASK>_P95_MS``
#: (task name uppercased, e.g. ``LUMEN_SLO_CLIP_IMAGE_EMBED_P95_MS``).
SLO_PREFIX = "LUMEN_SLO_"
SLO_SUFFIX = "_P95_MS"

#: gRPC Health trailing-metadata key carrying the SLO engine's state
#: (emitted by the router next to the breaker/replica/qos keys).
SLO_META_KEY = "lumen-slo-status"

#: SLO burn windows: (short, long) seconds — the 5m window decides
#: breach/recovery, the 1h window says how fast the monthly budget burns.
SLO_WINDOWS_S = (300.0, 3600.0)

#: event kinds that automatically capture an incident bundle.
INCIDENT_KINDS = ("breaker_open", "replica_down", "slo_breach", "fed_peer_down")

# Latched enabled flag: unlike utils/trace.py's per-call env re-read,
# the always-on layer latches the knob at first use — ``os.environ.get``
# alone costs over a microsecond on a loaded 1-core host, which would
# blow most of the <2µs per-request budget on a parse of the SAME
# answer. ``reset_hub()`` (tests / intentional reconfiguration) drops
# the latch.
_enabled_flag: bool | None = None


def telemetry_enabled() -> bool:
    """``LUMEN_TELEMETRY`` (default ON): the rolling-window feed. ``0``
    turns :func:`observe`/:func:`count`/:func:`busy` into no-ops (the
    flight recorder stays live — events are rare and bounded). Latched
    at first use; :func:`reset_hub` re-reads the env."""
    global _enabled_flag
    flag = _enabled_flag
    if flag is None:
        flag = _enabled_flag = os.environ.get(TELEMETRY_ENV) != "0"
    return flag


def telemetry_bucket_s() -> float:
    """``LUMEN_TELEMETRY_BUCKET_S``: ring time-bucket width (default 5s).
    Window edges are resolved to whole buckets, so reported windows are
    accurate to ±one bucket."""
    return env_float(BUCKET_ENV, 5.0, minimum=0.05)


def telemetry_retain_s() -> float:
    """``LUMEN_TELEMETRY_RETAIN_S``: how much history the rings keep
    (default 600s — enough for ``window=60``/``window=300`` queries; the
    SLO engine keeps its own coarser 1h rings either way)."""
    return env_float(RETAIN_ENV, 600.0, minimum=10.0)


def events_ring() -> int:
    """``LUMEN_EVENTS_RING``: flight-recorder capacity (default 512
    events; 0 disables event recording AND incident capture)."""
    return env_int(EVENTS_RING_ENV, 512, minimum=0)


def incidents_max() -> int:
    """``LUMEN_INCIDENTS_MAX``: retained incident bundles (default 8,
    oldest evicted first)."""
    return env_int(INCIDENTS_MAX_ENV, 8, minimum=1)


def incident_cooldown_s() -> float:
    """``LUMEN_INCIDENT_COOLDOWN_S``: per-kind debounce between bundle
    captures (default 30s) — a flapping breaker must not churn every
    retained bundle out of the store."""
    return env_float(INCIDENT_COOLDOWN_ENV, 30.0, minimum=0.0)


# -- rolling-window primitives ------------------------------------------------


class RollingCounter:
    """Windowed event totals: a ring of per-time-bucket sums.

    ``add(n)`` lands ``n`` in the current bucket; ``total(window_s)``
    sums the buckets covering the last ``window_s`` seconds. Stale slots
    (epochs older than the ring) are lazily zeroed on write and skipped
    on read — no sweeper thread."""

    __slots__ = ("bucket_s", "slots", "_vals", "_epochs", "_lock")

    def __init__(self, bucket_s: float, slots: int):
        self.bucket_s = bucket_s
        self.slots = max(2, slots)
        self._vals = [0.0] * self.slots
        self._epochs = [-1] * self.slots
        self._lock = threading.Lock()

    def add(self, n: float, now: float) -> None:
        epoch = int(now / self.bucket_s)
        i = epoch % self.slots
        with self._lock:
            if self._epochs[i] != epoch:
                self._epochs[i] = epoch
                self._vals[i] = 0.0
            self._vals[i] += n

    def total(self, window_s: float, now: float) -> float:
        epoch = int(now / self.bucket_s)
        # Whole buckets only: the current (partial) bucket counts, plus
        # enough full buckets to cover the window.
        n_back = int(window_s / self.bucket_s)
        oldest = epoch - n_back
        out = 0.0
        with self._lock:
            for i in range(self.slots):
                if oldest <= self._epochs[i] <= epoch:
                    out += self._vals[i]
        return out

    def series(self, window_s: float, now: float) -> list[float]:
        """Per-bucket totals over the last ``window_s`` seconds, oldest
        first, COMPLETED buckets only — the current partial bucket would
        bias a trend fit low. Buckets nothing landed in read 0.0."""
        epoch = int(now / self.bucket_s)
        n_back = min(self.slots - 1, max(2, int(window_s / self.bucket_s)))
        with self._lock:
            have = dict(zip(self._epochs, self._vals))
        return [have.get(e, 0.0) for e in range(epoch - n_back, epoch)]


class RollingHistogram:
    """Windowed latency quantiles: a ring of per-bucket count arrays
    sharing the metrics registry's log-spaced bounds, so a windowed p95
    and the cumulative ``/metrics`` p95 quantize identically."""

    __slots__ = (
        "bucket_s", "slots", "bounds", "_nb",
        "_counts", "_sums", "_totals", "_epochs", "_lock",
    )

    def __init__(self, bucket_s: float, slots: int, bounds: list[float] | None = None):
        from .metrics import _default_bounds

        self.bucket_s = bucket_s
        self.slots = max(2, slots)
        self.bounds = bounds if bounds is not None else _default_bounds()
        self._nb = len(self.bounds) + 1
        # Slot count arrays are allocated lazily (None until first write)
        # so hundreds of mostly-idle names don't pin len(bounds)-sized
        # lists per time bucket.
        self._counts: list[list[int] | None] = [None] * self.slots
        self._sums = [0.0] * self.slots
        self._totals = [0] * self.slots
        self._epochs = [-1] * self.slots
        self._lock = threading.Lock()

    def observe(self, ms: float, now: float) -> None:
        # THE per-request method (via the metrics tee): local-aliased and
        # branch-light on purpose — its cost is most of the always-on
        # <2µs budget the tier-1 guard enforces.
        epoch = int(now / self.bucket_s)
        i = epoch % self.slots
        idx = bisect_left(self.bounds, ms)
        with self._lock:
            epochs = self._epochs
            if epochs[i] != epoch:
                epochs[i] = epoch
                self._counts[i] = None
                self._sums[i] = 0.0
                self._totals[i] = 0
            counts = self._counts[i]
            if counts is None:
                counts = self._counts[i] = [0] * self._nb
            counts[idx] += 1
            self._totals[i] += 1
            self._sums[i] += ms

    def window(self, window_s: float, now: float) -> dict:
        """``{count, sum_ms, mean_ms, p50_ms, p95_ms, p99_ms}`` over the
        last ``window_s`` seconds (quantiles are bucket upper bounds,
        like the cumulative histograms')."""
        epoch = int(now / self.bucket_s)
        oldest = epoch - int(window_s / self.bucket_s)
        merged = [0] * (len(self.bounds) + 1)
        total = 0
        sum_ms = 0.0
        with self._lock:
            for i in range(self.slots):
                if oldest <= self._epochs[i] <= epoch and self._counts[i] is not None:
                    counts = self._counts[i]
                    for j, c in enumerate(counts):
                        merged[j] += c
                    total += self._totals[i]
                    sum_ms += self._sums[i]
        if total == 0:
            return {"count": 0, "sum_ms": 0.0, "mean_ms": 0.0,
                    "p50_ms": 0.0, "p95_ms": 0.0, "p99_ms": 0.0}

        def pct(q: float) -> float:
            rank = q * total
            seen = 0
            for j, c in enumerate(merged):
                seen += c
                if seen >= rank:
                    return self.bounds[j] if j < len(self.bounds) else self.bounds[-1]
            return self.bounds[-1]

        return {
            "count": total,
            "sum_ms": round(sum_ms, 3),
            "mean_ms": round(sum_ms / total, 3),
            "p50_ms": round(pct(0.50), 3),
            "p95_ms": round(pct(0.95), 3),
            "p99_ms": round(pct(0.99), 3),
        }

class DutyMeter:
    """Busy-time accounting for one resource.

    ``add(t0, t1)`` credits the busy interval to the time buckets it
    overlaps. Two modes:

    - **union** (``union=True``, capacity 1) — for a serialized resource
      observed through possibly-overlapping reports (the batcher's
      dispatch→settle envelopes overlap under pipelining): intervals are
      clamped against the furthest end seen, so duty can never exceed
      wall time. Correct because settle order == dispatch order.
    - **sum** (default) — for a pool of ``capacity`` workers reporting
      per-task run time: busy seconds add up and the fraction divides by
      ``window * capacity``.
    """

    __slots__ = ("counter", "capacity", "union", "_last_end", "_lock")

    def __init__(self, bucket_s: float, slots: int, capacity: float = 1.0, union: bool = False):
        self.counter = RollingCounter(bucket_s, slots)
        self.capacity = max(1e-9, capacity)
        self.union = union
        self._last_end = -float("inf")
        self._lock = threading.Lock()

    def add(self, t0: float, t1: float) -> None:
        if self.union:
            with self._lock:
                t0 = max(t0, self._last_end)
                if t1 <= t0:
                    return
                self._last_end = t1
        elif t1 <= t0:
            return
        # Split the interval across the buckets it overlaps (usually 1-2).
        bucket = self.counter.bucket_s
        cur = t0
        while cur < t1:
            edge = (int(cur / bucket) + 1) * bucket
            end = min(edge, t1)
            self.counter.add(end - cur, cur)
            cur = end

    def window(self, window_s: float, now: float) -> dict:
        busy = self.counter.total(window_s, now)
        frac = busy / (window_s * self.capacity) if window_s > 0 else 0.0
        return {
            "busy_s": round(busy, 3),
            "fraction": round(min(1.0, frac), 4),
            "capacity": self.capacity,
        }


# -- SLO engine ---------------------------------------------------------------


def _slo_env_task(key: str) -> str | None:
    """``LUMEN_SLO_CLIP_IMAGE_EMBED_P95_MS`` -> ``clip_image_embed``;
    None for non-objective keys (e.g. ``LUMEN_SLO_AVAILABILITY``)."""
    if not key.startswith(SLO_PREFIX) or not key.endswith(SLO_SUFFIX):
        return None
    middle = key[len(SLO_PREFIX):-len(SLO_SUFFIX)]
    return middle.lower() if middle else None


def slo_objectives() -> dict[str, float]:
    """Per-task p95 objectives from the environment: ``{task: ms}``."""
    out: dict[str, float] = {}
    for key, raw in os.environ.items():
        task = _slo_env_task(key)
        if task is None:
            continue
        try:
            ms = float(raw)
        except ValueError:
            logger.warning("ignoring malformed SLO knob %s=%r", key, raw)
            continue
        if ms > 0:
            out[task] = ms
    return out


def slo_availability() -> float | None:
    """``LUMEN_SLO_AVAILABILITY``: availability objective in (0, 1)
    (e.g. ``0.999``); unset/malformed = no availability SLO."""
    raw = os.environ.get(SLO_AVAILABILITY_ENV)
    if not raw:
        return None
    try:
        v = float(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r", SLO_AVAILABILITY_ENV, raw)
        return None
    return v if 0.0 < v < 1.0 else None


class SLOEngine:
    """Multi-window error-budget burn rates for configured objectives.

    A latency objective ``p95 <= X ms`` allows 5% of requests over X; a
    burn rate is ``observed_slow_fraction / 0.05``. An availability
    objective ``A`` allows ``1 - A`` errors; burn is
    ``error_fraction / (1 - A)``. Burn 1.0 = spending budget exactly at
    the sustainable rate; >1 on the short (5m) window flips the task to
    **breach** (counted + flight-recorded once per transition), and
    dropping back to <=1 recovers it. Evaluation is lazy — every surface
    (Health, ``/slo``, ``/stats``, the ``slo`` gauge provider) evaluates
    on read, so there is no poller thread and fake-clock tests drive
    transitions deterministically.

    The engine keeps its OWN coarse rings (60s buckets x the long
    window) so the 1h burn never depends on ``LUMEN_TELEMETRY_RETAIN_S``.
    Slow/fast is classified EXACTLY at feed time against the objective
    (the precise latency is in hand there) — deriving it from log-spaced
    histogram buckets would leave a ~47%-wide blind band around every
    bucket boundary, and objectives below the first bound could never
    breach at all.
    """

    BUCKET_S = 60.0

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.objectives = slo_objectives()
        self.availability = slo_availability()
        slots = int(SLO_WINDOWS_S[1] / self.BUCKET_S) + 2
        self._n: dict[str, RollingCounter] = {}
        self._slow: dict[str, RollingCounter] = {}
        self._errors: dict[str, RollingCounter] = {}
        self._states: dict[str, str] = {}
        self._slots = slots

    @property
    def enabled(self) -> bool:
        return bool(self.objectives) or self.availability is not None

    #: observe() names that are internal instrumentation, not served
    #: tasks — the availability SLO must not grow bogus "task" rows for
    #: them (per-stage trace histograms, XLA compile durations).
    _INTERNAL_PREFIXES = ("stage:", "xla_")

    def _tracked(self, task: str) -> bool:
        return task in self.objectives or (
            self.availability is not None
            and not task.startswith(self._INTERNAL_PREFIXES)
        )

    def _counter(self, table: dict[str, RollingCounter], task: str) -> RollingCounter:
        ctr = table.get(task)
        if ctr is None:
            with self._lock:
                ctr = table.setdefault(
                    task, RollingCounter(self.BUCKET_S, self._slots)
                )
        return ctr

    def feed(self, task: str, ms: float) -> None:
        if not self._tracked(task):
            return
        now = self._clock()
        self._counter(self._n, task).add(1, now)
        threshold = self.objectives.get(task)
        if threshold is not None and ms > threshold:
            self._counter(self._slow, task).add(1, now)

    def feed_error(self, task: str) -> None:
        if not self._tracked(task):
            return
        self._counter(self._errors, task).add(1, self._clock())

    # -- evaluation --------------------------------------------------------

    def _burns(self, task: str, now: float) -> dict[str, Any]:
        out: dict[str, Any] = {}
        n = self._n.get(task)
        slow_ctr = self._slow.get(task)
        errors = self._errors.get(task)
        threshold = self.objectives.get(task)
        for label, win in zip(("5m", "1h"), SLO_WINDOWS_S):
            total = n.total(win, now) if n is not None else 0
            slow = slow_ctr.total(win, now) if slow_ctr is not None else 0
            err = errors.total(win, now) if errors is not None else 0.0
            burn = 0.0
            if threshold is not None and total > 0:
                burn = (slow / total) / 0.05
            if self.availability is not None and (total + err) > 0:
                avail_burn = (err / (total + err)) / (1.0 - self.availability)
                burn = max(burn, avail_burn)
                out[f"availability_burn_{label}"] = round(avail_burn, 3)
            out[f"burn_{label}"] = round(burn, 3)
            if label == "5m":
                out["window_requests"] = int(total + err)
        if threshold is not None:
            out["objective_p95_ms"] = threshold
        if self.availability is not None:
            out["objective_availability"] = self.availability
        return out

    def status(self) -> dict[str, dict]:
        """Evaluate every tracked task: ``{task: {state, burn_5m,
        burn_1h, ...}}``. Breach transitions are counted and
        flight-recorded HERE (once per ok->breach edge)."""
        if not self.enabled:
            return {}
        now = self._clock()
        with self._lock:
            tasks = sorted(set(self._n) | set(self._errors) | set(self.objectives))
        out: dict[str, dict] = {}
        breached: list[tuple[str, dict]] = []
        recovered: list[str] = []
        for task in tasks:
            rec = self._burns(task, now)
            burn = rec.get("burn_5m", 0.0)
            observed = rec.get("window_requests", 0) > 0
            state = "breach" if (burn > 1.0 and observed) else "ok"
            with self._lock:
                prev = self._states.get(task, "ok")
                self._states[task] = state
            if state == "breach" and prev != "breach":
                breached.append((task, rec))
            elif state == "ok" and prev == "breach":
                recovered.append(task)
            rec["state"] = state
            out[task] = rec
        # Counters/events OUTSIDE the engine lock (metrics.count tees back
        # into the telemetry hub; holding our lock across it invites
        # ordering surprises even though today's paths don't cycle).
        for task, rec in breached:
            metrics.count("slo_breaches")
            metrics.count(f"slo_breaches:{task}")
            record_event(
                "slo_breach", task,
                f"burn_5m={rec.get('burn_5m')} over objective "
                f"(p95<={rec.get('objective_p95_ms', '-')}ms, "
                f"availability>={rec.get('objective_availability', '-')})",
            )
        for task in recovered:
            record_event("slo_recover", task, "burn back under 1.0")
        return out


# -- flight recorder ----------------------------------------------------------


class EventLog:
    """Bounded ring of structured operational events.

    Every record carries a wall-clock timestamp, the ambient tenant (from
    the QoS contextvar) and the active trace id when one exists — an
    event during a traced request greps straight to its trace. High-rate
    kinds (sheds) pass ``min_interval_s`` so a flood cannot churn the
    breaker transitions out of the ring."""

    def __init__(self, capacity: int | None = None):
        self.capacity = events_ring() if capacity is None else max(0, capacity)
        self._ring: deque[dict] = deque(maxlen=max(1, self.capacity))
        self._lock = threading.Lock()
        self._seq = 0
        self._last: dict[tuple[str, str], float] = {}

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def record(
        self,
        kind: str,
        component: str,
        message: str,
        min_interval_s: float = 0.0,
        **fields: Any,
    ) -> dict | None:
        if not self.enabled:
            return None
        now_mono = time.monotonic()
        if min_interval_s > 0:
            key = (kind, component)
            with self._lock:
                last = self._last.get(key)
                if last is not None and now_mono - last < min_interval_s:
                    return None
                self._last[key] = now_mono
        event: dict[str, Any] = {
            "unix_ms": round(time.time() * 1e3, 1),
            "kind": kind,
            "component": component,
            "message": message,
        }
        qos = sys.modules.get("lumen_tpu.utils.qos")
        if qos is not None:
            try:
                tenant = qos.current_tenant()
                if tenant and tenant != qos.DEFAULT_TENANT:
                    event["tenant"] = tenant
            except Exception:  # noqa: BLE001 - telemetry must never break the caller
                pass
        trace_mod = sys.modules.get("lumen_tpu.utils.trace")
        if trace_mod is not None:
            tr = trace_mod.current_trace()
            if tr is not None:
                event["trace_id"] = tr.trace_id
        if fields:
            event.update({k: v for k, v in fields.items() if v is not None})
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            self._ring.append(event)
        return event

    def export(self, n: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        # Positive n = newest-n tail; anything else = everything (a
        # negative slice bound would invert the meaning to drop-oldest).
        return out[-n:] if n is not None and n > 0 else out


class IncidentRecorder:
    """Bounded store of incident bundles — the flight recorder's crash
    dump. A bundle freezes the operational context around a trigger
    event (breaker open, replica down, SLO breach): the recent event
    window, the retained request traces (always-retained error traces
    included, so >=1 correlated trace id exists whenever tracing is on),
    a device-memory snapshot and the live gauge/counter surface."""

    #: traces embedded per bundle (ids of ALL retained traces ride along).
    MAX_TRACES = 8
    #: events embedded per bundle.
    MAX_EVENTS = 64

    def __init__(self, capacity: int | None = None, cooldown_s: float | None = None):
        self.capacity = incidents_max() if capacity is None else max(1, capacity)
        self.cooldown_s = incident_cooldown_s() if cooldown_s is None else max(0.0, cooldown_s)
        self._bundles: deque[dict] = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._last_by_kind: dict[str, float] = {}
        self._capturing = threading.local()

    def capture(self, trigger: dict, events: list[dict], slo: dict) -> dict | None:
        kind = trigger.get("kind", "unknown")
        # Re-entrancy guard: the gauge snapshot below evaluates the SLO
        # gauge provider, whose breach transition would record an
        # slo_breach event and try to capture ANOTHER bundle from inside
        # this one — one bundle per trigger, the nested transition still
        # lands in the event ring and gets its own bundle next probe.
        if getattr(self._capturing, "active", False):
            return None
        now = time.monotonic()
        with self._lock:
            last = self._last_by_kind.get(kind)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_by_kind[kind] = now
            self._seq += 1
            seq = self._seq
        self._capturing.active = True
        from .trace import get_recorder

        try:
            traces = get_recorder().traces()
            snap = metrics.snapshot()
            bundle = {
                "id": seq,
                "unix_ms": round(time.time() * 1e3, 1),
                "kind": kind,
                "trigger": trigger,
                "events": events[-self.MAX_EVENTS:],
                "trace_ids": [t["trace_id"] for t in traces],
                "traces": traces[-self.MAX_TRACES:],
                "device_memory": MetricsRegistry.device_memory(),
                "gauges": snap.get("gauges", {}),
                "counters": snap.get("counters", {}),
                "slo": slo,
            }
        finally:
            self._capturing.active = False
        with self._lock:
            self._bundles.append(bundle)
        metrics.count("incidents_captured")
        logger.error(
            "incident bundle #%d captured (trigger: %s %s — %s)",
            seq, kind, trigger.get("component"), trigger.get("message"),
        )
        return bundle

    def export(self) -> list[dict]:
        with self._lock:
            return list(self._bundles)


# -- the hub ------------------------------------------------------------------


class TelemetryHub:
    """Process-wide container tying the rolling rings, the SLO engine
    and the flight recorder together. One instance per process (see
    :func:`get_hub`); tests build their own with a fake clock and
    install it via :func:`install_hub`."""

    #: cap on distinct rolling names per kind — a name-spraying caller
    #: lands on ``_other`` instead of growing the rings without bound.
    MAX_NAMES = 512

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self.clock = clock
        self.bucket_s = telemetry_bucket_s()
        self.slots = max(2, int(telemetry_retain_s() / self.bucket_s) + 2)
        self.enabled = telemetry_enabled()
        self._lock = threading.Lock()
        self._counters: dict[str, RollingCounter] = {}
        self._hists: dict[str, RollingHistogram] = {}
        self._duties: dict[str, DutyMeter] = {}
        self.slo = SLOEngine(clock=clock)
        self._slo_enabled = self.slo.enabled
        self.events = EventLog()
        self.incidents = IncidentRecorder()
        if self.slo.enabled:
            # Burn-rate gauges next to the component gauges: evaluating
            # at scrape time keeps breach counters live without a poller.
            def _slo_gauges() -> dict:
                out: dict[str, float] = {}
                for task, rec in self.slo.status().items():
                    out[f"burn5m:{task}"] = rec.get("burn_5m", 0.0)
                    out[f"burn1h:{task}"] = rec.get("burn_1h", 0.0)
                    out[f"breach:{task}"] = 1 if rec.get("state") == "breach" else 0
                return out

            self._slo_gauge_fn = _slo_gauges
            metrics.register_gauges("slo", _slo_gauges)

    # -- named-structure access (capped) ----------------------------------

    def _get(self, table: dict, name: str, factory: Callable[[], Any]):
        obj = table.get(name)
        if obj is None:
            with self._lock:
                obj = table.get(name)
                if obj is None:
                    if len(table) >= self.MAX_NAMES:
                        name = "_other"
                        obj = table.get(name)
                        if obj is not None:
                            return obj
                    obj = table[name] = factory()
        return obj

    # -- the feed ----------------------------------------------------------

    def observe(self, name: str, ms: float) -> None:
        hist = self._hists.get(name)
        if hist is None:
            hist = self._get(
                self._hists, name,
                lambda: RollingHistogram(self.bucket_s, self.slots),
            )
        hist.observe(ms, self.clock())
        # _slo_enabled is latched at hub build (objectives are env
        # config, not runtime state): the unconfigured default skips the
        # engine entirely on the per-request path.
        if self._slo_enabled:
            self.slo.feed(name, ms)

    def count(self, name: str, n: float = 1) -> None:
        ctr = self._counters.get(name)
        if ctr is None:
            ctr = self._get(
                self._counters, name,
                lambda: RollingCounter(self.bucket_s, self.slots),
            )
        ctr.add(n, self.clock())

    def count_error(self, task: str) -> None:
        self.count(f"errors:{task}")
        if self._slo_enabled:
            self.slo.feed_error(task)

    def set_capacity(self, name: str, capacity: float, union: bool = False) -> None:
        """(Re)declare a duty-metered resource's capacity — the batcher
        declares ``device:{name}`` (capacity 1, union mode) at start, the
        decode pool declares ``decode:{name}`` with its worker count."""
        with self._lock:
            meter = self._duties.get(name)
            if meter is None:
                if len(self._duties) >= self.MAX_NAMES:
                    return
                self._duties[name] = DutyMeter(
                    self.bucket_s, self.slots, capacity=capacity, union=union
                )
            else:
                meter.capacity = max(1e-9, capacity)
                meter.union = union

    def busy(self, name: str, t0: float, t1: float) -> None:
        meter = self._duties.get(name)
        if meter is None:
            meter = self._get(
                self._duties, name,
                lambda: DutyMeter(self.bucket_s, self.slots),
            )
        meter.add(t0, t1)

    # -- point sensors (the autopilot's read surface) ----------------------

    def duty_fraction(self, name: str, window_s: float) -> float | None:
        """One duty meter's busy fraction over the last ``window_s``
        seconds, or ``None`` when the meter does not exist yet — the
        controller treats "no sensor" as "no actuation", never as 0."""
        meter = self._duties.get(name)
        if meter is None:
            return None
        return meter.window(window_s, self.clock())["fraction"]

    def window_total(self, name: str, window_s: float) -> float:
        """One rolling counter's total over the last ``window_s`` seconds
        (0.0 when the counter does not exist)."""
        ctr = self._counters.get(name)
        return 0.0 if ctr is None else ctr.total(window_s, self.clock())

    def forecast_rate(
        self, name: str, window_s: float, horizon_s: float
    ) -> float | None:
        """Short-horizon arrival-rate forecast for one rolling counter:
        a least-squares line through the per-bucket rates of the last
        ``window_s`` seconds, extrapolated ``horizon_s`` past the newest
        complete bucket and floored at 0. ``None`` when the counter does
        not exist (no sensor = no forecast — the autopilot falls back to
        its reactive thresholds) or the window holds fewer than two
        complete buckets."""
        ctr = self._counters.get(name)
        if ctr is None:
            return None
        series = ctr.series(window_s, self.clock())
        if len(series) < 2:
            return None
        b = ctr.bucket_s
        xs = [i * b for i in range(len(series))]
        ys = [v / b for v in series]
        n = len(xs)
        mean_x = sum(xs) / n
        mean_y = sum(ys) / n
        var = sum((x - mean_x) ** 2 for x in xs)
        if var <= 0:
            return None
        slope = sum(
            (x - mean_x) * (y - mean_y) for x, y in zip(xs, ys)
        ) / var
        return max(0.0, mean_y + slope * (xs[-1] + horizon_s - mean_x))

    def device_duty(self, window_s: float) -> float | None:
        """Worst ``device:*`` duty fraction over the window — the
        host-level headroom signal the federation capacity gossip
        advertises. ``None`` when no device meter exists yet."""
        with self._lock:
            meters = [
                m for n, m in self._duties.items() if n.startswith("device:")
            ]
        if not meters:
            return None
        now = self.clock()
        return max(m.window(window_s, now)["fraction"] for m in meters)

    # -- export ------------------------------------------------------------

    def window_stats(self, window_s: float) -> dict:
        now = self.clock()
        with self._lock:
            hists = dict(self._hists)
            counters = dict(self._counters)
            duties = dict(self._duties)
        tasks = {}
        for name, h in sorted(hists.items()):
            snap = h.window(window_s, now)
            if snap["count"]:
                snap["rps"] = round(snap["count"] / window_s, 3)
                tasks[name] = snap
        counts = {}
        for name, c in sorted(counters.items()):
            total = c.total(window_s, now)
            if total:
                counts[name] = round(total, 3)
        duty = {
            name: d.window(window_s, now)
            for name, d in sorted(duties.items())
        }
        return {
            "window_s": window_s,
            "bucket_s": self.bucket_s,
            "enabled": self.enabled,
            "tasks": tasks,
            "counters": counts,
            "duty": duty,
        }


_hub: TelemetryHub | None = None
_hub_lock = threading.Lock()


def get_hub() -> TelemetryHub:
    """The process-wide hub (lazily built from the env)."""
    global _hub
    if _hub is None:
        with _hub_lock:
            if _hub is None:
                _hub = TelemetryHub()
    return _hub


def install_hub(hub: TelemetryHub | None) -> None:
    """Swap the process hub (tests: inject a fake-clock instance; None
    drops it so the next :func:`get_hub` rebuilds from the env)."""
    global _hub
    with _hub_lock:
        old, _hub = _hub, hub
    if old is not None and getattr(old, "_slo_gauge_fn", None) is not None:
        metrics.unregister_gauges("slo", old._slo_gauge_fn)


def reset_hub() -> None:
    """Drop the shared hub (tests); also re-reads the enabled flag."""
    global _enabled_flag
    _enabled_flag = None
    install_hub(None)


# -- module-level feed (the whole hot-path API) -------------------------------


def enabled() -> bool:
    return telemetry_enabled()


def observe(name: str, ms: float) -> None:
    """Windowed latency observation — THE per-request call (teed from
    ``metrics.observe``). No-op when ``LUMEN_TELEMETRY=0``. Reads the
    latched module globals directly: this is the one call on the
    serving hot path, and every indirection here is paid per request."""
    flag = _enabled_flag
    if flag is None:
        flag = telemetry_enabled()
    if not flag:
        return
    hub = _hub
    if hub is None:
        hub = get_hub()
    # Known-name fast path: skip one call frame (hub.observe) — the
    # slow path below only runs once per new name.
    hist = hub._hists.get(name)
    if hist is None:
        hub.observe(name, ms)
        return
    hist.observe(ms, hub.clock())
    if hub._slo_enabled:
        hub.slo.feed(name, ms)


def count(name: str, n: float = 1) -> None:
    """Windowed event counter (teed from ``metrics.count`` plus direct
    per-batch feeds like ``batch_items:{batcher}``)."""
    if not telemetry_enabled():
        return
    get_hub().count(name, n)


def count_error(task: str) -> None:
    if not telemetry_enabled():
        return
    get_hub().count_error(task)


def busy(name: str, t0: float, t1: float) -> None:
    """Credit a busy interval (``time.monotonic`` bounds) to a duty
    meter — per-batch/per-task, never per-request."""
    if not telemetry_enabled():
        return
    get_hub().busy(name, t0, t1)


def set_capacity(name: str, capacity: float, union: bool = False) -> None:
    if not telemetry_enabled():
        return
    get_hub().set_capacity(name, capacity, union=union)


def duty_fraction(name: str, window_s: float) -> float | None:
    """Windowed busy fraction of one duty meter (``None`` = no meter yet,
    or telemetry disabled — the autopilot's no-sensor/no-actuation rule
    covers both)."""
    if not telemetry_enabled():
        return None
    hub = _hub
    if hub is None:
        return None  # nothing has fed yet; don't build a hub to say so
    return hub.duty_fraction(name, window_s)


def window_total(name: str, window_s: float) -> float:
    """Windowed total of one rolling counter (0.0 when absent/disabled)."""
    if not telemetry_enabled():
        return 0.0
    hub = _hub
    if hub is None:
        return 0.0
    return hub.window_total(name, window_s)


def forecast_rate(name: str, window_s: float, horizon_s: float) -> float | None:
    """Trend-extrapolated arrival rate for one rolling counter
    (``None`` = counter absent, too little history, or telemetry
    disabled — the no-sensor/no-forecast rule)."""
    if not telemetry_enabled():
        return None
    hub = _hub
    if hub is None:
        return None
    return hub.forecast_rate(name, window_s, horizon_s)


def device_duty(window_s: float) -> float | None:
    """Worst device duty fraction across the host's ``device:*`` meters
    (``None`` = no meter yet or telemetry disabled)."""
    if not telemetry_enabled():
        return None
    hub = _hub
    if hub is None:
        return None
    return hub.device_duty(window_s)


def record_event(
    kind: str, component: str, message: str,
    min_interval_s: float = 0.0, **fields: Any,
) -> dict | None:
    """Append a flight-recorder event; trigger kinds
    (:data:`INCIDENT_KINDS`) also capture an incident bundle (debounced
    by ``LUMEN_INCIDENT_COOLDOWN_S``)."""
    hub = get_hub()
    event = hub.events.record(
        kind, component, message, min_interval_s=min_interval_s, **fields
    )
    if event is not None and kind in INCIDENT_KINDS:
        try:
            hub.incidents.capture(
                event, hub.events.export(), slo_status()
            )
        except Exception:  # noqa: BLE001 - capture must never break the trigger path
            logger.exception("incident capture failed for %s", kind)
    return event


def slo_status() -> dict:
    """The SLO engine's evaluated state (``{}`` when no objective is
    configured) — the body of the ``lumen-slo-status`` Health key."""
    hub = _hub
    if hub is None:
        # Don't build a hub just to say "nothing configured".
        if not slo_objectives() and slo_availability() is None:
            return {}
        hub = get_hub()
    return hub.slo.status()


def export_events(n: int | None = None) -> dict:
    hub = get_hub()
    return {
        "capacity": hub.events.capacity,
        "events": hub.events.export(n),
    }


def export_incidents() -> dict:
    hub = get_hub()
    return {
        "capacity": hub.incidents.capacity,
        "cooldown_s": hub.incidents.cooldown_s,
        "incidents": hub.incidents.export(),
    }


# -- the /stats payload -------------------------------------------------------


def _device_memory_view() -> dict:
    """Per-device HBM occupancy + derived headroom from the shared
    ``metrics.device_memory()`` probe (empty on backends without
    stats)."""
    out: dict[str, dict] = {}
    for dev, stats in MetricsRegistry.device_memory().items():
        view = dict(stats)
        in_use = stats.get("bytes_in_use")
        limit = stats.get("bytes_limit")
        if in_use is not None and limit:
            view["headroom_bytes"] = limit - in_use
            view["occupancy_pct"] = round(100.0 * in_use / limit, 2)
        out[dev] = view
    return out


def capacity_stats(window_s: float = 60.0) -> dict:
    """The ``GET /stats?window=N`` body: windowed task latencies and
    event rates, duty cycles, per-batcher batch/padding/transfer
    accounting, XLA compile activity, HBM occupancy/headroom and the SLO
    summary — one probe answering "where is capacity going right now"."""
    window_s = max(1.0, min(float(window_s), 24 * 3600.0))
    hub = get_hub()
    out = hub.window_stats(window_s)
    counters = out["counters"]

    # Per-batcher batch accounting from the windowed counter families.
    batch: dict[str, dict] = {}
    for name, val in counters.items():
        if name.startswith("batch_items:"):
            batch.setdefault(name.split(":", 1)[1], {})["items"] = int(val)
        elif name.startswith("batch_padded:"):
            batch.setdefault(name.split(":", 1)[1], {})["padded"] = int(val)
        elif name.startswith("batch_bucket:"):
            _, batcher, size = name.split(":", 2)
            b = batch.setdefault(batcher, {})
            b.setdefault("buckets", {})[size] = int(val)
    for b in batch.values():
        items = b.get("items", 0)
        padded = b.get("padded", 0)
        slots = items + padded
        b["padding_waste_pct"] = round(100.0 * padded / slots, 2) if slots else 0.0
        if "buckets" in b:
            b["distinct_buckets"] = len(b["buckets"])
    out["batch"] = batch

    transfer: dict[str, dict] = {}
    for name, val in counters.items():
        for direction in ("h2d", "d2h"):
            prefix = f"transfer_{direction}:"
            if name.startswith(prefix):
                t = transfer.setdefault(name[len(prefix):], {})
                t[f"{direction}_bytes"] = int(val)
    out["transfer"] = transfer

    compile_hist = out["tasks"].pop("xla_compile_ms", None)
    out["compile"] = {
        "compiles": int(counters.get("xla_compiles", 0)),
        "ms": compile_hist or None,
    }
    out["device_memory"] = _device_memory_view()
    out["slo"] = slo_status()
    return out


def slo_report() -> dict:
    """The ``GET /slo`` body: objectives + evaluated burn state."""
    hub = get_hub()
    return {
        "objectives": {
            "p95_ms": hub.slo.objectives,
            "availability": hub.slo.availability,
        },
        "windows_s": list(SLO_WINDOWS_S),
        "tasks": hub.slo.status(),
    }
