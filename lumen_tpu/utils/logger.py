"""Shared logging setup.

One configuration point for every entry script (the reference duplicates a
colorlog setup in each package's ``server.py``; here it lives once). Colour
is ANSI-only (no colorlog dependency) and disabled on non-TTY outputs.
"""

from __future__ import annotations

import logging
import sys

_COLORS = {
    logging.DEBUG: "\x1b[36m",
    logging.INFO: "\x1b[32m",
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[1;31m",
}
_RESET = "\x1b[0m"


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        base = super().format(record)
        color = _COLORS.get(record.levelno)
        if color and sys.stderr.isatty():
            return f"{color}{base}{_RESET}"
        return base


def setup_logging(level: str = "INFO") -> None:
    root = logging.getLogger()
    root.setLevel(level.upper())
    # Idempotent: replace our handler if already installed.
    for h in list(root.handlers):
        if getattr(h, "_lumen_tpu", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    handler._lumen_tpu = True  # type: ignore[attr-defined]
    handler.setFormatter(
        _ColorFormatter("%(asctime)s %(levelname)-8s %(name)s: %(message)s", "%H:%M:%S")
    )
    root.addHandler(handler)


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)
