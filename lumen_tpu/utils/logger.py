"""Shared logging setup.

One configuration point for every entry script (the reference duplicates a
colorlog setup in each package's ``server.py``; here it lives once). Colour
is ANSI-only (no colorlog dependency) and disabled on non-TTY outputs.

Log <-> trace correlation: a :class:`TraceContextFilter` stamps every
record emitted while a request trace is live (``LUMEN_TRACE_SAMPLE`` > 0)
with the trace id, and the formatter renders it as a ``[trace=...]``
suffix on the logger name — so a server log line greps straight to its
request in ``GET /traces`` output (and vice versa). Outside a trace the
attribute is an empty string and log lines are byte-identical to before.
"""

from __future__ import annotations

import logging
import sys

from .trace import current_trace

_COLORS = {
    logging.DEBUG: "\x1b[36m",
    logging.INFO: "\x1b[32m",
    logging.WARNING: "\x1b[33m",
    logging.ERROR: "\x1b[31m",
    logging.CRITICAL: "\x1b[1;31m",
}
_RESET = "\x1b[0m"


class TraceContextFilter(logging.Filter):
    """Injects the active request-trace id into every record.

    Sets two attributes: ``trace_id`` (the bare id, or ``""``) for
    structured consumers, and ``trace_tag`` (`` [trace=<id>]`` or ``""``)
    for drop-in use inside a format string. Never rejects a record."""

    def filter(self, record: logging.LogRecord) -> bool:
        tr = current_trace()
        if tr is not None:
            record.trace_id = tr.trace_id
            record.trace_tag = f" [trace={tr.trace_id}]"
        else:
            record.trace_id = ""
            record.trace_tag = ""
        return True


class _ColorFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        # Records from foreign handlers/tests may not have passed through
        # TraceContextFilter; the formatter must not KeyError on them.
        if not hasattr(record, "trace_tag"):
            record.trace_tag = ""
        base = super().format(record)
        color = _COLORS.get(record.levelno)
        if color and sys.stderr.isatty():
            return f"{color}{base}{_RESET}"
        return base


def setup_logging(level: str = "INFO") -> None:
    root = logging.getLogger()
    root.setLevel(level.upper())
    # Idempotent: replace our handler if already installed.
    for h in list(root.handlers):
        if getattr(h, "_lumen_tpu", False):
            root.removeHandler(h)
    handler = logging.StreamHandler(sys.stderr)
    handler._lumen_tpu = True  # type: ignore[attr-defined]
    handler.addFilter(TraceContextFilter())
    handler.setFormatter(
        _ColorFormatter(
            "%(asctime)s %(levelname)-8s %(name)s%(trace_tag)s: %(message)s", "%H:%M:%S"
        )
    )
    root.addHandler(handler)


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(name)
