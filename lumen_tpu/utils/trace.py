"""Request-scoped tracing: per-stage latency attribution for the serving path.

BENCH_r05 measured the device sustaining ~9k img/s/chip while gRPC c10
delivers 77 rps — ROADMAP item 3 says the remaining ~10x lives in the
host/request path, but the metrics registry only records ONE end-to-end
histogram per task. Nobody can say whether a slow request spent its time
in the admission queue, the decode pool, the batch collect window, the
device call, or response serialization. This module is the measurement
layer that makes that legible:

- a :class:`Trace` rides the request on a :mod:`contextvars` variable
  (same cross-layer pattern as ``utils/deadline.py`` and
  ``utils/request_notes.py``); every stage the request crosses appends a
  :class:`Span` (name, start, duration, begin/end thread);
- contextvars do NOT cross threads, so thread-hopping components (the
  pipelined micro-batcher, the decode pool, the ingest consumer) carry
  explicit :class:`SpanHandle` objects attached to their work units —
  a span can *begin* on the gRPC handler thread and *end* on the batch
  collector or fetch worker, and records both thread names;
- finished traces land in a bounded ring with **tail sampling**: errored
  traces and the slowest-N are always retained, the rest are kept with
  probability ``LUMEN_TRACE_SAMPLE``; sampled-out traces leave no
  residue (every span still feeds the per-stage latency histograms);
- the retained set exports as JSON (``GET /traces`` on the metrics
  sidecar) and as Chrome trace-event JSON (``GET /traces/perfetto``,
  loadable in Perfetto/chrome://tracing next to a ``jax.profiler`` dump);
- each span also feeds a ``stage:{task}/{span}`` latency histogram in
  the process metrics registry, so ``bench.py --phase attribution`` can
  print a per-stage time-budget table without parsing traces.

**Overhead contract**: with ``LUMEN_TRACE_SAMPLE=0`` (the default) the
per-request cost is one cached env check plus contextvar reads that
return ``None`` — tier-1 asserts <2µs/request so the layer can stay
wired into the hot path permanently. With sampling on, every request is
traced (spans are appended under a per-trace lock) and the *retention*
decision happens at the tail.

Deliberately jax-free and dependency-light (stdlib + ``utils.metrics``):
imported by the serving base class, the logger, and the example client —
none of which may drag in a backend. ``lumen_tpu.runtime.trace`` is the
canonical façade for runtime-side consumers (the batcher, decode pool,
result cache and ingest pipeline, which already live behind the
jax-importing runtime package ``__init__``).
"""

from __future__ import annotations

import contextvars
import heapq
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Iterator

from .env import env_int
from .metrics import metrics

TRACE_SAMPLE_ENV = "LUMEN_TRACE_SAMPLE"
TRACE_RING_ENV = "LUMEN_TRACE_RING"
TRACE_SLOW_ENV = "LUMEN_TRACE_SLOW_N"

#: gRPC metadata key carrying the client's trace id (client → server
#: propagation; the server's trace adopts the id so both sides join up).
TRACE_META_KEY = "lumen-trace"

#: response-meta key echoing the request's trace id back to the caller.
TRACE_RESPONSE_META = "trace_id"

# The per-request env probe reads os.environ's BACKING DICT directly:
# ``os._Environ.get`` resolves a missing key by raising-and-catching
# KeyError internally, which costs over a microsecond on a loaded 1-core
# host — most of the <2µs disabled-path budget for the same answer every
# time. The backing dict is the store ``os.environ[...]`` (and pytest's
# monkeypatch.setenv) mutate, so visibility semantics are unchanged:
# a mid-process flip is seen on the very next request. Falls back to the
# public API if the CPython internals ever move.
try:
    _env_data = os.environ._data
    _env_key = os.environ.encodekey(TRACE_SAMPLE_ENV)
except AttributeError:  # pragma: no cover - non-CPython / API drift
    _env_data = None
    _env_key = TRACE_SAMPLE_ENV


def _raw_sample():
    data = _env_data
    if data is not None:
        return data.get(_env_key)
    return os.environ.get(TRACE_SAMPLE_ENV)


# (raw env value, parsed rate) — re-parsed only when the raw value
# changes, so the disabled-path check stays a dict lookup + compare.
_rate_cache: tuple = (b"\x00unset", 0.0)


def sample_rate() -> float:
    """``LUMEN_TRACE_SAMPLE``: 0 (default) disables tracing entirely; a
    value in (0, 1] traces every request and *retains* that fraction of
    non-error, non-slowest traces in the ring (tail sampling). Malformed
    values read as 0 (off) — tracing must degrade, not crash serving."""
    global _rate_cache
    raw = _raw_sample()
    cached_raw, cached = _rate_cache
    if raw == cached_raw:
        return cached
    try:
        text = os.fsdecode(raw) if raw is not None else None
        rate = min(1.0, max(0.0, float(text))) if text else 0.0
    except ValueError:
        rate = 0.0
    _rate_cache = (raw, rate)
    return rate


def enabled() -> bool:
    return sample_rate() > 0.0


def trace_ring() -> int:
    """``LUMEN_TRACE_RING``: capacity of the sampled-trace ring buffer
    (unset/malformed -> 256; floor 1)."""
    return env_int(TRACE_RING_ENV, 256, minimum=1)


def trace_slow_n() -> int:
    """``LUMEN_TRACE_SLOW_N``: how many slowest traces are always
    retained regardless of sampling (unset/malformed -> 16; 0 disables
    the slowest-N lane)."""
    return env_int(TRACE_SLOW_ENV, 16, minimum=0)


def new_trace_id() -> str:
    return os.urandom(8).hex()


class SpanHandle:
    """One in-progress span. ``end()`` is idempotent and may run on a
    DIFFERENT thread than ``begin`` — that is the point: the handle is
    what crosses the batcher/decode-pool/ingest thread boundaries that a
    contextvar cannot."""

    __slots__ = ("trace", "name", "t0", "begin_thread", "meta", "_done")

    def __init__(self, trace: "Trace", name: str, meta: dict | None = None):
        self.trace = trace
        self.name = name
        self.t0 = time.perf_counter()
        self.begin_thread = threading.current_thread().name
        self.meta = meta
        self._done = False

    def end(self, error: str | None = None, **meta: Any) -> None:
        if self._done:
            return
        self._done = True
        t1 = time.perf_counter()
        m = dict(self.meta) if self.meta else {}
        if meta:
            m.update(meta)
        if error:
            m["error"] = error
        self.trace._append(
            self.name, self.t0, t1, self.begin_thread,
            threading.current_thread().name, m or None,
        )


class Trace:
    """All spans one request (or one ingest batch) crossed.

    Span timestamps are ``time.perf_counter()`` instants, stored relative
    to ``t0`` in the exported record; ``epoch0`` anchors the record on
    the wall clock for Perfetto. Thread-safe: spans are appended under a
    lock because the batcher fetch worker, the decode pool and the
    request thread all write concurrently."""

    __slots__ = (
        "trace_id", "task", "t0", "epoch0", "spans", "error", "_lock",
    )

    def __init__(self, task: str, trace_id: str | None = None, t0: float | None = None):
        self.trace_id = trace_id or new_trace_id()
        self.task = task
        now = time.perf_counter()
        self.t0 = now if t0 is None else t0
        # Anchor the wall clock at the (possibly back-dated) t0.
        self.epoch0 = time.time() - (now - self.t0)
        self.spans: list[tuple] = []  # (name, t0, t1, begin_thread, end_thread, meta)
        self.error: str | None = None
        self._lock = threading.Lock()

    # -- span recording ----------------------------------------------------

    def begin(self, name: str, meta: dict | None = None) -> SpanHandle:
        return SpanHandle(self, name, meta)

    @contextmanager
    def span(self, name: str, **meta: Any) -> Iterator[SpanHandle]:
        h = self.begin(name, meta or None)
        try:
            yield h
        finally:
            h.end()

    def add_span(
        self, name: str, t0: float, t1: float, meta: dict | None = None
    ) -> None:
        """Record a span with explicit ``perf_counter`` bounds (e.g. the
        gRPC receive/reassembly window, whose start predates the trace
        object)."""
        thread = threading.current_thread().name
        self._append(name, t0, t1, thread, thread, meta)

    def _append(
        self, name: str, t0: float, t1: float,
        begin_thread: str, end_thread: str, meta: dict | None,
    ) -> None:
        with self._lock:
            self.spans.append((name, t0, t1, begin_thread, end_thread, meta))

    def set_error(self, message: str) -> None:
        # First error wins: the root cause, not the last symptom.
        if self.error is None:
            self.error = message

    # -- export ------------------------------------------------------------

    def to_record(self, t_end: float | None = None) -> dict:
        with self._lock:
            spans = list(self.spans)
        if t_end is None:
            # A trace's duration is its SPAN ENVELOPE (first-chunk arrival
            # to the last instrumented stage's end): post-response
            # bookkeeping — generator teardown, the recorder call itself,
            # a preemption between them — is not part of the request and
            # must not show up as unattributed time in the stage budget.
            t_end = max((s[2] for s in spans), default=time.perf_counter())
            t_end = max(t_end, self.t0)
        out_spans = []
        for name, s0, s1, bt, et, meta in spans:
            span: dict[str, Any] = {
                "name": name,
                "start_ms": round((s0 - self.t0) * 1e3, 3),
                "dur_ms": round((s1 - s0) * 1e3, 3),
                "begin_thread": bt,
                "end_thread": et,
            }
            if meta:
                span["meta"] = meta
            out_spans.append(span)
        out_spans.sort(key=lambda s: s["start_ms"])
        rec = {
            "trace_id": self.trace_id,
            "task": self.task,
            "start_unix_ms": round(self.epoch0 * 1e3, 3),
            "duration_ms": round((t_end - self.t0) * 1e3, 3),
            "spans": out_spans,
        }
        if self.error:
            rec["error"] = self.error
        return rec


# -- contextvar propagation --------------------------------------------------

_current: contextvars.ContextVar[Trace | None] = contextvars.ContextVar(
    "lumen_trace", default=None
)


def current_trace() -> Trace | None:
    """The active request's trace, or None (tracing off / outside a
    request). THE hot-path check: one contextvar read."""
    return _current.get()


def activate(trace: Trace) -> contextvars.Token:
    return _current.set(trace)


def deactivate(token: contextvars.Token) -> None:
    _current.reset(token)


@contextmanager
def span(name: str, **meta: Any) -> Iterator[SpanHandle | None]:
    """Span on the current trace; no-op (yields None) when untraced."""
    tr = _current.get()
    if tr is None:
        yield None
        return
    h = tr.begin(name, meta or None)
    try:
        yield h
    finally:
        h.end()


# -- recorder (tail-sampling ring + export) ----------------------------------


class TraceRecorder:
    """Bounded retention of finished traces with tail sampling.

    Three lanes, all bounded:

    - **errors** — a trace that finished with an error is always kept
      (deque, ``capacity // 4`` floor 8);
    - **slowest-N** — a min-heap of the N largest durations seen, so the
      tail a percentile hides is always inspectable;
    - **sampled** — everything else survives with probability
      ``LUMEN_TRACE_SAMPLE`` (ring of ``LUMEN_TRACE_RING``).

    A sampled-out trace leaves no residue here (its spans already fed the
    per-stage histograms in :mod:`lumen_tpu.utils.metrics` — aggregates
    are kept for every request, bodies only for the interesting ones)."""

    def __init__(self, capacity: int | None = None, slow_n: int | None = None):
        self.capacity = trace_ring() if capacity is None else max(1, capacity)
        self.slow_n = trace_slow_n() if slow_n is None else max(0, slow_n)
        self._lock = threading.Lock()
        self._seq = 0
        self._sampled: deque[dict] = deque(maxlen=self.capacity)
        self._errors: deque[dict] = deque(maxlen=max(8, self.capacity // 4))
        self._slow: list[tuple[float, int, dict]] = []  # min-heap
        self._rng = random.Random()
        self.counters = {"finished": 0, "retained": 0, "sampled_out": 0}

    # -- ingestion ---------------------------------------------------------

    def finish(self, trace: Trace, error: str | None = None) -> dict:
        """Close out a trace: feed the per-stage histograms (always) and
        decide retention (tail sampling). Returns the exported record."""
        if error:
            trace.set_error(error)
        record = trace.to_record()
        task = record["task"]
        for s in record["spans"]:
            metrics.observe(f"stage:{task}/{s['name']}", s["dur_ms"])
        metrics.observe(f"stage:{task}/_total", record["duration_ms"])
        dur = record["duration_ms"]
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            self.counters["finished"] += 1
            retained = False
            if record.get("error"):
                self._errors.append(record)
                retained = True
            if self.slow_n > 0:
                heapq.heappush(self._slow, (dur, record["seq"], record))
                if len(self._slow) > self.slow_n:
                    evicted = heapq.heappop(self._slow)
                    retained = retained or evicted[1] != record["seq"]
                else:
                    retained = True
            if self._rng.random() < sample_rate():
                self._sampled.append(record)
                retained = True
            self.counters["retained" if retained else "sampled_out"] += 1
        return record

    def clear(self) -> None:
        with self._lock:
            self._sampled.clear()
            self._errors.clear()
            self._slow.clear()
            self.counters = {k: 0 for k in self.counters}

    # -- export ------------------------------------------------------------

    def traces(self) -> list[dict]:
        """Union of all three retention lanes, deduped, oldest first."""
        with self._lock:
            by_seq: dict[int, dict] = {}
            for rec in self._sampled:
                by_seq[rec["seq"]] = rec
            for rec in self._errors:
                by_seq[rec["seq"]] = rec
            for _, seq, rec in self._slow:
                by_seq[seq] = rec
        return [by_seq[k] for k in sorted(by_seq)]

    def slowest(self) -> dict | None:
        with self._lock:
            if not self._slow:
                return None
            return max(self._slow)[2]

    def export(self) -> dict:
        return {
            "enabled": enabled(),
            "sample_rate": sample_rate(),
            "counters": dict(self.counters),
            "traces": self.traces(),
        }

    def perfetto(self, records: list[dict] | None = None) -> dict:
        """Chrome trace-event JSON for the retained traces — loadable in
        Perfetto / chrome://tracing next to a ``jax.profiler`` dump."""
        if records is None:
            records = self.traces()
        return perfetto_export(records)


def perfetto_export(records: list[dict]) -> dict:
    """Render trace records as Chrome trace-event format: one complete
    ("X") event per span on the tid of its *begin* thread (the end thread
    rides in ``args`` — a queue-style span legitimately ends elsewhere),
    plus one envelope event per request and thread-name metadata."""
    events: list[dict] = []
    tids: dict[str, int] = {}

    def tid_for(thread: str) -> int:
        if thread not in tids:
            tids[thread] = len(tids) + 1
        return tids[thread]

    for rec in records:
        base_us = rec["start_unix_ms"] * 1e3
        args = {"trace_id": rec["trace_id"]}
        if rec.get("error"):
            args["error"] = rec["error"]
        req_thread = (
            rec["spans"][0]["begin_thread"] if rec.get("spans") else "request"
        )
        events.append({
            "name": f"request:{rec['task']}",
            "cat": rec["task"],
            "ph": "X",
            "ts": base_us,
            "dur": rec["duration_ms"] * 1e3,
            "pid": 1,
            "tid": tid_for(req_thread),
            "args": args,
        })
        for s in rec["spans"]:
            sargs: dict[str, Any] = {
                "trace_id": rec["trace_id"],
                "end_thread": s["end_thread"],
            }
            if s.get("meta"):
                sargs.update({str(k): str(v) for k, v in s["meta"].items()})
            events.append({
                "name": s["name"],
                "cat": rec["task"],
                "ph": "X",
                "ts": base_us + s["start_ms"] * 1e3,
                "dur": s["dur_ms"] * 1e3,
                "pid": 1,
                "tid": tid_for(s["begin_thread"]),
                "args": sargs,
            })
    for thread, tid in tids.items():
        events.append({
            "name": "thread_name",
            "ph": "M",
            "pid": 1,
            "tid": tid,
            "args": {"name": thread},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


_recorder: TraceRecorder | None = None
_recorder_lock = threading.Lock()


def get_recorder() -> TraceRecorder:
    """The process-wide recorder (lazily built from the env)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = TraceRecorder()
    return _recorder


def reset_recorder() -> None:
    """Drop the shared recorder (tests); the next :func:`get_recorder`
    rebuilds it from the current env."""
    global _recorder
    with _recorder_lock:
        _recorder = None


# -- request-level helpers (the serving layer's whole API) -------------------


def begin_request(
    task: str, trace_id: str | None = None, t0: float | None = None
) -> Trace | None:
    """Start a trace for one request, or None when tracing is off — the
    ONE per-request check on the disabled path. ``t0`` back-dates the
    trace start (e.g. to the first request chunk's arrival)."""
    if not enabled():
        return None
    return Trace(task, trace_id=trace_id, t0=t0)


def finish_request(trace: Trace | None, error: str | None = None) -> None:
    """Close a request trace into the recorder; no-op for None."""
    if trace is not None:
        get_recorder().finish(trace, error=error)
