"""``lumen-tpu-resources`` CLI.

Subcommands mirror the reference's ``lumen-resources`` CLI
(``lumen_resources/cli.py:314-398``): ``download``, ``validate``,
``validate-model-info``, ``list``.
"""

from __future__ import annotations

import argparse
import json
import sys

from .config import config_json_schema, load_config
from .downloader import Downloader
from .exceptions import ResourceError
from .model_info import load_model_info


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="lumen-tpu-resources")
    sub = p.add_subparsers(dest="cmd", required=True)

    dl = sub.add_parser("download", help="download all models for a config")
    dl.add_argument("--config", required=True)

    val = sub.add_parser("validate", help="validate a lumen config file")
    val.add_argument("--config", required=True)
    val.add_argument(
        "--loose",
        action="store_true",
        help="warn on unknown fields instead of failing (dev configs)",
    )

    vmi = sub.add_parser("validate-model-info", help="validate a model directory's model_info.json")
    vmi.add_argument("model_dir")

    ls = sub.add_parser("list", help="list models referenced by a config")
    ls.add_argument("--config", required=True)

    sub.add_parser("schema", help="print the config JSON schema")

    args = p.parse_args(argv)
    try:
        return _run(args)
    except ResourceError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def _run(args: argparse.Namespace) -> int:
    if args.cmd == "validate":
        if args.loose:
            from .config import load_config_loose

            cfg, warnings = load_config_loose(args.config)
            for w in warnings:
                print(f"warning: {w}", file=sys.stderr)
        else:
            cfg = load_config(args.config)
        print(f"OK: {len(cfg.services)} services, mode={cfg.deployment.mode}")
        return 0
    if args.cmd == "validate-model-info":
        info = load_model_info(args.model_dir)
        print(f"OK: {info.name} v{info.version} ({info.model_type}), runtimes={sorted(info.runtimes)}")
        return 0
    if args.cmd == "list":
        cfg = load_config(args.config)
        for svc_name, svc in cfg.services.items():
            for alias, m in svc.models.items():
                print(f"{svc_name}/{alias}: {m.model} runtime={m.runtime} dataset={m.dataset or '-'}")
        return 0
    if args.cmd == "download":
        cfg = load_config(args.config)
        report = Downloader(cfg).download_all()
        for r in report.results:
            status = "ok" if r.ok else f"FAILED: {r.error}"
            print(f"{r.service}/{r.alias} ({r.model}): {status}")
        return 0 if report.ok else 1
    if args.cmd == "schema":
        print(json.dumps(config_json_schema(), indent=2))
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
