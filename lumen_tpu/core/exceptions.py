"""Exception hierarchy for the core (resources/config) layer.

Mirrors the error taxonomy of the reference's
``lumen_resources/exceptions.py`` so that callers can make the same
distinctions (config vs download vs platform vs validation failures).
"""

from __future__ import annotations


class ResourceError(Exception):
    """Base class for all resource-layer failures."""

    def __init__(self, message: str, *, detail: str | None = None):
        super().__init__(message)
        self.message = message
        self.detail = detail

    def __str__(self) -> str:  # pragma: no cover - trivial
        if self.detail:
            return f"{self.message} ({self.detail})"
        return self.message


class ConfigError(ResourceError):
    """Invalid or unloadable lumen configuration."""


class ModelInfoError(ResourceError):
    """Invalid model_info.json manifest."""


class DownloadError(ResourceError):
    """Model artifact download or integrity-validation failure."""

    def __init__(self, message: str, *, repo_id: str | None = None, detail: str | None = None):
        super().__init__(message, detail=detail)
        self.repo_id = repo_id


class PlatformUnavailableError(ResourceError):
    """Neither HuggingFace Hub nor ModelScope SDK is importable/reachable."""


class ValidationError(ResourceError):
    """Schema validation failure (config or result payload)."""
