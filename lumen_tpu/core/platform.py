"""Unified model-hub adapter (HuggingFace Hub / ModelScope).

Role of the reference's ``lumen_resources/platform.py:30-270``: hide which
hub a model repo comes from behind one ``snapshot_download``-shaped call,
with region-based routing (``cn`` -> ModelScope, ``other`` -> HF Hub with
ModelScope fallback) and pattern-filtered downloads.

Both SDK imports are lazy and optional: on an air-gapped TPU VM the adapter
still resolves repos that already exist in the local cache directory.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import shutil

from .exceptions import DownloadError, PlatformUnavailableError

logger = logging.getLogger(__name__)

#: model-repo owner organisations, in lookup order
OWNER_ORGS = ("LumilioPhotos", "Lumilio-Photos")


def _have(mod: str) -> bool:
    try:
        return importlib.util.find_spec(mod) is not None
    except (ImportError, ValueError):
        return False


class Platform:
    """Resolve + download model repos from the configured hub."""

    def __init__(self, region: str, cache_dir: str):
        self.region = region
        self.cache_dir = os.path.expanduser(cache_dir)
        self.models_dir = os.path.join(self.cache_dir, "models")
        os.makedirs(self.models_dir, exist_ok=True)

    # -- resolution -------------------------------------------------------

    def local_dir(self, repo_name: str) -> str:
        """On-disk directory for a repo (flat ``<cache>/models/<name>``)."""
        return os.path.join(self.models_dir, repo_name.split("/")[-1])

    def is_cached(self, repo_name: str) -> bool:
        d = self.local_dir(repo_name)
        return os.path.isdir(d) and bool(os.listdir(d))

    def preferred_backends(self) -> list[str]:
        """Hub SDKs to try, in order, for this region."""
        if self.region == "cn":
            order = ["modelscope", "huggingface_hub"]
        else:
            order = ["huggingface_hub", "modelscope"]
        return [b for b in order if _have(b)]

    # -- download ---------------------------------------------------------

    def download(
        self,
        repo_name: str,
        allow_patterns: list[str] | None = None,
        force: bool = False,
        update: bool = False,
    ) -> str:
        """Fetch (a filtered snapshot of) a repo into the local cache.

        Tries each owner org on each available hub SDK; returns the local
        directory. If no SDK is importable but the repo is already cached,
        the cached copy is used (air-gapped operation).

        ``update=True`` fetches into an existing cached directory without
        wiping it (used for phase-two dataset files that the initial
        pattern-filtered snapshot did not cover); ``force=True`` wipes and
        re-downloads.
        """
        target = self.local_dir(repo_name)
        if self.is_cached(repo_name) and not force and not update:
            return target
        backends = self.preferred_backends()
        if not backends:
            if self.is_cached(repo_name):
                return target
            raise PlatformUnavailableError(
                "no hub SDK available (huggingface_hub / modelscope) and "
                f"model {repo_name!r} is not in the local cache {self.models_dir}"
            )
        if force and os.path.isdir(target):
            shutil.rmtree(target)

        errors: list[str] = []
        candidates = [repo_name] if "/" in repo_name else [
            f"{org}/{repo_name}" for org in OWNER_ORGS
        ]
        for backend in backends:
            for repo_id in candidates:
                try:
                    return self._snapshot(backend, repo_id, target, allow_patterns)
                except Exception as e:  # noqa: BLE001 - collected and re-raised
                    errors.append(f"{backend}:{repo_id}: {e}")
        raise DownloadError(
            f"failed to download {repo_name!r} from any hub",
            repo_id=repo_name,
            detail="; ".join(errors),
        )

    def _snapshot(
        self,
        backend: str,
        repo_id: str,
        target: str,
        allow_patterns: list[str] | None,
    ) -> str:
        logger.info("downloading %s via %s -> %s", repo_id, backend, target)
        if backend == "huggingface_hub":
            from huggingface_hub import snapshot_download

            snapshot_download(
                repo_id=repo_id,
                local_dir=target,
                allow_patterns=allow_patterns,
            )
        elif backend == "modelscope":
            from modelscope import snapshot_download  # type: ignore

            snapshot_download(
                repo_id,
                local_dir=target,
                allow_patterns=allow_patterns,
            )
        else:  # pragma: no cover
            raise ValueError(f"unknown hub backend {backend!r}")
        return target
