"""Core layer: configuration, model manifests, result schemas, resources.

TPU-native counterpart of the reference's ``lumen-resources`` package.
"""

from .config import LumenConfig, ServiceConfig, ModelConfig, load_config
from .exceptions import (
    ConfigError,
    DownloadError,
    ModelInfoError,
    PlatformUnavailableError,
    ResourceError,
    ValidationError,
)
from .model_info import ModelInfo, load_model_info
from .result_schemas import (
    EmbeddingV1,
    FaceV1,
    LabelsV1,
    OCRV1,
    TextGenerationV1,
    schema_for,
    validate_result,
)

__all__ = [
    "LumenConfig",
    "ServiceConfig",
    "ModelConfig",
    "load_config",
    "ModelInfo",
    "load_model_info",
    "ResourceError",
    "ConfigError",
    "DownloadError",
    "ModelInfoError",
    "PlatformUnavailableError",
    "ValidationError",
    "EmbeddingV1",
    "FaceV1",
    "OCRV1",
    "LabelsV1",
    "TextGenerationV1",
    "schema_for",
    "validate_result",
]
