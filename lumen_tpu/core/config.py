"""Unified configuration schema + loader for all lumen-tpu services.

YAML surface is compatible with the reference's config schema
(``packages/lumen-resources/src/lumen_resources/lumen_config.py:13-257``):
``metadata / deployment / server / services.<name>.{enabled, package,
import_info, backend_settings, models}``. Existing Lumen config files load
unchanged. Differences, all additive:

- ``runtime`` gains the value ``"jax"`` (the native runtime here). ``torch``
  and ``onnx`` remain accepted: their checkpoints are converted to jnp
  pytrees at load time. ``rknn`` is accepted but unsupported at run time.
- ``backend_settings`` gains TPU fields (``dtype``, ``mesh``,
  ``max_batch_latency_ms``, ``batch_buckets``) next to the reference's
  ``device`` / ``batch_size`` / ``onnx_providers`` (the last is accepted and
  ignored, for config-file compatibility).
"""

from __future__ import annotations

import os
import re
from typing import Any, Literal

import yaml
from pydantic import BaseModel, ConfigDict, Field, field_validator, model_validator

from .exceptions import ConfigError

_SERVICE_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")


class Metadata(BaseModel):
    model_config = ConfigDict(extra="forbid")

    version: str = Field(pattern=r"^\d+\.\d+\.\d+$")
    region: Literal["cn", "other"]
    cache_dir: str

    @property
    def cache_path(self) -> str:
        return os.path.expanduser(self.cache_dir)


class Deployment(BaseModel):
    """Single service or multi-service hub.

    The reference models this as two discriminated pydantic classes
    (``Deployment``/``Deployment1``); a single class with a cross-field
    validator expresses the same contract.
    """

    model_config = ConfigDict(extra="forbid")

    mode: Literal["single", "hub"]
    service: str | None = Field(None, pattern=_SERVICE_NAME_RE.pattern)
    services: list[str] | None = None

    @model_validator(mode="after")
    def _check_mode_fields(self) -> "Deployment":
        if self.mode == "single" and not self.service:
            raise ValueError("deployment.service is required when mode=single")
        if self.mode == "hub" and not self.services:
            raise ValueError("deployment.services is required when mode=hub")
        if self.services:
            for s in self.services:
                if not _SERVICE_NAME_RE.match(s):
                    raise ValueError(f"invalid service name: {s!r}")
        return self


class Mdns(BaseModel):
    model_config = ConfigDict(extra="forbid")

    enabled: bool = False
    # Optional: the server falls back to "lumen-tpu" when unset (the
    # reference accepts enabled=true with no name, so we must too).
    service_name: str | None = Field(None, pattern=r"^[a-z][a-z0-9-]*$")


class Server(BaseModel):
    model_config = ConfigDict(extra="forbid")

    port: int = Field(ge=1024, le=65535)
    host: str = "0.0.0.0"
    mdns: Mdns | None = None


class ImportInfo(BaseModel):
    """Dotted paths used by the hub to dynamically load a service.

    Same role as the reference's ``ImportInfo``
    (``lumen_config.py:130-155``); patterns relaxed only enough to accept
    both ``lumen_clip.*`` (reference packages) and ``lumen_tpu.*`` paths.
    """

    model_config = ConfigDict(extra="forbid")

    registry_class: str = Field(pattern=r"^[a-z_][a-zA-Z0-9_.]*\.[A-Z][a-zA-Z0-9]*$")
    add_to_server: str = Field(
        default="lumen_tpu.serving.proto.ml_service_pb2_grpc.add_InferenceServicer_to_server",
        pattern=r"^[a-z_][a-zA-Z0-9_.]*\.add_[A-Za-z0-9_]+_to_server$",
    )


Runtime = Literal["jax", "torch", "onnx", "rknn"]


class ModelConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    model: str
    runtime: Runtime = "jax"
    rknn_device: str | None = Field(None, pattern=r"^rk\d+$")
    dataset: str | None = None
    precision: str | None = None

    @model_validator(mode="after")
    def _rknn_device_required(self) -> "ModelConfig":
        if self.runtime == "rknn" and not self.rknn_device:
            raise ValueError("rknn_device is required when runtime=rknn")
        return self


class MeshConfig(BaseModel):
    """Logical device-mesh request for a service.

    ``axes`` maps axis name -> size; ``-1`` means "all remaining devices".
    Axis names follow the framework-wide convention in
    ``lumen_tpu.parallel.sharding``: ``data``/``model``/``seq``.
    """

    model_config = ConfigDict(extra="forbid")

    axes: dict[str, int] = Field(default_factory=lambda: {"data": -1})

    @field_validator("axes")
    @classmethod
    def _nonempty(cls, v: dict[str, int]) -> dict[str, int]:
        if not v:
            raise ValueError("mesh.axes must be non-empty")
        if sum(1 for s in v.values() if s == -1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        for name, size in v.items():
            if size == 0 or size < -1:
                raise ValueError(f"invalid mesh axis size {name}={size}")
        return v


class BackendSettings(BaseModel):
    model_config = ConfigDict(extra="forbid")

    device: str | None = None
    batch_size: int = Field(8, ge=1)
    # Accepted for reference-config compatibility; ignored by the jax runtime.
    onnx_providers: list[Any] | None = None

    # --- TPU-native knobs ---
    dtype: Literal["bfloat16", "float32", "float16"] = "bfloat16"
    mesh: MeshConfig | None = None
    max_batch_latency_ms: float = Field(5.0, ge=0)
    # Static-shape bucket ladder. The unit is family-specific: request
    # batch sizes for CLIP/face, detection side-lengths (px) for OCR,
    # prompt lengths (tokens) for the VLM — each service's from_config
    # documents its interpretation.
    batch_buckets: list[int] | None = None
    # Compile every batch bucket at startup instead of on first request.
    warmup: bool = False
    # VLM decode scheduling: "continuous" (the default) runs the paged-KV
    # continuous-batching engine — requests admit/retire at step
    # granularity into a shared page pool, no queueing behind long
    # generations; "coalesce" groups same-shape concurrent requests into
    # one fused-loop program (lowest dispatch overhead, best for
    # same-shaped bursts). LUMEN_VLM_SCHEDULER overrides either at boot.
    # Other services ignore this.
    scheduler: Literal["coalesce", "continuous"] = "continuous"
    # Continuous scheduler only: decode steps per compiled block (one host
    # dispatch per block; larger amortizes dispatch, smaller admits and
    # retires rows sooner). Ignored by "coalesce".
    decode_block: int = Field(8, ge=1)
    # VLM only: weight-only int8 for the decoder's attention + MLP
    # projections (per-channel scales). Halves the dominant HBM traffic of
    # bandwidth-bound decode; embeddings/norms/MoE banks stay full
    # precision. Other services ignore this.
    quantize: Literal["int8"] | None = None


class ServiceConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    enabled: bool
    package: str = Field(pattern=r"^[a-z][a-z0-9_.]*$")
    import_info: ImportInfo
    backend_settings: BackendSettings = Field(default_factory=BackendSettings)
    models: dict[str, ModelConfig]

    @field_validator("models")
    @classmethod
    def _nonempty_models(cls, v: dict[str, ModelConfig]) -> dict[str, ModelConfig]:
        if not v:
            raise ValueError("services.*.models must contain at least one model")
        return v


class LumenConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    metadata: Metadata
    deployment: Deployment
    server: Server
    services: dict[str, ServiceConfig]

    @model_validator(mode="after")
    def _deployment_refs_exist(self) -> "LumenConfig":
        names = set(self.services)
        wanted: list[str] = []
        if self.deployment.mode == "single" and self.deployment.service:
            wanted = [self.deployment.service]
        elif self.deployment.services:
            wanted = list(self.deployment.services)
        missing = [w for w in wanted if w not in names]
        if missing:
            raise ValueError(f"deployment references undefined services: {missing}")
        return self

    def enabled_services(self) -> dict[str, ServiceConfig]:
        """Services selected by the deployment block AND marked enabled."""
        if self.deployment.mode == "single":
            sel = [self.deployment.service]
        else:
            sel = list(self.deployment.services or [])
        return {n: self.services[n] for n in sel if self.services[n].enabled}


def _load_raw(path: str) -> dict[str, Any]:
    try:
        with open(os.path.expanduser(path), "r", encoding="utf-8") as f:
            raw = yaml.safe_load(f)
    except FileNotFoundError as e:
        raise ConfigError(f"config file not found: {path}") from e
    except yaml.YAMLError as e:
        raise ConfigError(f"config file is not valid YAML: {path}", detail=str(e)) from e
    if not isinstance(raw, dict):
        raise ConfigError(f"config root must be a mapping, got {type(raw).__name__}")
    return raw


def load_config(path: str) -> LumenConfig:
    """Load + strictly validate a YAML config file.

    Production entry point, same role as the reference's
    ``load_and_validate_config()``
    (``lumen_resources/lumen_config_validator.py:244-270``).
    """
    return validate_config_dict(_load_raw(path))


def load_config_loose(path: str) -> tuple[LumenConfig, list[str]]:
    """File-path variant of :func:`validate_config_loose` with the same
    error wrapping as :func:`load_config` (missing files and bad YAML are
    ``ConfigError``, not raw tracebacks)."""
    return validate_config_loose(_load_raw(path))


def validate_config_dict(raw: dict[str, Any]) -> LumenConfig:
    try:
        return LumenConfig.model_validate(raw)
    except Exception as e:  # pydantic.ValidationError
        raise ConfigError("config validation failed", detail=str(e)) from e


def validate_config_loose(raw: dict[str, Any]) -> tuple[LumenConfig, list[str]]:
    """Lenient validation: unknown fields are dropped with a warning
    instead of failing, everything else still validates strictly.

    Reference analog: the Draft7 jsonschema "flexible" mode next to strict
    pydantic (``lumen_resources/lumen_config_validator.py:19-270``), used
    for development configs and forward-compat fields. Returns the
    validated config plus the list of ignored-field warnings.
    """
    import copy

    raw = copy.deepcopy(raw)
    warnings: list[str] = []
    # Each pass strips every unknown-field error pydantic reports; nested
    # models can reveal further extras once parents parse, so iterate (the
    # bound is paranoid — one level of reveal per pass).
    for _ in range(20):
        try:
            return LumenConfig.model_validate(raw), warnings
        except Exception as e:
            errors = getattr(e, "errors", None)
            extras = [
                err for err in (errors() if callable(errors) else [])
                if err.get("type") == "extra_forbidden"
            ]
            if not extras:
                raise ConfigError("config validation failed", detail=str(e)) from e
            for err in extras:
                loc = err["loc"]
                node: Any = raw
                try:
                    for key in loc[:-1]:
                        node = node[key]
                    node.pop(loc[-1], None)
                except (KeyError, IndexError, TypeError):
                    raise ConfigError(
                        "config validation failed", detail=str(e)
                    ) from e
                warnings.append(
                    "ignored unknown field " + ".".join(str(k) for k in loc)
                )
    raise ConfigError("config validation failed", detail="loose-mode did not converge")


def config_json_schema() -> dict[str, Any]:
    """JSON Schema derived from the pydantic models.

    The reference maintains a hand-written ``config-schema.yaml`` validated
    with jsonschema Draft7 alongside the pydantic models; generating the
    schema from the single source of truth removes that duplication.
    """
    return LumenConfig.model_json_schema()
