"""Config-driven model-artifact downloader with integrity validation.

Covers the responsibilities of the reference's
``lumen_resources/downloader.py:61-513``:

- iterate every enabled service x model in a :class:`LumenConfig`,
- build runtime/precision-aware ``allow_patterns`` so only the needed
  artifacts are fetched,
- fetch declared zero-shot dataset files (labels JSON + ``.npy`` label
  embeddings) in a second phase,
- validate the downloaded tree against the repo's ``model_info.json``
  (including rknn-style per-device file dicts),
- roll the model directory back on failure so a later retry starts clean.
"""

from __future__ import annotations

import fnmatch
import logging
import re
import os
import shutil
from dataclasses import dataclass, field

from ..utils.retry import RetryPolicy, policy_from_env, retry_call
from .config import LumenConfig, ModelConfig
from .exceptions import DownloadError, ResourceError
from .model_info import ModelInfo, load_model_info
from .platform import Platform

logger = logging.getLogger(__name__)

#: Transient fetch failures worth a capped backoff-retry: hub/network
#: errors surface as DownloadError or OS-level errno; config/manifest
#: problems (ConfigError, ModelInfoError) do not get better by waiting.
#: FaultInjected (a plain ResourceError) is included so the test harness
#: exercises the same retry path real flakiness takes.
def _retryable_fetch(exc: BaseException) -> bool:
    from ..testing.faults import FaultInjected

    return isinstance(exc, (DownloadError, FaultInjected, OSError, ConnectionError, TimeoutError))


def download_retry_policy() -> RetryPolicy:
    """``LUMEN_DOWNLOAD_RETRIES`` / ``_BACKOFF_S`` / ``_BACKOFF_MAX_S``."""
    return policy_from_env(
        "DOWNLOAD", RetryPolicy(attempts=3, base_delay_s=0.5, max_delay_s=10.0)
    )

# Patterns always fetched: manifest, tokenizer + model configs.
_COMMON_PATTERNS = [
    "model_info.json",
    "*config*.json",
    "tokenizer*",
    "*.txt",
    "*.yaml",
]


@dataclass
class DownloadResult:
    service: str
    alias: str
    model: str
    ok: bool
    path: str | None = None
    error: str | None = None


@dataclass
class DownloadReport:
    results: list[DownloadResult] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def failures(self) -> list[DownloadResult]:
        return [r for r in self.results if not r.ok]


def allow_patterns_for(model_cfg: ModelConfig) -> list[str]:
    """Runtime/precision-aware filter for a snapshot download.

    Mirrors the selection semantics of the reference
    (``downloader.py:179-251``): onnx fetches ``*.{precision}.onnx`` (or all
    ``*.onnx`` when unspecified), torch fetches safetensors/bin checkpoints,
    rknn fetches the per-device subtree. The native ``jax`` runtime fetches
    safetensors (+ orbax checkpoint dirs).
    """
    patterns = list(_COMMON_PATTERNS)
    rt = model_cfg.runtime
    if rt == "jax":
        patterns += ["*.safetensors", "*.safetensors.index.json", "orbax/*", "jax/*", "*.bin", "*.pt"]
    elif rt == "torch":
        patterns += ["*.safetensors", "*.bin", "*.pt"]
    elif rt == "onnx":
        if model_cfg.precision:
            patterns += [f"onnx/*.{model_cfg.precision}.onnx", f"*.{model_cfg.precision}.onnx"]
        patterns += ["onnx/*.onnx", "*.onnx"] if not model_cfg.precision else []
    elif rt == "rknn":
        patterns += [f"rknn/{model_cfg.rknn_device}/*"]
    return patterns


_PRECISION_VARIANT = re.compile(r"\.(fp16|fp32|bf16|int8|uint8|q4|q4fp16|q4f16)\.(onnx|rknn|safetensors)$")


def _filter_by_precision(declared: list[str], precision: str | None) -> list[str]:
    """Keep only the declared files relevant to the configured precision.

    Multi-precision manifests declare sibling variants like
    ``onnx/text.fp32.onnx`` + ``onnx/text.fp16.onnx``; only the configured
    precision's variants are fetched, so only those may be required
    (reference behavior: ``downloader.py:484-493``). Files with no
    precision marker are always required. If no variant matches the
    configured precision, fall back to requiring the fp32 variants
    (mirroring the fp32-fallback preference chain).
    """
    if not precision:
        return declared
    plain = [f for f in declared if not _PRECISION_VARIANT.search(f)]
    variants = [f for f in declared if _PRECISION_VARIANT.search(f)]
    matching = [f for f in variants if _PRECISION_VARIANT.search(f).group(1) == precision]
    if not matching:
        matching = [f for f in variants if _PRECISION_VARIANT.search(f).group(1) == "fp32"]
    return plain + matching


class Downloader:
    def __init__(self, config: LumenConfig):
        self.config = config
        self.platform = Platform(config.metadata.region, config.metadata.cache_dir)

    # -- public API -------------------------------------------------------

    def download_all(self) -> DownloadReport:
        """Download every model of every enabled service; never raises —
        failures are reported per model (callers decide whether to abort,
        as the reference hub does at ``src/lumen/server.py:168-175``)."""
        report = DownloadReport()
        for svc_name in self.config.enabled_services():
            report.results.extend(self.download_service(svc_name).results)
        return report

    def download_service(self, svc_name: str) -> DownloadReport:
        """Per-service variant of :meth:`download_all` (the degraded-service
        recovery path re-fetches only the broken service's models)."""
        report = DownloadReport()
        svc = self.config.enabled_services().get(svc_name)
        if svc is None:
            report.results.append(
                DownloadResult(
                    service=svc_name, alias="", model="", ok=False,
                    error=f"service {svc_name!r} is not enabled by the deployment config",
                )
            )
            return report
        for alias, model_cfg in svc.models.items():
            report.results.append(self._download_one(svc_name, alias, model_cfg))
        return report

    def check_all(self) -> DownloadReport:
        """Offline presence/integrity check: is every enabled model
        already in the cache with its declared files (and dataset labels)?
        Never downloads and never raises — per-model failures are reported
        so the session-resume flow (``/api/v1/session/status``, the
        reference SessionHub's ``checkInstallationPath`` recommendation)
        can decide start-existing vs run-installer."""
        report = DownloadReport()
        for svc_name, svc in self.config.enabled_services().items():
            for alias, model_cfg in svc.models.items():
                res = DownloadResult(
                    service=svc_name, alias=alias, model=model_cfg.model, ok=False
                )
                try:
                    if not self.platform.is_cached(model_cfg.model):
                        raise DownloadError(
                            f"model {model_cfg.model!r} is not in the cache",
                            repo_id=model_cfg.model,
                        )
                    path = self.platform.local_dir(model_cfg.model)
                    info = load_model_info(path)
                    self.validate_files(path, info, model_cfg)
                    res.path, res.ok = path, True
                except (ResourceError, OSError) as e:
                    # OSError too (permission-denied listdir/stat): the
                    # "never raises" contract holds for unreadable caches.
                    res.error = str(e)
                report.results.append(res)
        return report

    # -- internals --------------------------------------------------------

    def _download_one(self, svc: str, alias: str, model_cfg: ModelConfig) -> DownloadResult:
        res = DownloadResult(service=svc, alias=alias, model=model_cfg.model, ok=False)
        # Remember whether this model pre-existed: rollback must never
        # destroy a cached copy we did not just (re)download.
        was_cached = self.platform.is_cached(model_cfg.model)
        try:
            res.path = self._fetch_and_validate(model_cfg)
            res.ok = True
        except ResourceError as e:
            if was_cached:
                # A cached-but-invalid tree (interrupted earlier download,
                # changed runtime/precision in config): try to repair it
                # with an incremental update fetch rather than failing on
                # the cache-hit fast path forever.
                logger.warning("cached copy of %s invalid (%s); attempting repair", model_cfg.model, e)
                try:
                    res.path = self._fetch_and_validate(model_cfg, update=True)
                    res.ok = True
                    return res
                except ResourceError as e2:
                    e = e2
            logger.error("download failed for %s/%s: %s", svc, alias, e)
            if not was_cached:
                self.cleanup_model(model_cfg.model)
            res.error = str(e)
        return res

    def _fetch(self, model_cfg: ModelConfig, patterns: list[str], update: bool) -> str:
        """One snapshot fetch, with the ``download`` fault point inside the
        retried unit (so an injected fault is retried exactly like a real
        transient failure) and capped exponential-backoff retries."""
        from ..testing.faults import faults

        def attempt() -> str:
            faults.check("download", model_cfg.model)
            return self.platform.download(model_cfg.model, allow_patterns=patterns, update=update)

        return retry_call(
            attempt,
            policy=download_retry_policy(),
            retryable=_retryable_fetch,
            scope="download",
        )

    def _fetch_and_validate(self, model_cfg: ModelConfig, update: bool = False) -> str:
        path = self._fetch(model_cfg, allow_patterns_for(model_cfg), update)
        info = load_model_info(path)
        self._download_datasets(path, info, model_cfg)
        self.validate_files(path, info, model_cfg)
        return path

    def _download_datasets(self, path: str, info: ModelInfo, model_cfg: ModelConfig) -> None:
        """Phase two: fetch dataset files named in model_info (relative
        paths), only for the dataset the config selects."""
        if not model_cfg.dataset or not info.datasets:
            return
        ds = info.datasets.get(model_cfg.dataset)
        if ds is None:
            raise DownloadError(
                f"dataset {model_cfg.dataset!r} not declared by model {info.name!r}",
                repo_id=model_cfg.model,
            )
        missing = [p for p in (ds.labels, ds.embeddings) if not os.path.exists(os.path.join(path, p))]
        if missing:
            # update=True: the model dir already exists from phase one, so a
            # plain download() would be a cache-hit no-op.
            self._fetch(model_cfg, missing, update=True)

    def _resolve_runtime_entry(self, info: ModelInfo, model_cfg: ModelConfig):
        """Runtime entry to validate against; ``jax`` falls back to the
        ``torch`` entry (safetensors/bin checkpoints get converted to jnp
        pytrees at load time)."""
        entry = info.runtimes.get(model_cfg.runtime)
        if entry is not None and entry.available:
            return entry
        if model_cfg.runtime == "jax":
            torch_entry = info.runtimes.get("torch")
            if torch_entry is not None and torch_entry.available:
                return torch_entry
        raise DownloadError(
            f"runtime {model_cfg.runtime!r} not available in model_info for {info.name!r}",
            repo_id=model_cfg.model,
        )

    def validate_files(self, path: str, info: ModelInfo, model_cfg: ModelConfig) -> None:
        """Post-download integrity check against model_info's declared file
        list for the configured runtime (reference: ``downloader.py:449-513``)."""
        entry = self._resolve_runtime_entry(info, model_cfg)
        device = model_cfg.rknn_device
        declared = entry.files_for(device) if entry.files else []
        declared = _filter_by_precision(declared, model_cfg.precision)
        missing: list[str] = []
        for rel in declared:
            # Manifests may template the precision into a filename; plain
            # replace (not str.format) so literal braces never crash.
            rel_resolved = rel.replace("{precision}", model_cfg.precision or "fp32")
            if "*" in rel_resolved:
                hits = [
                    os.path.join(dp, f)
                    for dp, _, fs in os.walk(path)
                    for f in fs
                    if fnmatch.fnmatch(os.path.relpath(os.path.join(dp, f), path), rel_resolved)
                ]
                if not hits:
                    missing.append(rel_resolved)
            elif not os.path.exists(os.path.join(path, rel_resolved)):
                missing.append(rel_resolved)
        if missing:
            raise DownloadError(
                f"model {info.name!r} is missing declared files: {missing}",
                repo_id=model_cfg.model,
            )
        if model_cfg.dataset and info.datasets:
            ds = info.datasets.get(model_cfg.dataset)
            if ds:
                # Labels are required; precomputed embeddings are optional —
                # the CLIP manager computes them from labels at startup when
                # the .npy is absent (reference: clip_model.py:145-172).
                if not os.path.exists(os.path.join(path, ds.labels)):
                    raise DownloadError(
                        f"dataset labels missing after download: {ds.labels}",
                        repo_id=model_cfg.model,
                    )
                if not os.path.exists(os.path.join(path, ds.embeddings)):
                    logger.warning(
                        "dataset %r has no precomputed embeddings (%s); they "
                        "will be computed at startup",
                        model_cfg.dataset,
                        ds.embeddings,
                    )

    def cleanup_model(self, repo_name: str) -> None:
        """Rollback: remove a partially-downloaded model directory."""
        d = self.platform.local_dir(repo_name)
        if os.path.isdir(d):
            logger.warning("cleaning up partial download at %s", d)
            shutil.rmtree(d, ignore_errors=True)
