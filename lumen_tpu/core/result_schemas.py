"""Versioned result-payload contracts.

Every gRPC ``InferResponse.result`` is JSON whose shape is pinned by a named,
versioned schema advertised in ``result_mime`` as
``application/json;schema=<name>`` — same contract as the reference's
``lumen_resources/result_schemas/`` package (embedding_v1, face_v1, labels_v1,
ocr_v1, text_generation_v1). ``extra='forbid'`` keeps producers honest.
"""

from __future__ import annotations

from typing import ClassVar, Literal

from pydantic import BaseModel, ConfigDict, Field, field_validator

from .exceptions import ValidationError

JSON_MIME = "application/json"


class _Schema(BaseModel):
    model_config = ConfigDict(extra="forbid")

    #: schema name used in result_mime; overridden per subclass
    SCHEMA_NAME: ClassVar[str] = ""

    @classmethod
    def mime(cls) -> str:
        return f"{JSON_MIME};schema={cls.SCHEMA_NAME}"

    def to_json_bytes(self) -> bytes:
        return self.model_dump_json().encode("utf-8")


class EmbeddingV1(_Schema):
    SCHEMA_NAME: ClassVar[str] = "embedding_v1"

    vector: list[float]
    dim: int = Field(ge=1)
    model_id: str

    @field_validator("vector")
    @classmethod
    def _nonempty(cls, v: list[float]) -> list[float]:
        if not v:
            raise ValueError("vector must be non-empty")
        return v


class FaceItem(BaseModel):
    model_config = ConfigDict(extra="forbid")

    bbox: list[float] = Field(min_length=4, max_length=4)  # x1, y1, x2, y2
    confidence: float = Field(ge=0.0, le=1.0)
    landmarks: list[list[float]] | None = None  # [[x, y] x 5|68]
    embedding: list[float] | None = None


class FaceV1(_Schema):
    SCHEMA_NAME: ClassVar[str] = "face_v1"

    faces: list[FaceItem]
    count: int = Field(ge=0)
    model_id: str


class OcrItem(BaseModel):
    model_config = ConfigDict(extra="forbid")

    box: list[list[float]] = Field(min_length=3)  # polygon, >= 3 points
    text: str
    confidence: float = Field(ge=0.0, le=1.0)


class OCRV1(_Schema):
    SCHEMA_NAME: ClassVar[str] = "ocr_v1"

    items: list[OcrItem]
    count: int = Field(ge=0)
    model_id: str


class LabelItem(BaseModel):
    model_config = ConfigDict(extra="forbid")

    label: str
    score: float


class LabelsV1(_Schema):
    SCHEMA_NAME: ClassVar[str] = "labels_v1"

    labels: list[LabelItem]
    model_id: str


FinishReason = Literal["stop", "length", "eos_token", "stop_sequence", "error"]


class TextGenerationV1(_Schema):
    SCHEMA_NAME: ClassVar[str] = "text_generation_v1"

    text: str
    finish_reason: FinishReason
    generated_tokens: int = Field(ge=0)
    input_tokens: int = Field(ge=0)
    model_id: str
    metadata: dict[str, float | int | str | bool | None] = Field(default_factory=dict)


SCHEMAS: dict[str, type[_Schema]] = {
    "embedding_v1": EmbeddingV1,
    "face_v1": FaceV1,
    "ocr_v1": OCRV1,
    "labels_v1": LabelsV1,
    "text_generation_v1": TextGenerationV1,
}


def schema_for(name: str) -> type[_Schema]:
    try:
        return SCHEMAS[name]
    except KeyError as e:
        raise ValidationError(f"unknown result schema: {name!r}") from e


def validate_result(name: str, payload: bytes) -> _Schema:
    """Parse + validate a JSON result payload against a named schema."""
    import json

    cls = schema_for(name)
    try:
        return cls.model_validate(json.loads(payload.decode("utf-8")))
    except Exception as e:
        raise ValidationError(f"payload does not match schema {name!r}", detail=str(e)) from e
