"""Per-model-repo manifest (``model_info.json``) models.

Same manifest surface as the reference's
``lumen_resources/model_info.py:14-102`` — a model repository carries a
``model_info.json`` describing its source, per-runtime file lists, optional
zero-shot datasets and free-form ``extra_metadata`` (where e.g. the VLM
generation/kv-cache/vision configs and face-pack specs live).

Additive change: ``runtimes`` may declare a ``jax`` entry (safetensors
weights consumed natively); ``torch``/``onnx`` entries remain loadable via
conversion.
"""

from __future__ import annotations

import json
import os
from typing import Any

from pydantic import BaseModel, ConfigDict, Field

from .exceptions import ModelInfoError

MODEL_INFO_FILENAME = "model_info.json"


class ModelSource(BaseModel):
    model_config = ConfigDict(extra="forbid")

    format: str = Field(pattern=r"^(huggingface|openclip|modelscope|custom)$")
    repo_id: str = Field(min_length=1)


class RuntimeRequirements(BaseModel):
    model_config = ConfigDict(extra="allow")

    python: str | None = None
    dependencies: list[str] | None = None


class RuntimeEntry(BaseModel):
    model_config = ConfigDict(extra="forbid")

    available: bool
    # Plain list for most runtimes; dict[device -> files] for rknn-style
    # per-device artifacts (reference: model_info.py:36-44).
    files: list[str] | dict[str, list[str]] | None = None
    devices: list[str] | None = None
    requirements: RuntimeRequirements | None = None

    def files_for(self, device: str | None = None) -> list[str]:
        if self.files is None:
            return []
        if isinstance(self.files, dict):
            if device is None:
                raise ModelInfoError("device required to resolve per-device file dict")
            try:
                return list(self.files[device])
            except KeyError as e:
                raise ModelInfoError(f"no files declared for device {device!r}") from e
        return list(self.files)


class DatasetEntry(BaseModel):
    model_config = ConfigDict(extra="forbid")

    labels: str
    embeddings: str


class ModelInfo(BaseModel):
    model_config = ConfigDict(extra="forbid")

    name: str = Field(min_length=1, max_length=100)
    version: str = Field(pattern=r"^\d+\.\d+\.\d+$")
    description: str = Field(min_length=1, max_length=500)
    model_type: str
    embedding_dim: int | None = Field(None, ge=1, le=100000)
    source: ModelSource
    runtimes: dict[str, RuntimeEntry]
    datasets: dict[str, DatasetEntry] | None = None
    extra_metadata: dict[str, Any] | None = None
    metadata: dict[str, Any] | None = None

    def runtime(self, name: str) -> RuntimeEntry:
        entry = self.runtimes.get(name)
        if entry is None or not entry.available:
            raise ModelInfoError(
                f"runtime {name!r} not available for model {self.name!r} "
                f"(declared: {sorted(self.runtimes)})"
            )
        return entry

    def extra(self, key: str, default: Any = None) -> Any:
        if not self.extra_metadata:
            return default
        return self.extra_metadata.get(key, default)


def dataclass_from_extra(cls, extra: dict | None, defaults: dict | None = None, tuple_keys: tuple[str, ...] = ()):
    """Build an architecture-config dataclass from a manifest ``extra``
    dict: unknown keys dropped, ``defaults`` applied when absent, listed
    keys coerced to tuples (JSON has no tuples). Shared by every model
    family's manager."""
    import dataclasses

    merged = dict(defaults or {})
    merged.update(extra or {})
    valid = {f.name for f in dataclasses.fields(cls)}
    kw = {k: v for k, v in merged.items() if k in valid}
    for key in tuple_keys:
        if key in kw:
            kw[key] = tuple(kw[key])
    return cls(**kw)


def load_model_info(model_dir: str) -> ModelInfo:
    path = os.path.join(model_dir, MODEL_INFO_FILENAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
    except FileNotFoundError as e:
        raise ModelInfoError(f"{MODEL_INFO_FILENAME} not found in {model_dir}") from e
    except json.JSONDecodeError as e:
        raise ModelInfoError(f"invalid JSON in {path}", detail=str(e)) from e
    try:
        return ModelInfo.model_validate(raw)
    except Exception as e:  # pydantic.ValidationError
        raise ModelInfoError(f"invalid model_info in {path}", detail=str(e)) from e
