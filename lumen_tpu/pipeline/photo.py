"""The concrete photo-indexing ingest pipeline: CLIP + face + OCR (+ VLM).

Composes the per-family managers into `IngestPipeline` stages. Each stage's
dense forward (CLIP towers, SCRFD detector, DBNet detector) runs as ONE
data-parallel device call per global batch, sharded over the mesh's ``data``
axis; the irregular tails (face-crop embedding, OCR crop recognition, VLM
captioning) run through the managers' own bucketed batchers.

This is the north-star capability from SURVEY.md §6 (full-library ingest);
the reference has nothing comparable — it processes one payload per gRPC
message (``SURVEY.md`` §2.8 "Batching").
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Iterable, Iterator

import numpy as np

from lumen_tpu.ops.image import (
    decode_image_bytes,
    decode_image_bytes_scaled,
    letterbox_numpy,
)
from lumen_tpu.pipeline.ingest import IngestPipeline, Stage

logger = logging.getLogger(__name__)


@dataclass
class PhotoRecord:
    index: int
    clip_embedding: np.ndarray | None = None
    labels: list[tuple[str, float]] = field(default_factory=list)
    faces: list = field(default_factory=list)  # models.face.FaceDetection
    ocr: list = field(default_factory=list)  # models.ocr.OcrResult
    caption: str | None = None
    error: str | None = None  # decode failure (on_decode_error="record")
    # Content fingerprint of the RAW input bytes (None for non-bytes
    # items): stable across decode policy and model versions — the dedupe
    # primitive, and the natural vector id for the search index.
    sha256: str | None = None


class PhotoIngestPipeline:
    """Bulk photo indexing over a device mesh.

    Pass any subset of initialized managers; stages are built only for the
    families provided. ``items`` fed to :meth:`run` are raw image bytes (or
    anything ``decode_image_bytes`` accepts).
    """

    def __init__(
        self,
        mesh,
        clip=None,
        face=None,
        ocr=None,
        vlm=None,
        batch_size: int = 64,
        classify_top_k: int = 0,
        ocr_det_size: int | None = None,
        ocr_use_angle_cls: bool = False,
        caption: bool = False,
        caption_prompt: str = "Describe this photo in one sentence.",
        caption_max_tokens: int = 32,
        caption_workers: int = 4,
        prefetch: int = 2,
        inflight: int = 2,
        workers: int | None = None,
        on_decode_error: str = "raise",
        decode_max_edge: int | None = None,
        index: Any | None = None,
    ):
        if on_decode_error not in ("raise", "record"):
            raise ValueError("on_decode_error must be 'raise' or 'record'")
        # "record": a corrupt/undecodable image yields a PhotoRecord with
        # .error set instead of aborting the whole bulk run (one bad file
        # must not kill a multi-hour library index).
        self.on_decode_error = on_decode_error
        if clip is None and face is None and ocr is None:
            raise ValueError("need at least one of clip/face/ocr managers")
        if caption and vlm is None:
            raise ValueError("caption=True requires a vlm manager")
        for mgr in (clip, face, ocr, vlm):
            if mgr is not None:
                mgr._ensure_ready()  # stages reach into post-initialize state
        self.clip, self.face, self.ocr, self.vlm = clip, face, ocr, vlm
        self.ocr_det_size = ocr_det_size
        self.ocr_use_angle_cls = ocr_use_angle_cls
        # The per-request and ingest paths must share ONE device copy of
        # each family's weights (a second copy could evict HBM needed for
        # activations), and the managers' micro-batchers keep sharding
        # inputs with their OWN mesh — so the pipeline mesh must cover the
        # identical device set/order, or per-request serving after ingest
        # hits device-assignment mismatches / silent resharding.
        pipeline_devs = tuple(mesh.devices.flat)
        for name, mgr in (("clip", clip), ("face", face), ("ocr", ocr), ("vlm", vlm)):
            if mgr is None:
                continue
            mgr_mesh = getattr(mgr, "mesh", None)
            if mgr_mesh is not None and tuple(mgr_mesh.devices.flat) != pipeline_devs:
                raise ValueError(
                    f"{name} manager mesh devices {tuple(str(d) for d in mgr_mesh.devices.flat)} "
                    f"differ from pipeline mesh devices {tuple(str(d) for d in pipeline_devs)}; "
                    "build the pipeline with the managers' mesh (or managers with the "
                    "pipeline's) so both paths share one device placement"
                )
        # Managers place their params at initialize() (replicated, or
        # TP-sharded when their mesh has a model axis); the device-set
        # guard above already proved that placement is valid here, so the
        # pipeline must NOT re-place — a blanket replicate() would silently
        # undo a TP-sharded CLIP tower.
        self.classify_top_k = classify_top_k
        self.caption = caption
        self.caption_prompt = caption_prompt
        self.caption_max_tokens = caption_max_tokens
        self.caption_workers = max(1, caption_workers)

        # Scaled decode target: the producer decodes oversized JPEGs at
        # reduced scale, never below the LARGEST consumer's input edge, so
        # every stage's resize/letterbox still only downscales. ``None`` =
        # auto (max over the configured stages); ``0`` disables (full
        # decode). Stage coordinates are mapped back to the ORIGINAL frame
        # via the per-item decode scale, so records are unchanged apart
        # from resampling tolerance.
        if decode_max_edge is None:
            targets = []
            if clip is not None:
                targets.append(clip.cfg.image_size)
            if face is not None:
                targets.append(face.det_cfg.input_size)
            if ocr is not None:
                from lumen_tpu.runtime.batcher import bucket_for

                buckets = sorted(ocr.spec.det_buckets)
                targets.append(bucket_for(ocr_det_size or buckets[-1], buckets))
            decode_max_edge = max(targets)
        self.decode_max_edge = decode_max_edge

        stages = []
        if clip is not None:
            stages.append(self._clip_stage(mesh))
        if face is not None:
            stages.append(self._face_stage(mesh))
        if ocr is not None:
            stages.append(self._ocr_stage(mesh))
        # embed -> index as a CONFIGURED task-graph edge: a derived node
        # fed by the clip stage's record value plus the item's content
        # fingerprint. ``cache_output=False`` keeps the sink's verdict out
        # of the result cache AND re-fires it on cache hits, so a warm
        # re-ingest of an already-embedded library still (re)indexes every
        # photo without touching the decode pool or the device.
        if index is not None:
            if clip is None:
                raise ValueError("index= requires a clip manager (the embedding source)")
            if not callable(index):
                raise ValueError("index= must be a callable(sha256, clip_out)")

            def index_post(decoded, deps):
                clip_out = deps["clip"]
                if clip_out is None or clip_out.get("embedding") is None:
                    return None  # undecodable item: nothing to index
                return index(deps.get("_sha256"), clip_out)

            stages.append(
                Stage(
                    "index",
                    postprocess=index_post,
                    inputs=("clip", "_sha256"),
                    cache_output=False,
                )
            )
        # Content-addressed re-ingest cache: the namespace pins every model
        # id@revision (and its compute precision — records from one
        # numerics config must not answer for another, esp. across
        # restarts via the disk tier) in the stage set; the options pin
        # every knob that changes a record. A re-index pass over an
        # unchanged library (or its duplicate-heavy tail) then skips
        # decode AND all device programs per hit; `stats.cache_hit_rate`
        # reports it.
        import jax.numpy as jnp

        def _sig(mgr) -> str:
            parts = [jnp.dtype(mgr.policy.compute_dtype).name]
            route = getattr(mgr, "quant_route", None)
            if route:
                parts.append(route)
            return ":".join(parts)

        models = ",".join(
            f"{fam}={mgr.model_id}@{mgr.info.version}:{_sig(mgr)}"
            for fam, mgr in (("clip", clip), ("face", face), ("ocr", ocr))
            if mgr is not None
        )
        self.engine = IngestPipeline(
            mesh,
            stages,
            decode=self._decode,
            batch_size=batch_size,
            prefetch=prefetch,
            inflight=inflight,
            workers=workers,
            annotate=lambda d: {"_error": d["error"]} if "error" in d else {},
            cache_namespace=f"ingest/photo/{models}",
            cache_options={
                "classify_top_k": classify_top_k,
                "ocr_det_size": ocr_det_size,
                "ocr_use_angle_cls": ocr_use_angle_cls,
                # Decode resolution changes record numerics (resampling):
                # entries from one decode policy must not answer another.
                "decode_max_edge": self.decode_max_edge,
            },
            # Process-parallel decode: the "photo" spec is _decode's
            # byte-path twin registered in lumen_tpu.utils.host_decode —
            # with LUMEN_DECODE_PROCS the producer's JPEG decode runs in
            # worker processes (no GIL) and pixels land in shared-memory
            # arena slots this pipeline's batches stack from directly.
            decode_spec=(
                "photo",
                {
                    "max_edge": self.decode_max_edge or 0,
                    "on_error": self.on_decode_error,
                },
            ),
            decode_adapter=self._adapt_decoded,
        )

    # -- decode -----------------------------------------------------------

    @staticmethod
    def _adapt_decoded(result) -> dict:
        """DecodedTensor from the "photo" spec -> the dict `_decode`
        produces (same keys, same error policy)."""
        dscale, oh, ow, err = result.extras
        if err is not None:
            return {"img": result.array, "meta": {}, "error": err}
        return {
            "img": result.array,
            "meta": {},
            "decode_scale": dscale,
            "orig_hw": (oh, ow),
        }

    def _decode(self, item) -> dict:
        try:
            dscale, orig_hw = 1.0, None
            if isinstance(item, (bytes, bytearray)):
                if self.decode_max_edge:
                    img, dscale, orig_hw = decode_image_bytes_scaled(
                        item, color="rgb", max_edge=self.decode_max_edge
                    )
                else:
                    img = decode_image_bytes(item, color="rgb")
            else:
                img = np.asarray(item)
            if img.ndim != 3 or img.shape[2] != 3:
                raise ValueError(f"expected HWC RGB image, got shape {img.shape}")
        except ValueError as e:
            if self.on_decode_error == "raise":
                raise
            # Placeholder keeps batch shapes static; stages skip real work.
            return {"img": np.zeros((8, 8, 3), np.uint8), "meta": {}, "error": str(e)}
        return {
            "img": img,
            "meta": {},
            "decode_scale": dscale,
            "orig_hw": orig_hw if orig_hw is not None else img.shape[:2],
        }

    # -- stages -----------------------------------------------------------

    def _clip_stage(self, mesh) -> Stage:
        mgr = self.clip
        size = mgr.cfg.image_size

        def preprocess(decoded: dict) -> np.ndarray:
            import cv2

            return cv2.resize(decoded["img"], (size, size), interpolation=cv2.INTER_LINEAR)

        def device_fn(pixels):
            return mgr._encode_images(mgr.params, pixels)

        def postprocess(decoded: dict, vec: np.ndarray):
            if "error" in decoded:
                return {"embedding": None}
            vec = mgr._check_vector(vec)
            out = {"embedding": vec}
            if self.classify_top_k > 0 and mgr._label_matrix is not None:
                res = mgr._classify_vector(
                    vec, mgr.label_names, mgr._label_matrix, self.classify_top_k
                )
                out["labels"] = res.labels
            return out

        return Stage("clip", preprocess, device_fn, postprocess)

    def _face_stage(self, mesh) -> Stage:
        mgr = self.face
        det_size = mgr.det_cfg.input_size

        def preprocess(decoded: dict) -> np.ndarray:
            boxed, scale, pad_top, pad_left = letterbox_numpy(decoded["img"], det_size)
            # Fold the scaled-decode factor into the unmap scale so boxes
            # and landmarks come out in ORIGINAL image coordinates.
            dscale = decoded.get("decode_scale", 1.0)
            h, w = decoded.get("orig_hw", decoded["img"].shape[:2])
            decoded["meta"]["face"] = (scale * dscale, pad_top, pad_left, h, w)
            return boxed

        def device_fn(images):
            return mgr._run_detector(mgr.det_vars, images)

        def postprocess(decoded: dict, row):
            if "error" in decoded:
                return []
            boxes, kps, scores, keep = row
            scale, pad_top, pad_left, h, w = decoded["meta"]["face"]
            faces = mgr.detections_from_outputs(
                boxes, kps, scores, keep,
                scale=scale, pad_top=pad_top, pad_left=pad_left, image_hw=(h, w),
            )
            if faces:
                mgr.embed_detections(
                    decoded["img"], faces,
                    coord_scale=decoded.get("decode_scale", 1.0),
                )
            return faces

        return Stage("face", preprocess, device_fn, postprocess)

    def _ocr_stage(self, mesh) -> Stage:
        from lumen_tpu.runtime.batcher import bucket_for

        mgr = self.ocr
        # One static det bucket for the whole ingest run (per-image bucket
        # choice would fragment the data-parallel batch into ragged shapes).
        # Defaults to the LARGEST bucket so bulk ingest matches the
        # per-request path's quality on big photos; dial down via
        # ``ocr_det_size`` to trade recall for throughput.
        buckets = sorted(mgr.spec.det_buckets)
        det_size = bucket_for(self.ocr_det_size or buckets[-1], buckets)

        def preprocess(decoded: dict) -> np.ndarray:
            boxed, scale, pad_top, pad_left = letterbox_numpy(decoded["img"], det_size)
            decoded["meta"]["ocr"] = (scale, pad_top, pad_left)
            return boxed

        def device_fn(images):
            return mgr._run_detector(mgr.det_vars, images)

        def postprocess(decoded: dict, prob):
            if "error" in decoded:
                return []
            scale, pad_top, pad_left = decoded["meta"]["ocr"]
            img = decoded["img"]
            found = mgr.boxes_from_det_output(
                np.asarray(prob),
                image_hw=img.shape[:2],
                scale=scale,
                pad_top=pad_top,
                pad_left=pad_left,
            )
            if not found:
                return []
            results = mgr.recognize_boxes(
                img, found, use_angle_cls=self.ocr_use_angle_cls
            )
            # Crops come from the (possibly scaled-)decoded frame; the
            # reported quads go back to ORIGINAL coordinates.
            dscale = decoded.get("decode_scale", 1.0)
            if dscale != 1.0:
                for r in results:
                    r.box = np.asarray(r.box, np.float32) / dscale
            return results

        return Stage("ocr", preprocess, device_fn, postprocess)

    # -- run --------------------------------------------------------------

    def run(self, items: Iterable[Any]) -> Iterator[PhotoRecord]:
        for raw in self.engine.run(items):
            rec = PhotoRecord(
                index=raw["_index"],
                error=raw.get("_error"),
                sha256=raw.get("_sha256"),
            )
            if "clip" in raw and raw["clip"] is not None:
                rec.clip_embedding = raw["clip"]["embedding"]
                rec.labels = raw["clip"].get("labels", [])
            if "face" in raw:
                rec.faces = raw["face"]
            if "ocr" in raw:
                rec.ocr = raw["ocr"]
            yield rec

    def run_with_captions(self, items: list[bytes]) -> list[PhotoRecord]:
        """Caption path: VLM generation is autoregressive (one lax.while_loop
        per image) and dominates cost, so it runs after the dense sweep."""
        records = list(self.run(items))
        return self.caption_records(records, items)

    def caption_records(
        self, records: list[PhotoRecord], items: list[bytes]
    ) -> list[PhotoRecord]:
        """Caption already-swept records in place. Per-image fault
        tolerance matches the decode contract: one VLM failure records an
        error on that row instead of aborting a multi-hour bulk run.

        Generation is autoregressive, but the continuous engine multiplexes
        decode slots — serial submission would leave all but one slot idle.
        Items fan out over ``caption_workers`` submitter threads (bounded:
        the engine's own admission queue is the real backpressure, the
        bound just keeps this caller from camping every slot), each tagged
        onto the BULK QoS lane so a captioning sweep browns out before
        interactive VLM traffic, never displacing it."""
        if not self.caption or self.vlm is None:
            return records
        from concurrent.futures import ThreadPoolExecutor

        from lumen_tpu.models.vlm.chat import ChatMessage
        from lumen_tpu.runtime.qos import LANE_BULK, qos_context

        def caption_one(rec: PhotoRecord, payload: bytes) -> None:
            # contextvars don't cross thread starts: re-tag per task.
            with qos_context(None, LANE_BULK):
                try:
                    result = self.vlm.generate(
                        [ChatMessage(role="user", content=self.caption_prompt)],
                        image_bytes=payload,
                        max_new_tokens=self.caption_max_tokens,
                    )
                    rec.caption = result.text
                except Exception as e:  # noqa: BLE001 - record, don't abort
                    logger.warning("caption failed for item %d: %s", rec.index, e)
                    rec.error = f"caption failed: {e}"

        todo = [
            (rec, payload)
            for rec, payload in zip(records, items)
            if not rec.error  # undecodable image: nothing to caption
        ]
        if not todo:
            return records
        if len(todo) == 1 or self.caption_workers == 1:
            for rec, payload in todo:
                caption_one(rec, payload)
            return records
        with ThreadPoolExecutor(
            max_workers=min(self.caption_workers, len(todo)),
            thread_name_prefix="caption",
        ) as pool:
            # Each record is touched by exactly ONE task (in-place, no
            # shared state); list(…) propagates nothing — caption_one
            # already contains every failure as a record error.
            list(pool.map(lambda rp: caption_one(*rp), todo))
        return records

    @property
    def stats(self):
        return self.engine.stats
