"""Batch-ingest pipeline: data-parallel bulk indexing over a device mesh.

The reference has no bulk-ingest path at all — its nearest mechanism is
per-request hub routing (``src/lumen/router.py:22-46``, SURVEY.md §6 "Full
ingest"). This subpackage is the new TPU-native capability that closes that
gap: a scheduler that streams a library of images through fixed-shape,
data-parallel device batches (CLIP + face + OCR [+ VLM]) with host-side
decode overlapped against device execution.
"""

from lumen_tpu.pipeline.ingest import IngestPipeline, IngestStats, Stage
from lumen_tpu.pipeline.photo import PhotoIngestPipeline, PhotoRecord

__all__ = [
    "IngestPipeline",
    "IngestStats",
    "Stage",
    "PhotoIngestPipeline",
    "PhotoRecord",
]
