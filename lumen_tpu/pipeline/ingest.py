"""Generic data-parallel ingest scheduler.

Execution model (the three overlapped lanes):

1. **host decode/preprocess** — a producer thread drains the item iterator,
   fans per-item work over a thread pool, stacks results into fixed-shape
   numpy batches (padding the tail batch), and transfers them to the mesh
   with a ``data``-axis sharding;
2. **device** — the consumer dispatches every stage's jitted function on a
   prepared batch and keeps up to ``inflight`` batches un-fetched, so XLA's
   async dispatch pipelines batch *k+1* behind batch *k*;
3. **host postprocess** — once a batch's device work is fetched (one
   device->host transfer per stage), per-item ``postprocess`` runs and a
   merged record per item is yielded in order.

Static shapes everywhere: every stage's ``preprocess`` must return leaves of
one fixed shape, and the batch size is constant (tail padded), so each stage
compiles exactly once (SURVEY.md §7 design stance (1)-(2)).

The reference has no equivalent component; its per-request hot loop is one
ONNX session call per payload (``SURVEY.md`` §3.2).
"""

from __future__ import annotations

import copy
import hashlib
import logging
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Iterator, Mapping, Sequence

import jax

from lumen_tpu.runtime.batcher import stack_and_pad, unstack
from lumen_tpu.runtime.decode_pool import DecodePool, get_decode_pool
from lumen_tpu.runtime.mesh import DATA_AXIS, data_sharding
from lumen_tpu.runtime.quarantine import QuarantineRegistry, get_quarantine
from lumen_tpu.runtime.qos import (
    LANE_BULK,
    activate as qos_activate,
    current_qos as qos_current,
    deactivate as qos_deactivate,
    qos_context,
)
from lumen_tpu.runtime.result_cache import ResultCache, get_result_cache, make_key
from lumen_tpu.runtime.trace import begin_request, finish_request
from lumen_tpu.utils.deadline import QueueFull

logger = logging.getLogger(__name__)


@dataclass
class Stage:
    """One node of an ingest task graph.

    Two kinds, distinguished by ``inputs``:

    **Source node** (``inputs=()``, the classic device-batched stage) —
    consumes the decoded item:

    - ``preprocess(decoded)`` -> fixed-shape numpy pytree for one item (host,
      runs in the decode worker pool);
    - ``device_fn(batched_tree)`` -> batched device result tree (should be
      ``jax.jit``-ed; inputs arrive sharded over the ``data`` mesh axis);
    - ``postprocess(decoded, row)`` -> the per-item record value (host).

    **Derived node** (``inputs`` non-empty) — a host-side step fed by other
    nodes' record values instead of a device batch. ``preprocess`` and
    ``device_fn`` are unused (must stay ``None``); ``postprocess(decoded,
    deps)`` receives a ``{input_name: value}`` dict of the declared inputs
    and its return value lands under ``name`` in the record. Inputs name
    other stages, or record meta keys starting with ``_`` (``"_sha256"``).
    Derived nodes run in dependency (topological) order after the item's
    source-stage values settle — including on CACHE-HIT records when
    ``cache_output=False`` (see below), where ``decoded`` is ``None``
    because the item was never decoded; a derived ``postprocess`` must
    tolerate that.

    ``cache_output=False`` marks a node whose value is a side effect (e.g.
    pushing an embedding into a search index), excluded from the result
    cache so it re-fires on every pass — cache hits included — instead of
    replaying a stale verdict.
    """

    name: str
    preprocess: Callable[[Any], Any] | None = None
    device_fn: Callable[[Any], Any] | None = None
    postprocess: Callable[[Any, Any], Any] = field(default=lambda decoded, row: row)
    inputs: tuple[str, ...] = ()
    cache_output: bool = True


def _build_graph(stages: Sequence[Stage]) -> tuple[list[Stage], list[Stage]]:
    """Validate the declared task graph -> ``(device_stages, derived_topo)``.

    Device stages keep their given order (it IS the dispatch and record-key
    order — the parity contract with the pre-DAG pipeline). Derived nodes
    come back topologically sorted; duplicate names, unknown inputs, a
    ``device_fn`` on a derived node, a missing one on a source node, and
    dependency cycles all raise at construction, not mid-run."""
    names = [s.name for s in stages]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise ValueError(f"duplicate stage names: {sorted(dupes)}")
    known = set(names)
    device: list[Stage] = []
    derived: list[Stage] = []
    for s in stages:
        if s.inputs:
            if s.device_fn is not None or s.preprocess is not None:
                raise ValueError(
                    f"derived stage {s.name!r} declares inputs; it runs "
                    "host-side and must not set preprocess/device_fn"
                )
            for dep in s.inputs:
                if not dep.startswith("_") and dep not in known:
                    raise ValueError(
                        f"stage {s.name!r} depends on unknown stage {dep!r}"
                    )
            derived.append(s)
        else:
            if s.preprocess is None or s.device_fn is None:
                raise ValueError(
                    f"source stage {s.name!r} needs both preprocess and "
                    "device_fn (declare inputs to make it a derived node)"
                )
            device.append(s)
    # Kahn's algorithm over the derived subgraph (device stages and meta
    # keys are always-ready inputs).
    derived_names = {s.name for s in derived}
    pending = {
        s.name: {d for d in s.inputs if d in derived_names} for s in derived
    }
    by_name = {s.name: s for s in derived}
    order: list[Stage] = []
    ready = [s.name for s in derived if not pending[s.name]]
    while ready:
        name = ready.pop(0)
        order.append(by_name[name])
        for other, deps in pending.items():
            if name in deps:
                deps.discard(name)
                if not deps:
                    ready.append(other)
    if len(order) != len(derived):
        stuck = sorted(set(derived_names) - {s.name for s in order})
        raise ValueError(f"dependency cycle among derived stages: {stuck}")
    return device, order


@dataclass
class IngestStats:
    items: int = 0
    batches: int = 0
    cache_hits: int = 0  # items answered from the result cache (no decode)
    errors: int = 0      # items that became per-item ``_error`` records
    quarantined: int = 0  # items rejected up front by the poison quarantine
    duplicates: int = 0  # byte items whose content sha256 repeated in-run
    wall_s: float = 0.0
    decode_s: float = 0.0  # producer-lane time (decode + preprocess + transfer)
    device_s: float = 0.0  # consumer time blocked on device fetches
    post_s: float = 0.0
    max_inflight: int = 0  # high-water mark of dispatched-unfetched batches
    pool: dict = field(default_factory=dict)  # decode-pool gauges at run end

    @property
    def items_per_sec(self) -> float:
        return self.items / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def cache_hit_rate(self) -> float:
        return self.cache_hits / self.items if self.items else 0.0

    def as_dict(self) -> dict:
        out = {
            "items": self.items,
            "batches": self.batches,
            "cache_hits": self.cache_hits,
            "cache_hit_rate": round(self.cache_hit_rate, 4),
            "errors": self.errors,
            "quarantined": self.quarantined,
            "duplicates": self.duplicates,
            "wall_s": round(self.wall_s, 4),
            "items_per_sec": round(self.items_per_sec, 2),
            "decode_s": round(self.decode_s, 4),
            "device_s": round(self.device_s, 4),
            "post_s": round(self.post_s, 4),
            "max_inflight": self.max_inflight,
        }
        if self.pool:
            out["pool"] = self.pool
        return out


class _Batch:
    __slots__ = (
        "decoded", "inputs", "outputs", "n", "indices", "keys", "shas",
        "trace", "qspan", "wspan", "leases",
    )

    def __init__(
        self,
        decoded: list,
        inputs: dict[str, Any],
        n: int,
        indices: list[int] | None = None,
        keys: list[str | None] | None = None,
        shas: list[str | None] | None = None,
    ):
        self.decoded = decoded
        self.inputs = inputs  # stage name -> sharded device tree
        self.outputs: dict[str, Any] = {}
        self.n = n
        # Global item indices (cache hits skip batches, so batch rows are
        # no longer contiguous) and per-row result-cache keys (None when
        # the item is uncacheable or caching is off).
        self.indices = indices if indices is not None else list(range(n))
        self.keys = keys if keys is not None else [None] * n
        # Content sha256 per row (None for non-bytes items): surfaces on
        # records as ``_sha256`` — the dedupe primitive — and is NOT part
        # of the cached value (attached fresh each run).
        self.shas = shas if shas is not None else [None] * n
        # Per-batch request trace (LUMEN_TRACE_SAMPLE > 0): the trace and
        # its open queue-wait / inflight-wait spans hop from the producer
        # thread to the consumer with the batch — contextvars don't cross.
        self.trace = None
        self.qspan = None
        self.wspan = None
        # Shared-memory decode leases (process-mode decode pool): each
        # decoded["img"] may be a zero-copy view over an arena slot. The
        # slots recycle only after the LAST consumer of the pixels —
        # postprocess (face crops, OCR warps) — has run; every exit path
        # of the consumer releases (idempotently).
        self.leases: list = []

    def release(self) -> None:
        for lease in self.leases:
            lease.release()


class IngestPipeline:
    """Stream items through data-parallel device stages over a mesh.

    ``batch_size`` must be a multiple of the mesh's ``data`` axis size (it is
    the GLOBAL batch; each device sees ``batch_size / data`` rows).
    """

    def __init__(
        self,
        mesh: jax.sharding.Mesh,
        stages: Sequence[Stage],
        decode: Callable[[Any], Any] = lambda item: item,
        batch_size: int = 64,
        prefetch: int = 2,
        inflight: int = 2,
        workers: int | None = None,
        annotate: Callable[[Any], dict] | None = None,
        cache_namespace: str | None = None,
        cache_options: Mapping[str, Any] | None = None,
        decode_spec: tuple[str, dict] | None = None,
        decode_adapter: Callable[[Any], Any] | None = None,
    ):
        if not stages:
            raise ValueError("need at least one stage")
        dp = mesh.shape.get(DATA_AXIS, 1)
        if batch_size % dp != 0:
            raise ValueError(
                f"batch_size {batch_size} not a multiple of '{DATA_AXIS}' axis size {dp}"
            )
        self.mesh = mesh
        self.stages = list(stages)
        # Task-graph validation: split the declared nodes into device
        # (source) stages — kept in GIVEN order, which fixes the batch
        # dispatch order and the record key order — and host-side derived
        # nodes, topologically sorted by their declared inputs.
        self._device_stages, self._derived_stages = _build_graph(self.stages)
        # Record keys excluded from cache.put values: positional meta plus
        # every ``cache_output=False`` node's value.
        self._strip_keys = {"_index", "_sha256"} | {
            s.name for s in self.stages if not s.cache_output
        }
        self.decode = decode
        self.batch_size = batch_size
        self.prefetch = max(prefetch, 1)
        self.inflight = max(inflight, 1)
        # Host decode/preprocess lane: the process-wide shared pool
        # (LUMEN_DECODE_WORKERS) by default, so concurrent pipelines and
        # the serving managers contend for one sized set of decode
        # threads instead of each spawning their own. An explicit
        # ``workers`` pins a private pool instead — created per run() and
        # torn down with it, so a dropped pipeline object leaks neither
        # threads nor metrics-gauge registrations.
        self._pinned_workers = max(0, workers or 0)
        #: optional per-item record enrichment from the decoded value (e.g.
        #: surfacing decode-failure markers set by a fault-tolerant decode)
        self.annotate = annotate
        # Result-cache integration: when a namespace is set, every BYTES
        # item is hashed and looked up in the process-wide cache BEFORE
        # the decode pool — a hit skips decode, preprocess, transfer and
        # every device stage (the host decode lane is the measured ingest
        # bottleneck, BENCH_r05). Misses are stored after postprocess, so
        # a warm re-ingest of the same library is pure cache traffic.
        # Non-bytes items pass through untouched. Best-effort within one
        # run: duplicates already in flight compute again (bulk ingest is
        # offline; single-flight coalescing is for the serving path).
        self.cache_namespace = cache_namespace
        self.cache_options = dict(cache_options or {})
        # Process-parallel decode: a ``(spec_name, params)`` pair names a
        # registered decode recipe (lumen_tpu.utils.host_decode) that can
        # run in the pool's worker PROCESSES — byte items then decode
        # with no GIL anywhere and land in shared-memory arena slots the
        # batch stacks from directly. ``decode_adapter(DecodedTensor)``
        # turns one result into the per-item decoded value ``decode``
        # would have produced. Engages only when the shared pool is in
        # process mode AND a chunk is all-bytes; everything else uses the
        # ``decode`` callable on the thread lane, unchanged.
        self.decode_spec = decode_spec
        self.decode_adapter = decode_adapter
        self._sharding = data_sharding(mesh)
        self.stats = IngestStats()  # stats of the most recent run()
        self._run_pool_tasks = 0

    def _cache(self) -> ResultCache | None:
        """The shared cache, when this pipeline is configured to use it and
        the env has not disabled it (resolved per run, like the pool)."""
        if not self.cache_namespace:
            return None
        cache = get_result_cache()
        return cache if cache.enabled else None

    @property
    def pool(self) -> DecodePool | None:
        """The shared pool, resolved at use time (a `shutdown_decode_pool`
        + rebuild between runs must not strand this pipeline on a closed
        executor); ``None`` when ``workers`` pins a run-scoped private
        pool."""
        return None if self._pinned_workers else get_decode_pool()

    @property
    def workers(self) -> int:
        pool = self.pool
        return pool.workers if pool is not None else self._pinned_workers

    # -- producer lane ----------------------------------------------------

    def _prepare(
        self, pool: DecodePool, chunk: list[tuple[int, Any, str | None, str | None]]
    ) -> _Batch:
        # One trace per BATCH (not per item — 64x cheaper and the stages
        # are batch-granular anyway): decode covers the producer lane
        # (pool fan-out + stack + transfer), queue is the hand-off wait to
        # the consumer, then dispatch/fetch/post land on the consumer.
        tr = begin_request("ingest")
        dspan = tr.begin("decode", {"items": len(chunk)}) if tr is not None else None
        raw_items = [item for _, item, _, _ in chunk]
        decoded, leases = self._decode_chunk(pool, raw_items)
        try:
            inputs: dict[str, Any] = {}
            for stage in self._device_stages:
                trees = pool.map(stage.preprocess, decoded)
                stacked = stack_and_pad(trees, self.batch_size)
                inputs[stage.name] = jax.tree_util.tree_map(
                    lambda leaf: jax.device_put(leaf, self._sharding), stacked
                )
        except BaseException:
            for lease in leases:
                lease.release()
            raise
        # Producer-side count (only the producer thread writes): the pool's
        # own `tasks` gauge is process-wide, so THIS run's decode work has
        # to be tallied where it is submitted.
        self._run_pool_tasks += len(raw_items) * (1 + len(self._device_stages))
        batch = _Batch(
            decoded,
            inputs,
            len(raw_items),
            [idx for idx, _, _, _ in chunk],
            [key for _, _, key, _ in chunk],
            [sha for _, _, _, sha in chunk],
        )
        batch.leases = leases
        if tr is not None:
            dspan.end()
            batch.trace = tr
            batch.qspan = tr.begin("queue")
        return batch

    def _decode_chunk(self, pool: DecodePool, raw_items: list) -> tuple[list, list]:
        """Decode one chunk -> ``(decoded_values, shm_leases)``. Routes
        through the process lane (registered spec, all-bytes chunk,
        process-mode pool) or the thread lane (the ``decode`` callable),
        producing identical values either way."""
        if (
            self.decode_spec is not None
            and pool.process_mode
            and all(isinstance(it, (bytes, bytearray)) for it in raw_items)
        ):
            name, params = self.decode_spec
            try:
                results = pool.map_decode(name, raw_items, params)
            except QueueFull as e:
                # A decode worker died mid-chunk. The serving path sheds
                # this as retryable; a bulk run retries ITSELF — on the
                # thread lane, immediately — so one crashed codec worker
                # never aborts a multi-hour ingest (map_decode already
                # released any half-chunk leases).
                logger.warning(
                    "process decode of a %d-item chunk failed (%s); "
                    "re-decoding on the thread lane", len(raw_items), e,
                )
                return pool.map(self.decode, raw_items), []
            adapt = self.decode_adapter or (lambda r: r.array)
            return [adapt(r) for r in results], results
        return pool.map(self.decode, raw_items), []

    @staticmethod
    def _offer(out: queue.Queue, entry, stop: threading.Event) -> bool:
        """put() that gives up when the consumer has stopped (an abandoned
        run() generator must not leave the producer parked on a full queue)."""
        while not stop.is_set():
            try:
                out.put(entry, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _producer(
        self,
        items: Iterable[Any],
        out: queue.Queue,
        stop: threading.Event,
        pool: DecodePool | None,
        cache: ResultCache | None,
        quarantine: QuarantineRegistry,
        tenant: str | None = None,
    ) -> None:
        # ``pool`` is run()'s single resolve of the shared pool (None when
        # ``workers`` is pinned) — resolving again here could land on a
        # different pool if the shared one is rebuilt mid-run, and the
        # finally-block gauge snapshot would describe the wrong pool.
        # The producer lane runs on the BULK QoS lane (contextvars don't
        # cross thread starts, so run()'s tag must be re-applied here):
        # ingest is the canonical bulk-convoy workload, and any lane-aware
        # component it reaches (today the consumer-side shared-batcher
        # submits — see the postprocess loop in run() — tomorrow anything
        # under the decode/cache path) must see it as bulk, never
        # displacing interactive traffic. The TENANT is run()'s caller
        # identity, captured on the caller thread and re-applied here for
        # the same reason: the producer computes cache keys and quarantine
        # fingerprints, and a tenant-scoped ingest must never read/flag
        # the default tenant's namespace.
        private: DecodePool | None = None
        qos_token = qos_activate(tenant, LANE_BULK)
        try:
            if pool is None:  # workers pinned: run-scoped private pool
                pool = private = DecodePool(
                    self._pinned_workers, name=f"ingest-prep:{id(self) & 0xFFFF:04x}"
                )
            chunk: list[tuple[int, Any, str | None, str | None]] = []
            hits: dict[int, dict] = {}
            index = 0
            # Content-fingerprint dedupe tally: one sha256 of the RAW bytes
            # per item (the cache key folds namespace+options in, so it
            # cannot serve as a pure content hash). Surfaced per record as
            # ``_sha256``; repeats within this run count as ``duplicates``.
            seen_shas: set[str] = set()

            def emit_hits() -> bool:
                nonlocal hits
                if not hits:
                    return True
                pending, hits = hits, {}
                return self._offer(out, ("hits", pending), stop)

            def emit_chunk() -> bool:
                nonlocal chunk
                t0 = time.perf_counter()
                batch = self._prepare(pool, chunk)
                self.stats.decode_s += time.perf_counter() - t0
                chunk = []
                if not self._offer(out, batch, stop):
                    batch.release()  # abandoned run: recycle shm slots
                    return False
                return True

            for item in items:
                if stop.is_set():
                    return
                key = None
                record = None
                sha = None
                if isinstance(item, (bytes, bytearray)):
                    sha = hashlib.sha256(item).hexdigest()
                    if sha in seen_shas:
                        self.stats.duplicates += 1
                    else:
                        seen_shas.add(sha)
                if (
                    self.cache_namespace
                    and isinstance(item, (bytes, bytearray))
                    and (cache is not None or quarantine.enabled)
                ):
                    # One sha256 over the RAW bytes serves both pre-decode
                    # gates: the quarantine rejection and the cache lookup
                    # — neither touches the decode pool (the lane
                    # BENCH_r05 measured as the ingest bottleneck).
                    key = make_key(self.cache_namespace, self.cache_options, item)
                    reason = quarantine.reason(key)
                    if reason is not None:
                        # Poison containment: a known-bad item becomes a
                        # per-item error record instead of wasting decode
                        # + device work failing the same way again.
                        self.stats.quarantined += 1
                        self.stats.errors += 1
                        record = {"_error": f"quarantined: {reason}"}
                    elif cache is not None:
                        found, rec = cache.get(key, clone=copy.deepcopy)
                        if found:
                            self.stats.cache_hits += 1
                            record = rec
                if record is not None:
                    record["_sha256"] = sha
                    hits[index] = record
                    index += 1
                    # Bound the consumer's reorder buffer: a long hit
                    # run stuck behind a part-filled miss chunk flushes
                    # that chunk (padded batch) instead of buffering
                    # hit records without limit.
                    if chunk and len(hits) >= self.batch_size:
                        if not emit_chunk():
                            return
                    if not chunk and not emit_hits():
                        return
                    continue
                chunk.append((index, item, key, sha))
                index += 1
                if len(chunk) == self.batch_size:
                    if not emit_hits() or not emit_chunk():
                        return
            if not emit_hits():
                return
            if chunk and not stop.is_set():
                if not emit_chunk():
                    return
            self._offer(out, None, stop)
        except BaseException as e:  # noqa: BLE001 - surface in the consumer
            self._offer(out, e, stop)
        finally:
            qos_deactivate(qos_token)
            if private is not None:
                self.stats.pool = private.gauges()
                private.close()

    # -- consumer ---------------------------------------------------------

    def run(self, items: Iterable[Any]) -> Iterator[dict]:
        """Yield one record dict per input item, in input order. Record keys
        are stage names plus ``_index``.

        With ``cache_namespace`` set, byte items found in the result cache
        bypass the batches entirely (their records arrive as ``hits``
        queue entries) and settled miss records are stored back — a small
        reorder buffer re-serializes the two streams into input order."""
        self.stats = IngestStats()  # fresh stats per run
        self._run_pool_tasks = 0  # producer-side tally of this run's tasks
        # One resolve for the whole run: the shared pool must not be
        # swapped (shutdown_decode_pool + rebuild) between the producer's
        # submissions and the finally-block snapshot. Same for the cache.
        run_pool = self.pool
        cache = self._cache()
        quarantine = get_quarantine()
        # Fence taken at run start: a namespace invalidation (model
        # hot-swap) landing mid-run must stop this run's records — which
        # were computed by the pre-swap managers — from being stored past
        # it. Hits already served are the caller's to judge; persistence
        # is what must stay clean.
        fence = cache.current_fence() if cache is not None else 0
        start = time.perf_counter()
        ready: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        producer = threading.Thread(
            target=self._producer,
            # The caller's tenant rides along explicitly: contextvars do
            # not cross the thread start, and the producer's cache keys /
            # quarantine fingerprints must stay in the caller's namespace.
            args=(items, ready, stop, run_pool, cache, quarantine,
                  qos_current()[0]),
            name="ingest-producer", daemon=True
        )
        producer.start()
        pending: deque[_Batch] = deque()
        current: _Batch | None = None  # batch mid-postprocess (lease cleanup)
        # Reorder buffer: index -> finished record. Cache hits land here
        # directly from the queue; batch rows land when their batch
        # settles. Bounded by the producer's chunk-flush rule (a hit run
        # can outpace a part-filled miss chunk by at most batch_size).
        finished: dict[int, dict] = {}
        next_idx = 0
        try:
            done = False
            while True:
                # Dispatch up to `inflight` batches before fetching results.
                # Only BLOCK when nothing else is actionable: no batch
                # pending AND no record ready to yield (a slow producer
                # must not delay results already in hand).
                while not done and len(pending) < self.inflight:
                    try:
                        got = ready.get(block=not pending and next_idx not in finished)
                    except queue.Empty:
                        break
                    if got is None:
                        done = True
                        break
                    if isinstance(got, BaseException):
                        raise got
                    if isinstance(got, tuple) and got and got[0] == "hits":
                        for i, rec in got[1].items():
                            rec["_index"] = i
                            # Side-effect nodes (cache_output=False) fire
                            # on hits too — a cached embedding still gets
                            # (re-)indexed. `decoded` is None: the item
                            # was answered without a decode. Runs under
                            # the bulk lane like every consumer-side hook.
                            if self._derived_stages:
                                try:
                                    with qos_context(None, LANE_BULK):
                                        self._apply_derived(
                                            rec, None, skip_cached=True
                                        )
                                except QueueFull as e:
                                    rec["_error"] = (
                                        f"shed: {type(e).__name__}: {e}"
                                    )
                                    self.stats.errors += 1
                            finished[i] = rec
                        continue
                    if got.qspan is not None:
                        got.qspan.end()  # thread hop: producer -> consumer
                    try:
                        # Bulk-lane scope like the producer/postprocess/
                        # salvage paths: a device_fn that submits into a
                        # shared lane-aware admission queue must compete
                        # as bulk, never displacing interactive traffic.
                        with qos_context(None, LANE_BULK):
                            if got.trace is not None:
                                # Per-stage child spans: DAG attribution —
                                # which node of the task graph ate the
                                # dispatch budget — for free in any trace.
                                with got.trace.span("device.dispatch"):
                                    for stage in self._device_stages:
                                        with got.trace.span(f"stage.{stage.name}"):
                                            got.outputs[stage.name] = stage.device_fn(
                                                got.inputs[stage.name]
                                            )
                            else:
                                for stage in self._device_stages:
                                    got.outputs[stage.name] = stage.device_fn(got.inputs[stage.name])
                    except Exception as e:  # noqa: BLE001 - contain, don't abort the run
                        self._salvage_batch(got, e, cache, fence, quarantine, finished)
                        got.release()
                        continue
                    if got.trace is not None:
                        # Device compute overlaps this wait (async dispatch):
                        # the batch sits dispatched-but-unfetched while the
                        # consumer settles its predecessors.
                        got.wspan = got.trace.begin("inflight")
                    pending.append(got)
                    self.stats.max_inflight = max(self.stats.max_inflight, len(pending))
                yielded = False
                while next_idx in finished:
                    record = finished.pop(next_idx)
                    next_idx += 1
                    self.stats.items += 1
                    yielded = True
                    yield record
                if yielded:
                    continue
                if not pending:
                    if done:
                        break
                    continue  # block in the fill loop for more input
                batch = current = pending.popleft()
                t0 = time.perf_counter()
                if batch.wspan is not None:
                    batch.wspan.end()
                fspan = batch.trace.begin("fetch") if batch.trace is not None else None
                try:
                    rows_by_stage = {
                        s.name: unstack(batch.outputs[s.name], batch.n)
                        for s in self._device_stages
                    }
                except Exception as e:  # noqa: BLE001 - async dispatch: errors often land at fetch
                    if fspan is not None:
                        fspan.end(error=type(e).__name__)
                    self.stats.device_s += time.perf_counter() - t0
                    self._salvage_batch(batch, e, cache, fence, quarantine, finished)
                    batch.release()
                    continue
                if fspan is not None:
                    fspan.end()
                self.stats.device_s += time.perf_counter() - t0
                t0 = time.perf_counter()
                pspan = batch.trace.begin("post") if batch.trace is not None else None
                # Postprocess runs under the BULK lane: per-item hooks can
                # submit into SHARED admission queues (the face stage's
                # embed_detections rides the rec-model MicroBatcher), and
                # those submits must queue as bulk — browning out before
                # interactive face requests, never displacing them. Scoped
                # to the loop (not the generator) so the tag cannot leak
                # into the caller's context across a yield.
                with qos_context(None, LANE_BULK):
                    for i in range(batch.n):
                        record: dict[str, Any] = {"_index": batch.indices[i]}
                        if batch.shas[i] is not None:
                            record["_sha256"] = batch.shas[i]
                        try:
                            for s in self._device_stages:
                                record[s.name] = s.postprocess(
                                    batch.decoded[i], rows_by_stage[s.name][i]
                                )
                            if self.annotate is not None:
                                record.update(self.annotate(batch.decoded[i]))
                            self._apply_derived(record, batch.decoded[i])
                        except QueueFull as e:
                            # A bulk-lane shed from a shared admission queue
                            # (postprocess hooks submit into MicroBatchers,
                            # which brown bulk out under pressure). Transient
                            # load, not bad input: the item gets a retryable
                            # _error record and the run continues.
                            record = {
                                "_index": batch.indices[i],
                                "_error": f"shed: {type(e).__name__}: {e}",
                            }
                            self.stats.errors += 1
                        # Store back (deep-copied: the caller owns and may
                        # mutate the yielded record) — except records flagged
                        # by annotate() as errored (e.g. decode failures under
                        # on_decode_error="record"): an error placeholder must
                        # not become the cached truth for those bytes.
                        if cache is not None and batch.keys[i] is not None and not record.get("_error"):
                            cache.put(
                                batch.keys[i],
                                {k: v for k, v in record.items()
                                 if k not in self._strip_keys},
                                clone=copy.deepcopy,
                                fence=fence,
                            )
                        finished[batch.indices[i]] = record
                if pspan is not None:
                    pspan.end()
                finish_request(batch.trace)
                # Postprocess (the last pixel consumer — face crops, OCR
                # warps read decoded["img"]) is done: recycle shm slots.
                batch.release()
                current = None
                self.stats.post_s += time.perf_counter() - t0
                self.stats.batches += 1
        finally:
            stop.set()
            # Abandoned run: batches dispatched-but-unfetched (and any
            # still in the hand-off queue, drained below) hold arena
            # leases — recycle them or the arena leaks until pool close.
            if current is not None:
                current.release()
            for b in pending:
                b.release()
            # Unblock a producer parked on a full queue; _offer's timeout
            # makes it observe `stop` within 100ms even if we drain nothing.
            while producer.is_alive():
                try:
                    got = ready.get(timeout=0.05)
                    if isinstance(got, _Batch):
                        got.release()
                except queue.Empty:
                    pass
                producer.join(timeout=0.05)
            self.stats.wall_s = time.perf_counter() - start
            if run_pool is not None:  # private pools snapshot at teardown
                g = run_pool.gauges()
                # `tasks` is this run's own submissions (exact, counted at
                # the producer); the other gauges are pool-level context —
                # on the SHARED pool, wait_ms_p50 and queue_depth include
                # concurrent users by design (that contention is real).
                g["tasks"] = self._run_pool_tasks
                self.stats.pool = g

    def _apply_derived(
        self, record: dict, decoded, skip_cached: bool = False
    ) -> None:
        """Evaluate the derived nodes of the task graph (topological
        order) against one record. A node whose declared inputs are not
        all present (an ``_error`` record, a stale cached shape) is
        skipped, not crashed. ``skip_cached=True`` — the cache-hit path —
        leaves already-cached values alone and only (re-)fires nodes
        missing from the record, i.e. every ``cache_output=False`` side
        effect plus any node added since the record was cached."""
        for s in self._derived_stages:
            if skip_cached and s.name in record:
                continue
            if not all(d in record for d in s.inputs):
                continue
            record[s.name] = s.postprocess(
                decoded, {d: record[d] for d in s.inputs}
            )

    def _salvage_batch(
        self,
        batch: _Batch,
        error: Exception,
        cache: ResultCache | None,
        fence: int,
        quarantine: QuarantineRegistry,
        finished: dict[int, dict],
    ) -> None:
        """A batch's device work raised: contain instead of aborting the
        run. Every item re-runs ALONE — its single-item tree padded to the
        same static ``batch_size`` shape, so no new compile — and the
        item(s) that still fail become per-item ``_error`` records with
        their fingerprints quarantined (the next ingest pass rejects them
        pre-decode); innocents keep their real records. Cost: up to
        ``batch_size`` full-shape device calls for the one failing batch —
        the rare-poison price, paid only on failure.

        Exception: a :class:`QueueFull` is a bulk-lane load shed from a
        shared admission queue, not a poison suspicion — every item becomes
        a retryable ``shed:`` record immediately (no per-item re-runs, which
        would hammer the very queue that just shed, and no quarantine)."""
        t0 = time.perf_counter()
        if isinstance(error, QueueFull):
            logger.warning(
                "ingest batch of %d shed by a shared admission queue (%s); "
                "items marked retryable", batch.n, error,
            )
            for i in range(batch.n):
                finished[batch.indices[i]] = {
                    "_index": batch.indices[i],
                    "_error": f"shed: {type(error).__name__}: {error}",
                }
                self.stats.errors += 1
            finish_request(batch.trace, error=f"{type(error).__name__}: {error}")
            self.stats.post_s += time.perf_counter() - t0
            self.stats.batches += 1
            return
        logger.warning(
            "ingest batch of %d failed (%s: %s); salvaging per-item",
            batch.n, type(error).__name__, error,
        )
        succeeded = 0
        failed: list[tuple[int, Exception]] = []  # (batch row, its error)
        # Bulk-lane scope for the same reason as run()'s postprocess loop:
        # the per-item re-runs call postprocess hooks that can submit into
        # shared admission queues.
        with qos_context(None, LANE_BULK):
            for i in range(batch.n):
                idx = batch.indices[i]
                record: dict[str, Any] = {"_index": idx}
                if batch.shas[i] is not None:
                    record["_sha256"] = batch.shas[i]
                try:
                    for s in self._device_stages:
                        tree = s.preprocess(batch.decoded[i])
                        stacked = stack_and_pad([tree], self.batch_size)
                        placed = jax.tree_util.tree_map(
                            lambda leaf: jax.device_put(leaf, self._sharding), stacked
                        )
                        row = unstack(s.device_fn(placed), 1)[0]
                        record[s.name] = s.postprocess(batch.decoded[i], row)
                except QueueFull as e:
                    # Shed mid-salvage (postprocess hooks submit into shared
                    # queues): transient, never a poison verdict — counts in
                    # neither `succeeded` nor `failed`.
                    record = {
                        "_index": idx,
                        "_error": f"shed: {type(e).__name__}: {e}",
                    }
                    self.stats.errors += 1
                except Exception as e:  # noqa: BLE001 - candidate poison (pending sibling evidence)
                    record = {
                        "_index": idx,
                        "_error": f"poison: {type(e).__name__}: {e}",
                    }
                    self.stats.errors += 1
                    failed.append((i, e))
                else:
                    succeeded += 1
                    if self.annotate is not None:
                        record.update(self.annotate(batch.decoded[i]))
                    try:
                        self._apply_derived(record, batch.decoded[i])
                    except QueueFull as e:
                        record = {
                            "_index": idx,
                            "_error": f"shed: {type(e).__name__}: {e}",
                        }
                        self.stats.errors += 1
                    if cache is not None and batch.keys[i] is not None and not record.get("_error"):
                        cache.put(
                            batch.keys[i],
                            {k: v for k, v in record.items()
                             if k not in self._strip_keys},
                            clone=copy.deepcopy,
                            fence=fence,
                        )
                finished[idx] = record
        # Same evidence rule as the batcher's bisection: a poison verdict
        # (and quarantine registration) requires at least one sibling that
        # ran clean. If EVERY item failed alone, the device — not the
        # inputs — is broken: the records still carry their errors, but
        # innocent photos must not be quarantined for the TTL window.
        if succeeded:
            for i, e in failed:
                if batch.keys[i]:
                    quarantine.add(
                        batch.keys[i], f"ingest: {type(e).__name__}: {e}"
                    )
        elif failed:
            logger.error(
                "ingest salvage found no healthy item in a batch of %d; "
                "treating as a device-level failure (nothing quarantined)",
                batch.n,
            )
            for i, _ in failed:
                finished[batch.indices[i]]["_error"] = (
                    f"batch: {type(error).__name__}: {error}"
                )
        finish_request(batch.trace, error=f"{type(error).__name__}: {error}")
        self.stats.post_s += time.perf_counter() - t0
        self.stats.batches += 1

    def run_all(self, items: Iterable[Any]) -> list[dict]:
        return list(self.run(items))
