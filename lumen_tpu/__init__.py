"""lumen-tpu: a TPU-native ML inference framework.

A from-scratch rebuild of the capabilities of EdwinZhanCN/Lumen (a local-first
photo-indexing inference microservice suite: CLIP embedding / zero-shot
classification, face detection + recognition, OCR, and VLM captioning behind a
shared gRPC streaming protocol) — re-designed for TPU hardware:

- Compute is Flax modules compiled by XLA (bf16 matmuls on the MXU), not ONNX
  graph sessions (reference execution layer:
  ``packages/lumen-clip/src/lumen_clip/backends/onnxrt_backend.py``).
- Throughput comes from a micro-batching runtime with static shape buckets
  (the reference serves one payload per request).
- Scale-out uses ``jax.sharding.Mesh`` + XLA collectives over ICI/DCN rather
  than per-process model replicas.

Subpackages
-----------
core      config / resources / result schemas (reference: lumen-resources)
runtime   mesh + dtype policy + batching queue + weight loading
ops       jnp/Pallas kernels: attention, NMS, CTC, image ops
parallel  sharding rules, ring attention, multi-host init
models    flax model families: clip, face, ocr, vlm
serving   gRPC wire protocol, task registry, hub router, servers
app       control plane (REST + WS log streaming)
utils     logging etc.
"""

__version__ = "0.1.0"
