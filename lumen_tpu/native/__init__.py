"""ctypes bindings for the native host-ops library (``_src/host_ops.cpp``).

The C core covers the host half of the serving hot loops — letterbox/resize,
NMS, CTC collapse — GIL-free so the ingest pipeline's preprocess workers
scale across cores. Loading policy:

1. use ``native/build/liblumen_host_ops.so`` if present and ABI-compatible;
2. else, if a C++ toolchain is available, build it once (quiet, ~1s);
3. else mark the library unavailable — every caller has a numpy/cv2
   fallback, so the framework stays pure-Python-runnable.

``LUMEN_TPU_NO_NATIVE=1`` skips native entirely (debugging/benchmark A/B).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

logger = logging.getLogger(__name__)

ABI_VERSION = 1

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_PKG_DIR))
# Canonical source ships inside the package (wheels are self-contained);
# the repo-root ``native/`` dir holds the Makefile + dev build output.
_SRC_PATH = os.path.join(_PKG_DIR, "_src", "host_ops.cpp")


def _build_dir() -> str:
    """Prefer the repo checkout's ``native/build`` (dev workflow, shared
    with the Makefile); installed wheels build into a per-user cache since
    site-packages may not be writable."""
    repo_native = os.path.join(_REPO_ROOT, "native")
    if os.path.isdir(repo_native) and os.access(repo_native, os.W_OK):
        return os.path.join(repo_native, "build")
    return os.path.join(
        os.path.expanduser(os.environ.get("XDG_CACHE_HOME", "~/.cache")),
        "lumen-tpu",
        "native",
    )


def _src_digest() -> str:
    """Short content hash of the C++ source: the cached .so is keyed on it
    so a package upgrade whose host_ops.cpp changed (even without an ABI
    bump) rebuilds instead of silently loading the old binary."""
    import hashlib

    try:
        with open(_SRC_PATH, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()[:12]
    except OSError:
        return "nosrc"


_LIB_PATH = os.path.join(
    _build_dir(), f"liblumen_host_ops-{ABI_VERSION}-{_src_digest()}.so"
)
# A `make -C native` prebuild lands at the unkeyed Makefile name; accept it
# as a fallback (the ABI gate in load() still applies) so prebuilding for a
# g++-less runtime keeps working alongside the digest-keyed self-build.
_PREBUILT_PATH = os.path.join(_build_dir(), "liblumen_host_ops.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")


def _build() -> bool:
    src = _SRC_PATH
    if not os.path.exists(src):
        return False
    os.makedirs(os.path.dirname(_LIB_PATH), exist_ok=True)
    # Compile to a per-process temp path and os.replace (atomic on POSIX):
    # concurrent processes racing the first build must never dlopen a
    # half-written .so, and a killed compiler must not leave a corrupt final.
    tmp = f"{_LIB_PATH}.tmp{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-o", tmp, src]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            logger.warning("native host-ops build failed:\n%s", proc.stderr[-2000:])
            return False
        os.replace(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native host-ops build skipped: %s", e)
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.lumen_host_ops_abi_version.restype = ctypes.c_int
    lib.resize_bilinear_u8.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_int, ctypes.c_int,
    ]
    lib.letterbox_u8.argtypes = [
        _u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, _u8p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
    ]
    lib.nms_f32.argtypes = [_f32p, _f32p, ctypes.c_int, ctypes.c_float, _i64p]
    lib.nms_f32.restype = ctypes.c_int
    lib.ctc_collapse_batch.argtypes = [
        _i32p, _f32p, ctypes.c_int, ctypes.c_int, ctypes.c_int, _i32p, _f32p, _i32p,
    ]
    return lib


def load() -> ctypes.CDLL | None:
    """The bound library, building it on first use; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("LUMEN_TPU_NO_NATIVE") == "1":
            return None
        for attempt in range(2):
            for candidate in (_LIB_PATH, _PREBUILT_PATH):
                if not os.path.exists(candidate):
                    continue
                try:
                    lib = _bind(ctypes.CDLL(candidate))
                    if lib.lumen_host_ops_abi_version() == ABI_VERSION:
                        _lib = lib
                        logger.info("native host-ops loaded: %s", candidate)
                        return _lib
                    logger.info("native host-ops ABI mismatch; rebuilding")
                    _unlink_quiet(candidate)
                except (OSError, AttributeError) as e:
                    # Stale/corrupt artifact (OSError: unloadable;
                    # AttributeError: loadable but missing a symbol, e.g.
                    # built from older sources): remove it so the rebuild
                    # below gets a clean slate.
                    logger.warning("native host-ops load failed: %s", e)
                    _unlink_quiet(candidate)
            if attempt == 0 and not _build():
                break
        return None


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def available() -> bool:
    return load() is not None


# -- op wrappers (numpy in, numpy out) --------------------------------------


def resize_bilinear_u8(img: np.ndarray, dh: int, dw: int) -> np.ndarray:
    """[H, W, C] uint8 -> [dh, dw, C] uint8 (bilinear, pixel-center aligned)."""
    lib = load()
    assert lib is not None, "native host-ops unavailable"
    img = np.ascontiguousarray(img, np.uint8)
    h, w, c = img.shape
    out = np.empty((dh, dw, c), np.uint8)
    lib.resize_bilinear_u8(img, h, w, c, out, dh, dw)
    return out


def letterbox_u8(img: np.ndarray, target: int, fill: int = 0) -> tuple[np.ndarray, float, int, int]:
    """Fused aspect-preserving resize + centered pad; mirrors
    ``ops.image.letterbox_numpy``'s return contract."""
    lib = load()
    assert lib is not None, "native host-ops unavailable"
    img = np.ascontiguousarray(img, np.uint8)
    h, w, c = img.shape
    out = np.empty((target, target, c), np.uint8)
    scale = ctypes.c_double()
    pad_top = ctypes.c_int()
    pad_left = ctypes.c_int()
    lib.letterbox_u8(img, h, w, c, out, target, fill,
                     ctypes.byref(scale), ctypes.byref(pad_top), ctypes.byref(pad_left))
    return out, scale.value, pad_top.value, pad_left.value


def nms_f32(boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.4) -> np.ndarray:
    """Greedy IoU NMS; kept indices by descending score (same contract as
    ``ops.nms.nms_numpy``)."""
    lib = load()
    assert lib is not None, "native host-ops unavailable"
    boxes = np.ascontiguousarray(boxes, np.float32)
    scores = np.ascontiguousarray(scores, np.float32)
    n = len(boxes)
    keep = np.empty((n,), np.int64)
    count = lib.nms_f32(boxes, scores, n, iou_threshold, keep)
    return keep[:count]


def ctc_collapse_batch(
    ids: np.ndarray, confs: np.ndarray, blank: int = 0
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """[B, T] ids/confs -> (emitted ids [B, T], confs [B, T], counts [B])."""
    lib = load()
    assert lib is not None, "native host-ops unavailable"
    ids = np.ascontiguousarray(ids, np.int32)
    confs = np.ascontiguousarray(confs, np.float32)
    b, t = ids.shape
    out_ids = np.empty((b, t), np.int32)
    out_confs = np.empty((b, t), np.float32)
    counts = np.empty((b,), np.int32)
    lib.ctc_collapse_batch(ids, confs, b, t, blank, out_ids, out_confs, counts)
    return out_ids, out_confs, counts
