// lumen-tpu native host ops.
//
// The TPU compute path is JAX/XLA; this library covers the host side of the
// serving hot loops — the per-image CV work that runs between gRPC and the
// device call (letterbox/resize, NMS, CTC collapse). The reference delegates
// this to OpenCV/numpy from Python (SURVEY.md §2.2-2.6); here it is a
// self-contained C core invoked through ctypes, GIL-free so the ingest
// pipeline's worker threads scale across cores.
//
// Build: see native/Makefile (g++ -O2 -shared -fPIC). No dependencies.
//
// All image buffers are uint8 HWC, C-contiguous.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Bilinear resize, uint8 HWC. Pixel-center alignment (matches
// cv2.INTER_LINEAR up to rounding):  src = (dst + 0.5) * scale - 0.5
// ---------------------------------------------------------------------------
void resize_bilinear_u8(const uint8_t* src, int sh, int sw, int channels,
                        uint8_t* dst, int dh, int dw) {
  if (sh <= 0 || sw <= 0 || dh <= 0 || dw <= 0 || channels <= 0) return;
  const double scale_y = static_cast<double>(sh) / dh;
  const double scale_x = static_cast<double>(sw) / dw;
  std::vector<int> x0s(dw), x1s(dw);
  std::vector<float> fxs(dw);
  for (int x = 0; x < dw; ++x) {
    double fx = (x + 0.5) * scale_x - 0.5;
    int x0 = static_cast<int>(std::floor(fx));
    float t = static_cast<float>(fx - x0);
    if (x0 < 0) { x0 = 0; t = 0.f; }
    int x1 = x0 + 1;
    if (x1 >= sw) { x1 = sw - 1; t = (x0 >= sw - 1) ? 0.f : t; x0 = std::min(x0, sw - 1); }
    x0s[x] = x0; x1s[x] = x1; fxs[x] = t;
  }
  for (int y = 0; y < dh; ++y) {
    double fy = (y + 0.5) * scale_y - 0.5;
    int y0 = static_cast<int>(std::floor(fy));
    float ty = static_cast<float>(fy - y0);
    if (y0 < 0) { y0 = 0; ty = 0.f; }
    int y1 = y0 + 1;
    if (y1 >= sh) { y1 = sh - 1; ty = (y0 >= sh - 1) ? 0.f : ty; y0 = std::min(y0, sh - 1); }
    const uint8_t* row0 = src + static_cast<size_t>(y0) * sw * channels;
    const uint8_t* row1 = src + static_cast<size_t>(y1) * sw * channels;
    uint8_t* out = dst + static_cast<size_t>(y) * dw * channels;
    for (int x = 0; x < dw; ++x) {
      const int x0 = x0s[x] * channels, x1 = x1s[x] * channels;
      const float tx = fxs[x];
      for (int c = 0; c < channels; ++c) {
        const float top = row0[x0 + c] + tx * (row0[x1 + c] - row0[x0 + c]);
        const float bot = row1[x0 + c] + tx * (row1[x1 + c] - row1[x0 + c]);
        const float v = top + ty * (bot - top);
        out[x * channels + c] = static_cast<uint8_t>(std::lround(std::min(255.f, std::max(0.f, v))));
      }
    }
  }
}

// ---------------------------------------------------------------------------
// Fused letterbox: aspect-preserving resize into a target x target canvas
// with centered padding, one pass, no intermediate buffer. Geometry matches
// lumen_tpu.ops.image.letterbox_params. Returns scale/pads via out-params.
// ---------------------------------------------------------------------------
void letterbox_u8(const uint8_t* src, int sh, int sw, int channels,
                  uint8_t* dst, int target, int fill,
                  double* out_scale, int* out_pad_top, int* out_pad_left) {
  const double scale = std::min(static_cast<double>(target) / sh,
                                static_cast<double>(target) / sw);
  // nearbyint (round-half-even under the default FP environment) matches
  // Python's round() in letterbox_params; lround's half-away-from-zero
  // would shift content by one row on exact .5 products.
  const int new_h = static_cast<int>(std::nearbyint(sh * scale));
  const int new_w = static_cast<int>(std::nearbyint(sw * scale));
  const int pad_top = (target - new_h) / 2;
  const int pad_left = (target - new_w) / 2;
  std::memset(dst, fill, static_cast<size_t>(target) * target * channels);
  std::vector<uint8_t> resized(static_cast<size_t>(new_h) * new_w * channels);
  resize_bilinear_u8(src, sh, sw, channels, resized.data(), new_h, new_w);
  for (int y = 0; y < new_h; ++y) {
    std::memcpy(dst + (static_cast<size_t>(pad_top + y) * target + pad_left) * channels,
                resized.data() + static_cast<size_t>(y) * new_w * channels,
                static_cast<size_t>(new_w) * channels);
  }
  if (out_scale) *out_scale = scale;
  if (out_pad_top) *out_pad_top = pad_top;
  if (out_pad_left) *out_pad_left = pad_left;
}

// ---------------------------------------------------------------------------
// Greedy IoU NMS. boxes: [n,4] float32 x1y1x2y2. Writes kept original
// indices (descending score) to out_keep; returns kept count. Semantics
// match lumen_tpu.ops.nms.nms_numpy (IoU > threshold suppressed,
// denominator clamped at 1e-9).
// ---------------------------------------------------------------------------
int nms_f32(const float* boxes, const float* scores, int n,
            float iou_threshold, int64_t* out_keep) {
  if (n <= 0) return 0;
  std::vector<int> order(n);
  for (int i = 0; i < n; ++i) order[i] = i;
  // Tie-break on HIGHER index first: numpy's argsort()[::-1] (the fallback
  // in ops/nms.py) reverses a stable ascending sort, so equal scores come
  // out in descending index order — match it exactly.
  std::sort(order.begin(), order.end(), [&](int a, int b) {
    return scores[a] != scores[b] ? scores[a] > scores[b] : a > b;
  });
  std::vector<float> areas(n);
  for (int i = 0; i < n; ++i) {
    const float* b = boxes + 4 * i;
    areas[i] = std::max(b[2] - b[0], 0.f) * std::max(b[3] - b[1], 0.f);
  }
  std::vector<char> removed(n, 0);
  int kept = 0;
  for (int oi = 0; oi < n; ++oi) {
    const int i = order[oi];
    if (removed[i]) continue;
    out_keep[kept++] = i;
    const float* bi = boxes + 4 * i;
    for (int oj = oi + 1; oj < n; ++oj) {
      const int j = order[oj];
      if (removed[j]) continue;
      const float* bj = boxes + 4 * j;
      const float xx1 = std::max(bi[0], bj[0]);
      const float yy1 = std::max(bi[1], bj[1]);
      const float xx2 = std::min(bi[2], bj[2]);
      const float yy2 = std::min(bi[3], bj[3]);
      const float inter = std::max(xx2 - xx1, 0.f) * std::max(yy2 - yy1, 0.f);
      const float denom = std::max(areas[i] + areas[j] - inter, 1e-9f);
      if (inter / denom > iou_threshold) removed[j] = 1;
    }
  }
  return kept;
}

// ---------------------------------------------------------------------------
// CTC greedy collapse for a batch: drop repeats, drop blanks. For each
// sequence, writes emitted symbol ids and their confidences; returns counts.
// ids: [batch, t] int32; confs: [batch, t] float32.
// out_ids/out_confs: [batch, t]; out_counts: [batch].
// Semantics match lumen_tpu.ops.ctc.ctc_collapse (emit when id != blank and
// id != previous id; confidence of the emitting timestep).
// ---------------------------------------------------------------------------
void ctc_collapse_batch(const int32_t* ids, const float* confs, int batch,
                        int t, int32_t blank, int32_t* out_ids,
                        float* out_confs, int32_t* out_counts) {
  for (int b = 0; b < batch; ++b) {
    const int32_t* seq = ids + static_cast<size_t>(b) * t;
    const float* conf = confs + static_cast<size_t>(b) * t;
    int32_t* oid = out_ids + static_cast<size_t>(b) * t;
    float* oconf = out_confs + static_cast<size_t>(b) * t;
    int count = 0;
    int32_t prev = -1;
    for (int step = 0; step < t; ++step) {
      const int32_t id = seq[step];
      if (id != blank && id != prev) {
        oid[count] = id;
        oconf[count] = conf[step];
        ++count;
      }
      prev = id;
    }
    out_counts[b] = count;
  }
}

// Version tag so the loader can detect stale builds.
int lumen_host_ops_abi_version() { return 1; }

}  // extern "C"
