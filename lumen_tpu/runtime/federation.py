"""Fleet federation: consistent-hash peer routing + cross-host cache tier.

Everything below this module serves from ONE process on one host's chips —
the actual ceiling for the ROADMAP's "millions of users" north star. This
module is the first subsystem where the *process boundary* is the unit of
scale: a set of lumen-tpu servers (peers) becomes one fleet, glued by
three ideas that all reuse machinery the stack already has, one level up:

- **consistent-hash ring keyed by the content address.** The result cache
  already addresses work by ``sha256(payload bytes)`` — that digest is
  network-portable by construction, so hashing it onto a ring of peers
  gives *cache affinity for free*: identical payloads always land on the
  same peer, whose RAM/disk tiers therefore concentrate the hits. The
  ring uses virtual nodes (64 per peer) so 3 peers split the keyspace
  within a few percent, and membership changes move only the
  departed/arrived peer's arcs (the classic consistent-hashing property —
  tested by ``tests/test_federation_props.py``).

- **per-peer health, breaker-style, one level up.** Each peer carries the
  same failure-streak → eject → background-probe → readmit lifecycle a
  :class:`~lumen_tpu.serving.breaker.CircuitBreaker` gives one service and
  a :class:`~lumen_tpu.runtime.fleet.ReplicaSet` gives one replica:
  in-band forward failures and Health-poll failures feed one streak
  (``LUMEN_FED_FAILURES``), an ejected peer's ring segment spills to its
  successors, and a background probe (``LUMEN_FED_POLL_S`` cadence, after
  ``LUMEN_FED_EJECT_S``) readmits it. Ejection records a ``fed_peer_down``
  flight-recorder event that captures an incident bundle; readmission
  records ``fed_peer_readmit``.

- **a peer-cache lookup protocol.** Before computing a missed request, a
  non-owner peer asks the ring owner's cache over the unchanged gRPC
  protocol (the reserved ``fed_cache_lookup`` task answered by the hub
  router, O(1) on the owner, before any admission accounting).
  Owner-side single-flight extends across the tier: the lookup can wait
  (``wait_ms``) on the owner's in-flight computation instead of
  duplicating it. Dedupe is **owner-anchored** (lookup-only, no
  write-back): traffic routed through a front tier always lands on the
  owner first, so a duplicate payload costs device work exactly once
  fleet-wide there (the bench-asserted guarantee); a result computed AT
  a non-owner (direct traffic that bypassed the front) stays in that
  host's local cache, so worst case is one compute per first-touch side.

A server with ``LUMEN_FED_PEERS`` **unset boots byte-identical to the
single-host path**: :func:`maybe_federation` returns ``None``, no thread
starts, no gauge registers, and the per-request serving path gains only a
task-name compare (tier-1 guard in ``tests/test_federation.py``).

Deliberately jax-free (like :mod:`~lumen_tpu.runtime.result_cache`): pure
host plumbing over gRPC, usable by a front tier that owns no models.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import logging
import pickle
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Any, Callable

import grpc
from google.protobuf import empty_pb2

from ..utils import telemetry
from ..utils.deadline import remaining
from ..utils.env import env_float, env_int, env_list
from ..utils.metrics import metrics
from ..utils.trace import current_trace

logger = logging.getLogger(__name__)

PEERS_ENV = "LUMEN_FED_PEERS"
SELF_ENV = "LUMEN_FED_SELF"
DISCOVER_ENV = "LUMEN_FED_DISCOVER"

# The reserved cache-lookup task name and the owner-side wait clamp live
# with their server half in the jax-free router (this module cannot be
# imported there); re-exported so federation callers have one local name
# for the protocol.
from ..serving.router import (  # noqa: E402,F401
    FED_CACHE_MAX_WAIT_S,
    FED_CACHE_TASK,
    FED_CAPACITY_ENV,
    FED_CAPACITY_META,
    FED_KV_PUT_TASK,
    FED_ROLE_META,
    ROLE_ENV,
    advertised_fed_role,
    capacity_gossip_enabled,
)

#: per-peer virtual nodes on the ring — enough that 3 peers split the
#: keyspace within a few percent, cheap enough that membership changes
#: rebuild in microseconds.
VNODES = 64

SERVING = "serving"
EJECTED = "ejected"
_STATE_CODES = {SERVING: 0, EJECTED: 2}

#: disaggregation lanes + their codes for the numeric-only gauges
#: registry (``federation:{peer}`` → ``fed_role``).
ROLE_BOTH, ROLE_PREFILL, ROLE_DECODE = "both", "prefill", "decode"
_ROLE_CODES = {ROLE_BOTH: 0, ROLE_PREFILL: 1, ROLE_DECODE: 2}

#: tasks the disaggregation planner splits across lanes — generation is
#: the only protocol task with a prefill/decode phase boundary to cut at.
DISAGG_TASKS = ("vlm_generate", "vlm_generate_stream")

#: wire chunk size for a migration commit's page payload (under the
#: 64 MB gRPC message cap with protobuf headroom).
_KV_CHUNK_BYTES = 48 * 1024 * 1024

#: process-wide KV-migration counters — both wire halves call in via
#: :func:`note_migration` (lock-free int += like ``Peer.stats``),
#: surfaced in ``export_status()["kv_migration"]`` and the client
#: ``peers`` subcommand's duty-split line.
MIGRATION = {
    "puts": 0,            # commit legs that retired on the decode peer
    "put_bytes": 0,       # payload bytes shipped out on the wire
    "put_failures": 0,    # outbound attempts that fell back to the local ladder
    "ref_pages": 0,       # pages resolved by content-hash reference, not bytes
    "lane_busy": 0,       # dispatches refused: all migration lanes in flight
    "in_commits": 0,      # rows this host admitted from a prefill peer
    "in_bytes": 0,        # payload bytes received on the wire
    "in_rejected": 0,     # inbound commits this host refused (typed, in-band)
}


def note_migration(**deltas: int) -> None:
    for key, delta in deltas.items():
        MIGRATION[key] = MIGRATION.get(key, 0) + int(delta)



def fed_hops() -> int:
    """``LUMEN_FED_HOPS``: forward attempts per request through the front
    tier (first ring owner + failover successors; default 3)."""
    return env_int("LUMEN_FED_HOPS", 3, minimum=1)


def fed_failures() -> int:
    """``LUMEN_FED_FAILURES``: consecutive transport/poll failures that
    eject a peer from the ring (default 3)."""
    return env_int("LUMEN_FED_FAILURES", 3, minimum=1)


def fed_eject_s() -> float:
    """``LUMEN_FED_EJECT_S``: how long an ejected peer sheds ring traffic
    before the background probe may readmit it (default 5s)."""
    return env_float("LUMEN_FED_EJECT_S", 5.0, minimum=0.1)


def fed_poll_s() -> float:
    """``LUMEN_FED_POLL_S``: health-poll cadence over the peer set
    (default 2s; each tick Health-probes every non-ejected peer and any
    ejected peer whose eject window elapsed)."""
    return env_float("LUMEN_FED_POLL_S", 2.0, minimum=0.1)


def fed_lookup_timeout_s() -> float:
    """``LUMEN_FED_LOOKUP_TIMEOUT_S``: RPC deadline for one peer-cache
    lookup (default 2s) — a lookup must always be much cheaper than the
    device work it tries to avoid."""
    return env_float("LUMEN_FED_LOOKUP_TIMEOUT_S", 2.0, minimum=0.05)


def fed_lookup_wait_ms() -> int:
    """``LUMEN_FED_LOOKUP_WAIT_MS``: how long the OWNER may hold a cache
    lookup on its in-flight computation of the same key (default 10000) —
    this is what extends single-flight coalescing across the tier. 0
    disables the wait (pure cache peek)."""
    return env_int("LUMEN_FED_LOOKUP_WAIT_MS", 10000, minimum=0)


def fed_forward_timeout_s() -> float:
    """``LUMEN_FED_FORWARD_TIMEOUT_S``: front-tier forward deadline per
    hop when the client set none (default 300s, the client default)."""
    return env_float("LUMEN_FED_FORWARD_TIMEOUT_S", 300.0, minimum=1.0)


def fed_role() -> str:
    """``LUMEN_FED_ROLE``: this host's lane in a disaggregated fleet —
    ``prefill`` (serve prompt prefill + vision encode, migrate the decode
    out), ``decode`` (accept migrated rows), or ``both`` (the default AND
    the byte-identical unconfigured state: nothing advertised, no routing
    change anywhere)."""
    return advertised_fed_role() or ROLE_BOTH


def fed_kv_timeout_s() -> float:
    """``LUMEN_FED_KV_TIMEOUT_S``: end-to-end deadline for one migration
    commit (default 300s). It covers the decode host's ENTIRE remaining
    decode, not just the page transfer — the token stream rides the same
    RPC back."""
    return env_float("LUMEN_FED_KV_TIMEOUT_S", 300.0, minimum=1.0)


def fed_kv_lanes() -> int:
    """``LUMEN_FED_KV_LANES``: concurrent migration dispatches in flight
    per prefill host (default 4). Over budget, rows decode locally
    instead of queueing — migration is an optimization, never a wait."""
    return env_int("LUMEN_FED_KV_LANES", 4, minimum=1)


def fed_capacity_hyst() -> float:
    """``LUMEN_FED_CAPACITY_HYST``: minimum per-peer weight delta before
    a capacity report may rebuild the ring (default 0.1) — sub-threshold
    duty jitter must not move arcs at all."""
    return env_float("LUMEN_FED_CAPACITY_HYST", 0.1, minimum=0.0, maximum=1.0)


def fed_capacity_remap_s() -> float:
    """``LUMEN_FED_CAPACITY_REMAP_S``: minimum seconds between two
    capacity-driven ring rebuilds (default 10) — the remap-rate cap that
    keeps a noisy fleet from thrashing arc ownership. A drain flip
    bypasses it: handing off a planned drain is exactly the case where
    waiting means discovering it by error."""
    return env_float("LUMEN_FED_CAPACITY_REMAP_S", 10.0, minimum=0.0)


def fed_capacity_stale_polls() -> int:
    """``LUMEN_FED_CAPACITY_STALE_POLLS``: consecutive polls without a
    capacity report before a peer's last report decays to neutral weight
    (default 3) — a silent sidecar must not keep its last headroom claim
    forever."""
    return env_int("LUMEN_FED_CAPACITY_STALE_POLLS", 3, minimum=1)


#: weight floor for a loaded-but-alive peer: ~3 vnodes of 64, so a fully
#: busy host sheds most arcs yet stays reachable. Only a DRAINING peer
#: goes to exactly 0 (no arcs at all).
MIN_CAPACITY_WEIGHT = 0.05

#: hot result-cache keys a draining peer advertises (and the front
#: prefetches onto ring successors) per drain handoff.
FED_HANDOFF_KEYS = 8


# ---------------------------------------------------------------------------
# Consistent-hash ring
# ---------------------------------------------------------------------------


class HashRing:
    """Consistent-hash ring over peer names, keyed by sha256 hex digests.

    Positions are the first 8 bytes of ``sha256(f"{name}#{vnode}")``; a
    key (a sha256 hexdigest — the result cache's content address) maps to
    the first vnode clockwise from ``int(key[:16], 16)``. Deterministic
    across processes and insertion orders by construction — the front
    tier and every peer build the SAME ring from the same peer list, so
    ownership agrees fleet-wide with zero coordination.

    ``weights`` (capacity gossip) scale a peer's vnode COUNT: weight
    ``w`` keeps ``round(vnodes * w)`` of its points, clamped to
    ``[0, vnodes]``; an omitted name keeps all of them. Because a peer's
    vnodes are the prefix ``name#0..#(k-1)``, changing one peer's weight
    only adds/removes that peer's own points — the minimal-remap
    property survives weighting (property-tested). Weight 0 removes the
    peer from the ring entirely (a draining host owns no arcs).
    """

    def __init__(
        self,
        names: list[str],
        vnodes: int = VNODES,
        weights: dict[str, float] | None = None,
    ):
        self.names = sorted(set(names))
        self.vnodes = vnodes
        self.weights = dict(weights) if weights else {}
        points: list[tuple[int, str]] = []
        for name in self.names:
            w = self.weights.get(name)
            count = (
                vnodes if w is None
                else max(0, min(vnodes, round(vnodes * w)))
            )
            for i in range(count):
                digest = hashlib.sha256(f"{name}#{i}".encode()).digest()
                points.append((int.from_bytes(digest[:8], "big"), name))
        points.sort()
        self._points = points
        self._positions = [p for p, _ in points]

    @staticmethod
    def key_position(key_hex: str) -> int:
        """Ring position of a content address (sha256 hexdigest or any
        hex string; shorter strings are zero-extended)."""
        return int((key_hex[:16] or "0").ljust(16, "0"), 16)

    def owners(self, key_hex: str, n: int = 1, skip: frozenset | set = frozenset()) -> list[str]:
        """Up to ``n`` DISTINCT peer names in preference order (the ring
        owner first, then clockwise successors), skipping names in
        ``skip`` — an ejected peer's arc spills to its successors."""
        if not self._points or n <= 0:
            return []
        out: list[str] = []
        start = bisect.bisect_right(self._positions, self.key_position(key_hex))
        total = len(self._points)
        for step in range(total):
            name = self._points[(start + step) % total][1]
            if name in skip or name in out:
                continue
            out.append(name)
            if len(out) >= n:
                break
        return out

    def owner(self, key_hex: str, skip: frozenset | set = frozenset()) -> str | None:
        owners = self.owners(key_hex, 1, skip)
        return owners[0] if owners else None

    def shares(self) -> dict[str, float]:
        """Fraction of the keyspace each peer owns (arc-length exact,
        not sampled) — the ``ring_share`` gauge and the ``peers``
        subcommand's ownership column."""
        if not self._points:
            return {}
        out = {name: 0 for name in self.names}
        span = 1 << 64
        prev = self._points[-1][0] - span  # wrap: last point opens the first arc
        for pos, name in self._points:
            out[name] += pos - prev
            prev = pos
        return {name: width / span for name, width in out.items()}


# ---------------------------------------------------------------------------
# Peer set
# ---------------------------------------------------------------------------


@dataclass
class PeerSpec:
    """One configured peer: gRPC address plus an optional observability
    sidecar. Spelled ``host:port`` or ``host:port@sidecar`` in
    ``LUMEN_FED_PEERS``, where ``sidecar`` is a bare port (same host) or
    its own ``host:port``."""

    addr: str
    sidecar: str | None = None

    @property
    def name(self) -> str:
        return self.addr


def parse_peer_spec(entry: str) -> PeerSpec | None:
    entry = entry.strip()
    if not entry:
        return None
    addr, _, sidecar = entry.partition("@")
    addr = addr.strip()
    if ":" not in addr:
        logger.warning("malformed %s entry %r (need host:port); ignored", PEERS_ENV, entry)
        return None
    sidecar = sidecar.strip() or None
    if sidecar and ":" not in sidecar:
        sidecar = f"{addr.rsplit(':', 1)[0]}:{sidecar}"
    return PeerSpec(addr=addr, sidecar=sidecar)


def parse_peer_specs() -> list[PeerSpec]:
    """The resolved static peer set from ``LUMEN_FED_PEERS`` (empty when
    unset — federation stays entirely off)."""
    specs = [parse_peer_spec(e) for e in env_list(PEERS_ENV)]
    out: list[PeerSpec] = []
    seen: set[str] = set()
    for spec in specs:
        if spec is not None and spec.addr not in seen:
            seen.add(spec.addr)
            out.append(spec)
    return out


class Peer:
    """Live state for one peer: lazy channel/stub, breaker-style health,
    and dispatch accounting surfaced as ``federation:{addr}`` gauges."""

    def __init__(self, spec: PeerSpec, stub_factory: Callable[[str], Any]):
        self.spec = spec
        self.name = spec.name
        self._stub_factory = stub_factory
        self._stub = None
        self._stub_lock = threading.Lock()
        self.state = SERVING
        self.streak = 0
        self.ejected_at = 0.0
        self.last_ok = 0.0
        self.last_error = ""
        self.slo: dict = {}
        # Disaggregation lane, learned passively from the peer's Health
        # trailing metadata; "both" until (unless) the peer advertises.
        self.role = ROLE_BOTH
        # Capacity gossip (duty / burn_5m / draining / hot keys), learned
        # the same way; {} until the peer reports, and decayed back to {}
        # (= neutral weight) after LUMEN_FED_CAPACITY_STALE_POLLS silent
        # polls.
        self.capacity: dict = {}
        self.weight = 1.0
        self.missed_capacity = 0
        self._stale_warned = False
        # Incremented lock-free from handler threads: int += is fine for
        # telemetry (same convention as ResultCache.stats) — health
        # decisions never read these, only streak/state, which ARE
        # taken under the manager lock.
        self.stats = {
            "dispatches": 0,
            "failovers": 0,
            "sheds": 0,
            "failures": 0,
            "cache_hits": 0,
            "cache_misses": 0,
        }

    @property
    def stub(self):
        if self._stub is None:
            with self._stub_lock:
                if self._stub is None:
                    self._stub = self._stub_factory(self.spec.addr)
        return self._stub

    def close(self) -> None:
        stub = self._stub
        self._stub = None
        chan = getattr(stub, "_lumen_channel", None)
        if chan is not None:
            try:
                chan.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass


def _default_stub_factory(addr: str):
    from ..serving.proto.ml_service_pb2_grpc import InferenceStub

    channel = grpc.insecure_channel(
        addr,
        options=[
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
        ],
    )
    stub = InferenceStub(channel)
    stub._lumen_channel = channel  # teardown handle (Peer.close)
    return stub


# ---------------------------------------------------------------------------
# Federation manager
# ---------------------------------------------------------------------------


class FederationManager:
    """The fleet view one server holds: the ring, per-peer health, the
    background poller, and the peer-cache lookup client.

    Two roles share this one class:

    - a **front tier** (no local models) uses :meth:`plan` +
      :meth:`record_*` from the routing loop in
      :class:`~lumen_tpu.serving.router.FederationRouter`;
    - a **peer-aware backend** (``LUMEN_FED_SELF`` set) installs
      :meth:`peer_cache_lookup` as the result cache's pre-compute hook so
      its misses consult the ring owner's cache first.
    """

    def __init__(
        self,
        specs: list[PeerSpec],
        self_name: str | None = None,
        stub_factory: Callable[[str], Any] | None = None,
        hops: int | None = None,
        failures: int | None = None,
        eject_s: float | None = None,
        poll_s: float | None = None,
    ):
        if not specs:
            raise ValueError("federation needs at least one peer")
        factory = stub_factory or _default_stub_factory
        self.peers: dict[str, Peer] = {s.name: Peer(s, factory) for s in specs}
        self.self_name = self_name or None
        # A self that matches no listed peer is NOT benign for lookups:
        # the ring still owns arcs under this host's LISTED name, so the
        # `owner == self` guard would fail and every owned-key miss
        # would RPC this host's own address and ride its own unresolved
        # flight until the wait times out (~10s/unique payload). The
        # server only installs the cache hook when `self_listed`.
        self.self_listed = self.self_name in self.peers
        if self.self_name and not self.self_listed:
            logger.warning(
                "%s=%r matches no %s entry %s — peer-cache lookups are "
                "DISABLED on this host (spell self exactly as it appears "
                "in the peer list)",
                SELF_ENV, self.self_name, PEERS_ENV, sorted(self.peers),
            )
        self.ring = HashRing(list(self.peers))
        self.hops = fed_hops() if hops is None else max(1, hops)
        self.failures = fed_failures() if failures is None else max(1, failures)
        self.eject_s = fed_eject_s() if eject_s is None else max(0.1, eject_s)
        self.poll_s = fed_poll_s() if poll_s is None else max(0.1, poll_s)
        self.lookup_timeout_s = fed_lookup_timeout_s()
        self.lookup_wait_ms = fed_lookup_wait_ms()
        self.forward_timeout_s = fed_forward_timeout_s()
        self.kv_timeout_s = fed_kv_timeout_s()
        self._kv_lanes = threading.BoundedSemaphore(fed_kv_lanes())
        self._role_warned = False
        if self.self_listed:
            # Our own lane comes from the env, not from probing ourselves
            # (the poll loop skips self on purpose).
            self.peers[self.self_name].role = fed_role()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # Capacity-weighted ring state. Latched at build like the other
        # knobs: with the gossip knob unset nothing below ever runs and
        # the ring stays the equal-weight one built above.
        self._capacity_on = capacity_gossip_enabled()
        self.capacity_hyst = fed_capacity_hyst()
        self.capacity_remap_s = fed_capacity_remap_s()
        self.capacity_stale_polls = fed_capacity_stale_polls()
        self._last_remap = -float("inf")
        # Shares are cached per ring BUILD (weight changes rebuild), so
        # the gauges below read live ownership, not the boot snapshot.
        self._shares = self.ring.shares()
        ref = weakref.ref(self)
        for name, peer in self.peers.items():

            def _gauges(p=peer, name=name) -> dict:
                m = ref()
                if m is None:
                    return {}
                out = {
                    **p.stats,
                    "state": _STATE_CODES[p.state],
                    "streak": p.streak,
                    "ring_share": round(m._shares.get(name, 0.0), 4),
                    "fed_role": _ROLE_CODES.get(p.role, 0),
                }
                if m._capacity_on:
                    out["weight"] = round(p.weight, 4)
                    out["draining"] = 1 if p.capacity.get("draining") else 0
                return out

            peer._gauge_fn = _gauges
            metrics.register_gauges(f"federation:{name}", _gauges)

    # -- routing -----------------------------------------------------------

    def _ejected_names(self) -> set[str]:
        with self._lock:
            return {n for n, p in self.peers.items() if p.state == EJECTED}

    def plan(self, digest_hex: str) -> list[Peer]:
        """Forward attempts for one content address, in preference order:
        live ring owner first, then live successors, up to the hop
        budget. With every peer ejected the raw owner order is returned
        anyway — trying a possibly-dead peer beats refusing outright (it
        doubles as the dispatch-path probe)."""
        ejected = self._ejected_names()
        names = self.ring.owners(digest_hex, self.hops, skip=ejected)
        if not names:
            names = self.ring.owners(digest_hex, self.hops)
        return [self.peers[n] for n in names]

    def owner_of(self, digest_hex: str) -> Peer | None:
        name = self.ring.owner(digest_hex, skip=self._ejected_names())
        return self.peers.get(name) if name else None

    def disagg_plan(
        self, task: str, plan: list[Peer]
    ) -> tuple[list[Peer], str | None]:
        """Role-aware rewrite of a forward plan. For generation tasks in
        a fleet with configured lanes: prefill-capable peers lead (the
        forward target runs vision encode + prefill) and the first
        decode-capable peer in RING ORDER is named the row's decode OWNER
        — the prefill host migrates the row's KV there after prefill.
        Identity ``(plan, None)`` whenever roles are unconfigured, the
        task has no phase boundary, only one peer is live, or a lane is
        missing entirely (unservable — warned once, routing stays
        role-blind rather than refusing). Owner is also None when the
        chosen forward peer IS the owner: colocated, no migration."""
        if task not in DISAGG_TASKS or len(plan) < 2:
            return plan, None
        roles = {p.name: p.role for p in plan}
        if all(r == ROLE_BOTH for r in roles.values()):
            return plan, None
        prefill = [p for p in plan if roles[p.name] != ROLE_DECODE]
        decode = [p for p in plan if roles[p.name] != ROLE_PREFILL]
        if not prefill or not decode:
            self._warn_unservable()
            return plan, None
        ordered = prefill + [p for p in plan if roles[p.name] == ROLE_DECODE]
        owner = decode[0].name
        if ordered[0].name == owner:
            return ordered, None
        return ordered, owner

    # -- health accounting (breaker semantics one level up) ----------------

    def record_dispatch(self, peer: Peer, failover: bool = False) -> None:
        peer.stats["dispatches"] += 1
        metrics.count("fed_dispatches")
        if failover:
            peer.stats["failovers"] += 1
            metrics.count("fed_failovers")

    def record_success(self, peer: Peer) -> None:
        with self._lock:
            peer.streak = 0
            peer.last_ok = time.monotonic()
            readmitted = peer.state == EJECTED
            if readmitted:
                peer.state = SERVING
        if readmitted:
            self._announce_readmit(peer, "dispatch succeeded")

    def record_shed(self, peer: Peer) -> None:
        """An in-band UNAVAILABLE answer (quota/queue/breaker/drain shed):
        the peer is ALIVE and talking — overload is not a health verdict
        (the same neutrality rule the service breaker applies), so the
        streak is untouched; the request just spills to a successor."""
        peer.stats["sheds"] += 1
        metrics.count("fed_sheds")

    def record_unreachable(self, peer: Peer, exc: BaseException, what: str) -> bool:
        """The ONE filter between an RPC exception and the ejection
        streak, shared by every dispatch surface (forward, caps, cache
        lookup): only a transport-unreachable verdict (UNAVAILABLE, or a
        non-gRPC error from a broken stub) counts. DEADLINE_EXCEEDED and
        CANCELLED describe the CALLER's budget or patience — ejecting a
        busy healthy peer for them is the one thing peer health must
        never do. Returns True when the failure was recorded."""
        code = (
            exc.code()
            if isinstance(exc, grpc.RpcError) and callable(getattr(exc, "code", None))
            else None
        )
        if code is None or code == grpc.StatusCode.UNAVAILABLE:
            self.record_failure(peer, f"{what}: {type(exc).__name__}: {code or exc}")
            return True
        return False

    def record_failure(self, peer: Peer, reason: str) -> None:
        """A transport-level forward/poll failure — the peer may be gone.
        ``LUMEN_FED_FAILURES`` consecutive ones eject it from the ring."""
        peer.stats["failures"] += 1
        peer.last_error = reason[:200]
        with self._lock:
            peer.streak += 1
            eject = peer.state == SERVING and peer.streak >= self.failures
            if eject:
                peer.state = EJECTED
                peer.ejected_at = time.monotonic()
        if eject:
            metrics.count("fed_peer_down")
            logger.error(
                "federation peer %s EJECTED after %d consecutive failures "
                "(%s); ring segment spills to successors, probe in %.1fs",
                peer.name, peer.streak, reason, self.eject_s,
            )
            # Incident-grade: fed_peer_down is in telemetry.INCIDENT_KINDS,
            # so this captures a flight-recorder bundle (events + traces +
            # device memory) exactly like a breaker-open or replica-down.
            telemetry.record_event(
                "fed_peer_down", peer.name,
                f"peer ejected after {self.failures} consecutive failures: "
                f"{reason}",
                streak=peer.streak,
            )

    def _announce_readmit(self, peer: Peer, how: str) -> None:
        metrics.count("fed_peer_readmits")
        logger.info("federation peer %s readmitted (%s)", peer.name, how)
        telemetry.record_event(
            "fed_peer_readmit", peer.name, f"peer readmitted: {how}"
        )

    # -- disaggregation role coverage --------------------------------------

    def _check_role_coverage(self) -> None:
        """An all-prefill or all-decode fleet can never FINISH a
        generation (no decode lane to own rows / no prefill lane to admit
        prompts). Roles are advisory — routing silently falls back to
        role-blind order — but a misconfigured fleet must say so LOUDLY,
        once, instead of quietly serving degraded forever."""
        roles = [p.role for p in self.peers.values()]
        if all(r == ROLE_BOTH for r in roles):
            return
        has_prefill = any(r in (ROLE_PREFILL, ROLE_BOTH) for r in roles)
        has_decode = any(r in (ROLE_DECODE, ROLE_BOTH) for r in roles)
        if not (has_prefill and has_decode):
            self._warn_unservable()

    def _warn_unservable(self) -> None:
        if self._role_warned:
            return
        self._role_warned = True
        roles = {n: p.role for n, p in sorted(self.peers.items())}
        missing = ROLE_DECODE if ROLE_PREFILL in roles.values() else ROLE_PREFILL
        logger.error(
            "federation role set is UNSERVABLE: %s — no %s-capable peer; "
            "role-aware routing is DISABLED (every peer treated as 'both') "
            "until %s on at least one host provides the missing lane",
            roles, missing, ROLE_ENV,
        )
        telemetry.record_event(
            "fed_roles_unservable", "federation",
            f"no {missing}-capable peer among {sorted(roles)}; "
            "role routing disabled, serving role-blind",
        )

    # -- KV page migration (disaggregated prefill/decode) ------------------

    def kv_migrate(self, scheduler, req, rec, manifest: list, target: str) -> None:
        """Migration dispatcher — installed as ``ContinuousScheduler.
        migrator`` on peer-aware backends. Validates the target and the
        lane budget SYNCHRONOUSLY (raising hands the row straight back to
        the scheduler's local degradation ladder, nothing half-done),
        then runs the wire legs on a short-lived daemon thread so the
        scheduler loop never blocks on the network."""
        peer = self.peers.get(target)
        if peer is None:
            raise RuntimeError(f"migration target {target!r} is not a peer")
        if self.self_listed and target == self.self_name:
            raise RuntimeError("migration target is this host (colocated row)")
        with self._lock:
            state = peer.state
        if state == EJECTED:
            raise RuntimeError(f"migration target {target} is ejected")
        if not self._kv_lanes.acquire(blocking=False):
            note_migration(lane_busy=1)
            metrics.count("fed_kv_lane_busy")
            raise RuntimeError("all KV migration lanes are in flight")
        threading.Thread(
            target=self._kv_migrate_run,
            args=(scheduler, req, rec, list(manifest), peer),
            name="fed-kv-migrate",
            daemon=True,
        ).start()

    def _kv_migrate_run(self, scheduler, req, rec, manifest, peer) -> None:
        ok = False
        try:
            ok = self._kv_migrate_legs(scheduler, req, rec, manifest, peer)
        except Exception as e:  # noqa: BLE001 - any crash -> the local ladder
            logger.warning(
                "KV migration to %s died (%s: %s); resuming locally",
                peer.name, type(e).__name__, e,
            )
        finally:
            self._kv_lanes.release()
        if not ok:
            note_migration(put_failures=1)
            metrics.count("fed_kv_put_failures")
            # rec.arrays still holds the full pre-slice snapshot
            # (slice_pages copies the list), so the local resume replays
            # the exact state the wire failed to deliver.
            scheduler.resubmit_spilled(req, rec)

    def _kv_migrate_legs(self, scheduler, req, rec, manifest, peer) -> bool:
        tr = getattr(req, "trace", None)
        span = (
            tr.begin("fed.kv_migrate", {"peer": peer.name, "pages": str(rec.n_pages)})
            if tr is not None
            else None
        )
        h = self._kv_offer(peer, manifest, rec) if manifest else 0
        status = self._kv_commit(scheduler, req, rec, manifest, peer, h)
        if status == "chunks_missing" and h > 0 and not getattr(req, "delivered", 0):
            # Offer/commit race: the promised prefix chunks were evicted
            # between the legs. One retry shipping full page contents —
            # safe only while no token has streamed to the client.
            status = self._kv_commit(scheduler, req, rec, manifest, peer, 0)
        if span is not None:
            span.end(ok="1" if status == "done" else "0", ref_pages=str(h))
        return status == "done"

    def _kv_offer(self, peer: Peer, manifest: list, rec) -> int:
        """Offer leg: ship the prompt's chain-key manifest, learn how
        many LEADING pages the decode host's prefix cache already holds —
        those migrate as references, only the missed suffix rides the
        commit. Advisory and best-effort: any failure means "ship
        everything" (0), never a migration failure."""
        from ..models.vlm import migration
        from ..serving.proto import ml_service_pb2 as pb

        try:
            resps = list(peer.stub.Infer(iter([pb.InferRequest(
                correlation_id="fedkv-offer",
                task=FED_KV_PUT_TASK,
                meta={"op": "offer", "manifest": migration.manifest_csv(manifest)},
            )]), timeout=self.lookup_timeout_s))
        except Exception as e:  # noqa: BLE001 - a failed offer ships bytes
            self.record_unreachable(peer, e, "kv offer")
            return 0
        last = resps[-1] if resps else None
        if last is None or last.HasField("error") or last.meta.get("fed_kv") != "ok":
            return 0
        try:
            hit = int(last.meta.get("hit", "0"))
        except ValueError:
            return 0
        # At least one page must ride the wire (the row's live tail page
        # is never content-addressable), and never claim more than the
        # manifest covers.
        return max(0, min(hit, rec.n_pages - 1, len(manifest)))

    def _kv_commit(self, scheduler, req, rec, manifest, peer: Peer, h: int) -> str:
        """Commit leg: slice off the ``h`` offered pages, pack the rest +
        decode state into chunked bundle frames, stream the decode host's
        tokens back into the request, and retire it on the done frame.
        Returns ``"done"``, ``"chunks_missing"`` (retryable offer race),
        or ``"failed"`` (caller falls back to the local ladder)."""
        import numpy as np

        # Lazy: the scheduler exists, so the engine module is loaded —
        # this import never drags jax into a jax-free process.
        from ..models.vlm import continuous, migration
        from ..serving.proto import ml_service_pb2 as pb

        n_page_leaves = len(rec.arrays) - 1  # [per-layer page stacks..., seen]
        leaves = migration.slice_pages(
            rec.arrays, n_page_leaves, h, stop=rec.n_pages
        )
        leaves.append(np.ascontiguousarray(np.asarray(rec.rng)))
        leaves.append(np.ascontiguousarray(np.asarray(req.prompt_ids)))
        blob, crc = migration.pack_payload(leaves)
        meta = migration.commit_meta(
            crc=crc,
            n_page_leaves=n_page_leaves,
            n_pages=rec.n_pages,
            n_shared=h,
            page_size=scheduler.page_size,
            cur_tok=rec.cur_tok,
            cur_len=rec.cur_len,
            n_gen=rec.n_gen,
            prompt_len=rec.prompt_len,
            max_new=int(req.max_new),
            temperature=req.temperature,
            top_p=req.top_p,
            do_sample=req.do_sample,
            repetition_penalty=req.repetition_penalty,
            manifest=manifest,
        )
        from ..utils.tensorwire import BUNDLE_MIME

        n_chunks = max(1, -(-len(blob) // _KV_CHUNK_BYTES))
        msgs = []
        for i in range(n_chunks):
            part = blob[i * _KV_CHUNK_BYTES : (i + 1) * _KV_CHUNK_BYTES]
            msgs.append(pb.InferRequest(
                correlation_id="fedkv-commit",
                task=FED_KV_PUT_TASK,
                payload=part,
                payload_mime=BUNDLE_MIME if i == 0 else "",
                meta=meta if i == 0 else None,
                seq=i,
                total=n_chunks,
            ))
        tokens: list[int] = []
        done = None
        try:
            for resp in peer.stub.Infer(iter(msgs), timeout=self.kv_timeout_s):
                if resp.HasField("error"):
                    if resp.meta.get("fed_kv") == "chunks_missing":
                        return "chunks_missing"
                    if resp.error.code == pb.ERROR_CODE_UNAVAILABLE:
                        self.record_shed(peer)  # alive but refusing: neutral
                    logger.warning(
                        "fed_kv_put to %s refused: %s",
                        peer.name, resp.error.message,
                    )
                    return "failed"
                kind = resp.meta.get("fed_kv", "")
                if kind == "tok":
                    for part in resp.meta.get("toks", "").split(","):
                        if not part:
                            continue
                        tok = int(part)
                        tokens.append(tok)
                        if req.stream_q is not None:
                            # Relay live so the CLIENT's stream keeps
                            # flowing during remote decode; delivered
                            # tracks it so a mid-stream peer death never
                            # double-delivers on the local fallback.
                            req.stream_q.put(tok)
                            req.delivered += 1
                elif kind == "done":
                    done = resp
                    break
        except Exception as e:  # noqa: BLE001 - transport death mid-stream
            self.record_unreachable(peer, e, "kv commit")
            logger.warning(
                "fed_kv_put commit to %s died mid-stream after %d token(s): %s",
                peer.name, len(tokens), e,
            )
            return "failed"
        if done is None:
            return "failed"
        eos = done.meta.get("eos") == "1"
        note_migration(puts=1, put_bytes=len(blob), ref_pages=h)
        metrics.count("fed_kv_puts")
        metrics.count("fed_kv_put_bytes", len(blob))
        self.record_success(peer)
        continuous._retire(req, tokens, eos)
        return "done"

    # -- background health poll --------------------------------------------

    def start(self) -> None:
        """Start the one poll thread (idempotent). Never called on the
        single-host path — :func:`maybe_federation` returns None before
        any thread exists."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._poll_loop, name="fed-poll", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3)
            self._thread = None
        for peer in self.peers.values():
            metrics.unregister_gauges(f"federation:{peer.name}", peer._gauge_fn)
            peer.close()

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            for peer in list(self.peers.values()):
                if self._stop.is_set():
                    return
                if peer.name == self.self_name:
                    continue
                with self._lock:
                    ejected = peer.state == EJECTED
                    waiting = ejected and (
                        time.monotonic() - peer.ejected_at < self.eject_s
                    )
                if waiting:
                    continue  # still inside the eject window: no probe yet
                self._probe(peer, ejected)
            self._check_role_coverage()
            self._maybe_reweight()

    def _probe(self, peer: Peer, ejected: bool) -> None:
        try:
            stub = peer.stub
            call = stub.Health.with_call(empty_pb2.Empty(), timeout=2.0)
        except AttributeError:
            # Test stubs without with_call: plain Health is probe enough.
            try:
                peer.stub.Health(empty_pb2.Empty(), timeout=2.0)
                call = None
            except Exception as e:  # noqa: BLE001 - probe failure is the signal
                self.record_failure(peer, f"health probe: {type(e).__name__}: {e}")
                self._note_capacity(peer, None)
                return
        except Exception as e:  # noqa: BLE001 - probe failure is the signal
            self.record_failure(peer, f"health probe: {type(e).__name__}: {e}")
            self._note_capacity(peer, None)
            return
        cap_seen = None
        if call is not None:
            try:
                # SLO burn + service status ride Health trailing metadata;
                # stash them so /peers answers "how is that host doing"
                # without another hop.
                trailing = call[1].trailing_metadata() or ()
                role_seen = None
                for item in trailing:
                    if item.key == telemetry.SLO_META_KEY:
                        peer.slo = json.loads(item.value)
                    elif item.key == FED_ROLE_META:
                        role = str(item.value)
                        if role in _ROLE_CODES:
                            role_seen = role
                    elif item.key == FED_CAPACITY_META:
                        cap_seen = json.loads(item.value)
                # No trailer = the default lane: a peer restarted WITHOUT
                # the knob must shed its stale role, not keep it forever.
                peer.role = role_seen or ROLE_BOTH
            except Exception:  # noqa: BLE001 - telemetry, never a verdict
                pass
        self._note_capacity(peer, cap_seen)
        with self._lock:
            peer.streak = 0
            peer.last_ok = time.monotonic()
            readmitted = peer.state == EJECTED
            if readmitted:
                peer.state = SERVING
        if readmitted:
            self._announce_readmit(peer, "health probe succeeded")

    # -- capacity gossip -> weighted ring + drain handoff ------------------

    def _note_capacity(self, peer: Peer, cap: dict | None) -> None:
        """Fold one poll's capacity report (or its absence) into the
        peer's state. Does nothing unless this host enables the gossip
        (``LUMEN_FED_CAPACITY=1``) — the unconfigured path keeps the
        boot-time equal-weight ring untouched."""
        if not self._capacity_on:
            return
        if not isinstance(cap, dict):
            peer.missed_capacity += 1
            if (
                peer.capacity
                and peer.missed_capacity >= self.capacity_stale_polls
            ):
                peer.capacity = {}
                metrics.count("fed_gossip_stale")
                if not peer._stale_warned:
                    peer._stale_warned = True
                    logger.warning(
                        "federation peer %s stopped reporting capacity "
                        "(%d silent poll(s)); last report discarded, "
                        "weight decays to neutral",
                        peer.name, peer.missed_capacity,
                    )
                self._maybe_reweight()
            return
        peer.missed_capacity = 0
        peer._stale_warned = False
        was_draining = bool(peer.capacity.get("draining"))
        peer.capacity = cap
        if bool(cap.get("draining")) and not was_draining:
            # A planned drain must never be discovered by failover: zero
            # the weight NOW (bypassing the remap-rate cap) and prefetch
            # the drained arcs' hottest cache entries onto successors.
            self._maybe_reweight(force=True)
            self._drain_handoff(peer)

    def _desired_weight(self, peer: Peer) -> float:
        """Gossip report -> ring weight: headroom (``1 - duty``), halved
        while the peer's error budget burns faster than sustainable,
        floored at :data:`MIN_CAPACITY_WEIGHT` so a busy-but-alive host
        keeps a sliver of the ring. Draining = exactly 0 (no arcs);
        no/stale report = neutral 1.0."""
        cap = peer.capacity
        if not cap:
            return 1.0
        if cap.get("draining"):
            return 0.0
        duty = cap.get("duty")
        try:
            w = 1.0 if duty is None else 1.0 - min(1.0, max(0.0, float(duty)))
        except (TypeError, ValueError):
            w = 1.0
        try:
            if float(cap.get("burn_5m") or 0.0) > 1.0:
                w *= 0.5
        except (TypeError, ValueError):
            pass
        return max(MIN_CAPACITY_WEIGHT, w)

    def _maybe_reweight(self, force: bool = False) -> bool:
        """Rebuild the ring from gossiped capacity — only when some
        weight moved past the hysteresis band, and at most once per
        ``LUMEN_FED_CAPACITY_REMAP_S`` (``force``, used by drain flips,
        bypasses both). Returns True when the ring was rebuilt."""
        if not self._capacity_on:
            return False
        desired = {n: self._desired_weight(p) for n, p in self.peers.items()}
        now = time.monotonic()
        with self._lock:
            current = self.ring.weights
            moved = any(
                abs(w - current.get(n, 1.0)) > self.capacity_hyst
                for n, w in desired.items()
            )
            if not moved and not force:
                return False
            if not force and now - self._last_remap < self.capacity_remap_s:
                return False
            weights = desired
            if all(w <= 0.0 for w in desired.values()):
                # Every peer drained at once: an empty ring refuses all
                # traffic, which is strictly worse — keep the equal-weight
                # ring and let per-request drain sheds steer instead.
                weights = {}
            self.ring = HashRing(list(self.peers), weights=weights)
            self._shares = self.ring.shares()
            self._last_remap = now
            for n, p in self.peers.items():
                p.weight = desired.get(n, 1.0)
        metrics.count("fed_ring_remaps")
        logger.info(
            "federation ring re-weighted from capacity gossip: %s",
            {n: round(w, 2) for n, w in sorted(desired.items())},
        )
        return True

    def _drain_handoff(self, peer: Peer) -> None:
        """Kick the hot-cache prefetch for a peer that just flipped its
        gossiped ``draining`` flag: its advertised hottest result-cache
        keys are fetched over the fed_cache_lookup peer-export path and
        pushed onto their new ring owners, so the handed-off arcs arrive
        warm. Runs on a short-lived daemon thread — the poll loop never
        blocks on N cross-host copies."""
        keys = [
            k for k in (peer.capacity.get("hot") or [])
            if isinstance(k, str)
        ][:FED_HANDOFF_KEYS]
        metrics.count("fed_drain_handoffs")
        telemetry.record_event(
            "fed_drain_handoff", peer.name,
            f"draining peer re-weighted to zero; prefetching {len(keys)} "
            "hot cache key(s) onto ring successors",
            keys=len(keys),
        )
        if keys:
            threading.Thread(
                target=self._drain_handoff_run, args=(peer, keys),
                name="fed-drain-handoff", daemon=True,
            ).start()

    def _drain_handoff_run(self, peer: Peer, keys: list[str]) -> None:
        moved = 0
        for key in keys:
            digest = key.rpartition(":")[2]
            target = None
            for name in self.ring.owners(digest, 2, skip=self._ejected_names()):
                if name not in (peer.name, self.self_name):
                    target = self.peers.get(name)
                    break
            if target is None:
                continue
            blob = self._fetch_blob(peer, key)
            if blob is not None and self._push_blob(target, key, blob):
                moved += 1
        if moved:
            metrics.count("fed_drain_prefetch", moved)
            logger.info(
                "drain handoff from %s: %d/%d hot cache blob(s) "
                "prefetched onto ring successors",
                peer.name, moved, len(keys),
            )

    def _fetch_blob(self, owner: Peer, key: str) -> bytes | None:
        """One raw (un-unpickled) cache export from ``owner`` — the
        drain-handoff fetch leg; the blob is relayed verbatim."""
        from ..serving.proto import ml_service_pb2 as pb

        try:
            req = pb.InferRequest(
                correlation_id="fedcache-handoff",
                task=FED_CACHE_TASK,
                payload=key.encode("utf-8"),
                meta={"wait_ms": "0"},
            )
            resps = list(owner.stub.Infer(iter([req]), timeout=self.lookup_timeout_s))
        except Exception as e:  # noqa: BLE001 - a failed fetch skips the key
            self.record_unreachable(owner, e, "drain handoff fetch")
            return None
        last = resps[-1] if resps else None
        if (
            last is None
            or last.HasField("error")
            or last.meta.get("fed_cache") != "hit"
        ):
            return None
        return b"".join(r.result for r in resps)

    def _push_blob(self, target: Peer, key: str, blob: bytes) -> bool:
        """Drain-handoff store leg: push one exported blob to its new
        ring owner (the ``op=put`` extension of the fed_cache task)."""
        from ..serving.proto import ml_service_pb2 as pb

        try:
            resps = list(target.stub.Infer(iter([pb.InferRequest(
                correlation_id="fedcache-put",
                task=FED_CACHE_TASK,
                payload=blob,
                meta={"op": "put", "key": key},
            )]), timeout=self.lookup_timeout_s))
        except Exception as e:  # noqa: BLE001 - a failed push skips the key
            self.record_unreachable(target, e, "drain handoff put")
            return False
        last = resps[-1] if resps else None
        return bool(
            last is not None
            and not last.HasField("error")
            and last.meta.get("fed_cache") == "stored"
        )

    # -- peer cache lookup (the ResultCache pre-compute hook) --------------

    def peer_cache_lookup(self, key: str, payload: bytes) -> tuple[bool, Any]:
        """Ask the ring owner's cache for ``key`` before computing
        locally. Installed as ``ResultCache.peer_lookup`` on peer-aware
        backends; returns ``(False, None)`` whenever the owner is self,
        ejected, or unreachable — the caller then computes as before."""
        if not self.self_listed:
            # Without a verified self identity the `owner == self` guard
            # below cannot work — a lookup could land on our own address
            # and ride our own unresolved flight. Defense in depth for
            # callers that bypass the server's install gate.
            return False, None
        digest = hashlib.sha256(payload).hexdigest()
        owner = self.owner_of(digest)
        if owner is None or owner.name == self.self_name:
            return False, None
        # The RPC deadline must COVER the owner-side flight wait we are
        # about to request (plus the probe itself), or the call always
        # dies DEADLINE_EXCEEDED before the owner's compute resolves and
        # cross-host coalescing can never engage for slow computes. Still
        # bounded by our own caller's remaining request deadline.
        wait_s = min(self.lookup_wait_ms / 1000.0, FED_CACHE_MAX_WAIT_S)
        timeout = self.lookup_timeout_s + wait_s
        rem = remaining()
        if rem is not None:
            if rem <= 0.01:
                return False, None
            timeout = min(timeout, rem)
        tr = current_trace()
        span = tr.begin("fed.peer_cache", {"peer": owner.name}) if tr else None
        found, value = self._lookup_once(owner, key, timeout)
        if span is not None:
            span.end(hit="1" if found else "0")
        return found, value

    def _lookup_once(self, owner: Peer, key: str, timeout: float) -> tuple[bool, Any]:
        from ..serving.proto import ml_service_pb2 as pb

        try:
            req = pb.InferRequest(
                correlation_id="fedcache",
                task=FED_CACHE_TASK,
                payload=key.encode("utf-8"),
                meta={"wait_ms": str(self.lookup_wait_ms)},
            )
            resps = list(owner.stub.Infer(iter([req]), timeout=timeout))
        except Exception as e:  # noqa: BLE001 - a failed lookup is a miss
            owner.stats["cache_misses"] += 1
            metrics.count("fed_cache_peer_misses")
            # Streak only on transport-unreachable (see record_unreachable):
            # a DEADLINE_EXCEEDED means the peer answered at the TCP level
            # but our own budget ran out (slow flight, caller's deadline).
            self.record_unreachable(owner, e, "cache lookup")
            return False, None
        last = resps[-1] if resps else None
        if (
            last is None
            or last.HasField("error")
            or last.meta.get("fed_cache") != "hit"
        ):
            owner.stats["cache_misses"] += 1
            metrics.count("fed_cache_peer_misses")
            return False, None
        try:
            value = pickle.loads(b"".join(r.result for r in resps))
        except Exception as e:  # noqa: BLE001 - a torn blob is a miss
            logger.warning("peer cache blob from %s undecodable: %s", owner.name, e)
            owner.stats["cache_misses"] += 1
            metrics.count("fed_cache_peer_misses")
            return False, None
        owner.stats["cache_hits"] += 1
        self.record_success(owner)
        metrics.count("fed_cache_peer_hits")
        return True, value

    # -- status surfaces ----------------------------------------------------

    def health_status(self) -> dict:
        """Compact per-peer state for the ``lumen-fed-status`` Health
        trailing-metadata key."""
        with self._lock:
            states = {n: p.state for n, p in sorted(self.peers.items())}
        return {"self": self.self_name, "peers": states}

    def export_status(self) -> dict:
        """Full per-peer view for ``GET /peers`` and the client ``peers``
        subcommand."""
        now = time.monotonic()
        peers: dict[str, dict] = {}
        hits = misses = 0
        with self._lock:
            shares = dict(self._shares)
            for name, p in sorted(self.peers.items()):
                hits += p.stats["cache_hits"]
                misses += p.stats["cache_misses"]
                peers[name] = {
                    "state": p.state,
                    "streak": p.streak,
                    "fed_role": p.role,
                    **p.stats,
                    "ring_share": round(shares.get(name, 0.0), 4),
                    "sidecar": p.spec.sidecar,
                    "last_ok_s_ago": (
                        round(now - p.last_ok, 1) if p.last_ok else None
                    ),
                    "last_error": p.last_error or None,
                    "slo": p.slo or None,
                }
                if self._capacity_on:
                    # Gossiped capacity columns (the `client peers` view):
                    # absent entirely when the gossip is off, so the
                    # unconfigured payload is unchanged.
                    peers[name].update({
                        "weight": round(p.weight, 4),
                        "duty": p.capacity.get("duty"),
                        "burn_5m": p.capacity.get("burn_5m"),
                        "draining": bool(p.capacity.get("draining")),
                    })
        out = {
            "enabled": True,
            "mode": "peer" if self.self_name else "front",
            "self": self.self_name,
            "role": fed_role(),
            "hops": self.hops,
            "peers": peers,
            "kv_migration": dict(MIGRATION),
            "cache_peer_hit_rate": round(hits / (hits + misses), 4)
            if hits + misses
            else 0.0,
        }
        if self._capacity_on:
            out["capacity_gossip"] = True
        return out


# ---------------------------------------------------------------------------
# Process-wide instance + boot wiring
# ---------------------------------------------------------------------------

_manager: FederationManager | None = None
_manager_lock = threading.Lock()


def get_federation() -> FederationManager | None:
    return _manager


def install_federation(manager: FederationManager | None) -> None:
    global _manager
    with _manager_lock:
        _manager = manager


def export_status() -> dict:
    """Module-level status for the observability sidecar's ``GET /peers``
    (read via ``sys.modules`` so a jax-free sidecar never imports this)."""
    m = _manager
    if m is None:
        return {"enabled": False, "peers": {}, "detail": "federation not configured"}
    return m.export_status()


def health_status() -> dict:
    m = _manager
    return m.health_status() if m is not None else {}


def maybe_federation() -> FederationManager | None:
    """Build (and install) the fleet view from the environment, or None.

    Peer sources: the ``LUMEN_FED_PEERS`` comma list, plus (with
    ``LUMEN_FED_DISCOVER=1``) a one-shot mDNS browse for ``_lumen._tcp``
    advertisers on the LAN. With neither configured this returns None
    having done NOTHING — no thread, no gauge, no socket — which is the
    whole single-host overhead story. The resolved peer set is logged
    once. The poll thread starts only when the caller says so
    (``manager.start()``)."""
    import os

    specs = parse_peer_specs()
    if os.environ.get(DISCOVER_ENV) == "1":
        from ..serving.mdns import discover_peers

        known = {s.addr for s in specs}
        discovered = [a for a in discover_peers() if a not in known]
        if discovered:
            # Trust posture, stated where the decision lands: mDNS is
            # unauthenticated and the peer protocol (insecure gRPC +
            # pickled cache blobs) assumes fleet-internal trust — any
            # LAN host that advertises _lumen._tcp joins the ring and
            # can answer cache lookups. Only enable discovery on
            # networks where every host is already trusted to serve.
            logger.warning(
                "federation: adding %d UNAUTHENTICATED mDNS-discovered "
                "peer(s) %s — the peer protocol assumes a trusted "
                "network (insecure gRPC, pickled cache payloads); use "
                "%s on untrusted LANs instead",
                len(discovered), discovered, PEERS_ENV,
            )
        for addr in discovered:
            specs.append(PeerSpec(addr=addr))
    if not specs:
        return None
    manager = FederationManager(specs, self_name=os.environ.get(SELF_ENV) or None)
    logger.info(
        "federation: %d peer(s) resolved: %s%s (hops=%d, failures=%d, "
        "eject=%.1fs, poll=%.1fs)",
        len(specs),
        [s.addr for s in specs],
        f"; self={manager.self_name}" if manager.self_name else " (front tier)",
        manager.hops, manager.failures, manager.eject_s, manager.poll_s,
    )
    install_federation(manager)
    return manager
