"""Capacity-telemetry layer — runtime-facing entry point.

The implementation lives in :mod:`lumen_tpu.utils.telemetry` for the same
reason ``utils/qos.py`` and ``utils/trace.py`` live in ``utils``: the
jax-free serving layer (router, observability sidecar, client) must read
rolling-window stats, SLO state and the flight recorder without dragging
in the jax-importing runtime package ``__init__``. This module re-exports
the surface runtime components feed — the micro-batcher credits
``device:{name}`` busy intervals and per-batch padding/transfer counts,
the decode pool credits ``decode:{name}`` worker time, the compile-cache
hook counts XLA compiles — so runtime code has one local name for the
layer.

See :mod:`lumen_tpu.utils.telemetry` for the full design notes: ring-
buffered time buckets, union- vs sum-mode duty meters, the SLO burn-rate
engine and the incident flight recorder.
"""

from ..utils.telemetry import (  # noqa: F401 - re-exported runtime surface
    INCIDENT_KINDS,
    SLO_META_KEY,
    busy,
    capacity_stats,
    count,
    count_error,
    device_duty,
    duty_fraction,
    enabled,
    export_events,
    export_incidents,
    forecast_rate,
    get_hub,
    install_hub,
    observe,
    record_event,
    reset_hub,
    set_capacity,
    slo_report,
    slo_status,
    telemetry_enabled,
    window_total,
)

__all__ = [
    "INCIDENT_KINDS",
    "SLO_META_KEY",
    "busy",
    "capacity_stats",
    "count",
    "count_error",
    "device_duty",
    "duty_fraction",
    "enabled",
    "export_events",
    "export_incidents",
    "forecast_rate",
    "get_hub",
    "install_hub",
    "observe",
    "record_event",
    "reset_hub",
    "set_capacity",
    "slo_report",
    "slo_status",
    "telemetry_enabled",
    "window_total",
]
