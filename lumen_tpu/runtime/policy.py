"""Mixed-precision policy.

TPU MXU wants bf16 matmuls; embeddings/results leave the device as fp32.
One small policy object threads through every model instead of per-backend
fp16 special cases (reference: CUDA AMP autocast at
``packages/lumen-clip/src/lumen_clip/backends/torch_backend.py:127-129``,
ONNX fp16 I/O juggling at ``onnxrt_backend.py:594-659``).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp


@dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype
    compute_dtype: jnp.dtype
    output_dtype: jnp.dtype

    def cast_params(self, tree):
        return _cast_floating(tree, self.param_dtype)

    def cast_compute(self, tree):
        return _cast_floating(tree, self.compute_dtype)

    def cast_output(self, tree):
        return _cast_floating(tree, self.output_dtype)


def _cast_floating(tree, dtype):
    import jax

    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree_util.tree_map(cast, tree)


_POLICIES = {
    # name -> (params, compute, output)
    "bfloat16": Policy(jnp.bfloat16, jnp.bfloat16, jnp.float32),
    "float32": Policy(jnp.float32, jnp.float32, jnp.float32),
    # fp16 accepted for config compat; on TPU bf16 is almost always better.
    "float16": Policy(jnp.float16, jnp.float16, jnp.float32),
}


def get_policy(name: str) -> Policy:
    try:
        return _POLICIES[name]
    except KeyError as e:
        raise ValueError(f"unknown dtype policy {name!r}; valid: {sorted(_POLICIES)}") from e
