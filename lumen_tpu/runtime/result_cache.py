"""Process-wide content-addressed inference result cache + single-flight.

BENCH_r05 measured the device lane ~100x ahead of the serving path (CLIP
9,083 images/s/chip device-only vs 37.9 images/s end-to-end ingest and 77
RPS gRPC c10): the binding resource is the *host* — decode (~100
images/s/core) and per-request serialization. The cheapest throughput
multiplier left is therefore not computing at all: photo-indexing traffic
is full of byte-identical work (re-index passes over an unchanged library,
burst duplicates, client retries after an admission shed), and every one
of those requests used to pay decode + batcher + device again.

This module is the answer, in two parts:

- **content-addressed result cache** — results keyed by
  ``(namespace, canonicalized request options, sha256(payload bytes))``
  where the namespace is ``{service}/{task}/{model-id}@{revision}``. The
  hash runs on the RAW bytes, so a hit is decided *before* the decode
  pool and the micro-batcher ever see the request: it skips the host
  decode bottleneck entirely and never counts against admission queues or
  deadline gates. Two tiers: a byte-budgeted in-RAM LRU
  (``LUMEN_CACHE_BYTES``, default 256 MiB, 0 disables) and an optional
  pickle-on-disk tier (``LUMEN_CACHE_DIR``) that survives restarts.

- **single-flight coalescing** — concurrent *identical* requests share one
  in-flight future: the first caller computes, the rest wait on its
  result, so a retry storm or duplicate burst costs ONE batcher
  submission instead of N. Caller-specific overload failures
  (:class:`~lumen_tpu.utils.deadline.DeadlineExpired` /
  :class:`~lumen_tpu.utils.deadline.QueueFull` on the owner) are NOT fanned
  out as final answers — a waiter whose owner was shed retries the compute
  itself (one of the waiters becomes the new owner), because the owner's
  deadline says nothing about the waiter's.

Invalidation is namespace-prefix-based: the router's hot-swap path
(:meth:`~lumen_tpu.serving.router.HubRouter.replace_service`, which the
background :class:`~lumen_tpu.serving.resilience.RecoveryManager` drives)
invalidates ``{service}/`` so a newly swapped-in model never serves a
predecessor's results even when id+revision match.

Deliberately jax-free (like :mod:`~lumen_tpu.runtime.decode_pool`): pure
host plumbing, importable from the serving layer without a backend.

Caching is only ever keyed on deterministic work: the VLM manager bypasses
the cache when ``do_sample`` / ``temperature > 0`` — sampled generations
must stay sampled.

Multi-tenant isolation (:mod:`~lumen_tpu.utils.qos`): keys for a
non-default tenant carry a ``/tenant=<id>`` namespace qualifier, so one
tenant's entries (and its poison-quarantine fingerprints — a tenant must
not be able to poison-flag content another tenant serves) never answer
for another's; per-tenant byte accounting rides each entry, and when the
RAM tier is over budget it evicts **fair-share-first**: the victim is
always the least-recently-used entry of the tenant holding the MOST
bytes, so a flooding tenant's churn evicts its own backlog while smaller
tenants' hot sets stay resident. ``cross_tenant_evictions`` counts the
violations (an under-fair-share tenant losing an entry to another
tenant's store) — zero by construction, watched by ``bench.py qos``.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import weakref
from collections import OrderedDict
from concurrent.futures import Future, TimeoutError as FuturesTimeout
from typing import Any, Callable, Mapping
from urllib.parse import quote, unquote

import numpy as np

from ..utils.deadline import DeadlineExpired, PoisonInput, QueueFull, remaining
from ..utils.env import env_int
from ..utils.metrics import metrics
from ..utils.qos import DEFAULT_TENANT, _MAX_TENANT_STATS, current_tenant
from ..utils.request_notes import mark as _mark
from .trace import current_trace

logger = logging.getLogger(__name__)

CACHE_BYTES_ENV = "LUMEN_CACHE_BYTES"
CACHE_DIR_ENV = "LUMEN_CACHE_DIR"

DEFAULT_CACHE_BYTES = 256 * 1024 * 1024


def cache_bytes() -> int:
    """RAM-tier byte budget: ``LUMEN_CACHE_BYTES`` (0 disables the RAM
    tier; unset -> 256 MiB default, malformed -> default with the shared
    parser's one-shot warning)."""
    return env_int(CACHE_BYTES_ENV, DEFAULT_CACHE_BYTES, minimum=0)


def cache_dir() -> str | None:
    """Disk-tier root: ``LUMEN_CACHE_DIR`` (unset/empty = no disk tier)."""
    return os.environ.get(CACHE_DIR_ENV) or None


def canonical_options(options: Mapping[str, Any] | None) -> str:
    """Canonical JSON for the request-options half of the key: sorted keys,
    no whitespace, non-JSON values via repr — the SAME logical options must
    hash identically regardless of dict insertion order."""
    return json.dumps(
        dict(options or {}), sort_keys=True, separators=(",", ":"), default=repr
    )


def make_namespace(
    family: str, task: str, model_id: str, revision: str, *qualifiers: str
) -> str:
    """The ONE namespace format: ``{family}/{task}/{model-id}@{revision}``
    plus any compute-path qualifiers (dtype policy, quant route, ...) that
    change the numerics of a result — entries computed under different
    precision must not answer for each other, especially across restarts
    via the disk tier. The family prefix is load-bearing: the router's
    hot-swap invalidation drops ``{family}/``, so every manager must build
    namespaces through here."""
    ns = f"{family}/{task}/{model_id}@{revision}"
    quals = [q for q in qualifiers if q]
    if quals:
        ns += "/" + ",".join(quals)
    return ns


#: namespace qualifier marking a non-default tenant's entries
_TENANT_MARK = "/tenant="


def make_key(namespace: str, options: Mapping[str, Any] | None, payload: bytes) -> str:
    """``{namespace}:{sha256 digest}`` — the namespace stays in the clear so
    prefix invalidation (model hot-swap) can drop a whole model's entries
    without remembering its keys.

    Tenant-scoped: a request running under a non-default tenant (the
    ``lumen-tenant`` contextvar, see :mod:`~lumen_tpu.utils.qos`) gets a
    trailing ``/tenant=<id>`` qualifier, so tenants never share entries —
    or poison-quarantine fingerprints, which are this same key. The
    family prefix stays leading, so hot-swap invalidation
    (``invalidate("clip/")``) still sweeps every tenant's entries.
    Default-tenant keys are byte-identical to the pre-QoS format."""
    tenant = current_tenant()
    if tenant != DEFAULT_TENANT:
        namespace = f"{namespace}{_TENANT_MARK}{quote(tenant, safe='')}"
    h = hashlib.sha256()
    h.update(namespace.encode("utf-8"))
    h.update(b"\x00")
    h.update(canonical_options(options).encode("utf-8"))
    h.update(b"\x00")
    h.update(payload)
    return f"{namespace}:{h.hexdigest()}"


def key_tenant(key: str) -> str:
    """The tenant a cache key belongs to (``default`` for unscoped keys)
    — the entry's accounting identity is intrinsic to its key, so
    promotions and replacements always charge the same tenant no matter
    which request context performs them."""
    ns, _, _ = key.rpartition(":")
    i = ns.rfind(_TENANT_MARK)
    if i < 0:
        return DEFAULT_TENANT
    return unquote(ns[i + len(_TENANT_MARK):])


class _Entry:
    __slots__ = ("value", "nbytes", "tenant")

    def __init__(self, value: Any, nbytes: int, tenant: str = DEFAULT_TENANT):
        self.value = value
        self.nbytes = nbytes
        self.tenant = tenant


class ResultCache:
    """Byte-budgeted LRU + optional disk tier + single-flight coalescing.

    ``get_or_compute`` is the whole API surface the serving path uses; the
    lower-level ``get``/``put``/``invalidate`` exist for the ingest
    pipeline (bulk peek/store without single-flight) and the hot-swap hook.

    **Fleet federation hook** (:mod:`~lumen_tpu.runtime.federation`):
    ``peer_lookup`` — when set (peer-aware backends with
    ``LUMEN_FED_SELF``), a local miss consults the consistent-hash ring
    owner's cache over the wire BEFORE computing — owner-anchored
    dedupe: duplicates that reach the ring owner first (all
    front-tier-routed traffic) cost device work once fleet-wide; a
    result computed at a non-owner stays local (lookup-only protocol,
    no write-back). The hook is ``(key, payload) -> (found, value)``
    and must never raise into the serving path (failures are treated as
    a miss). ``None`` (the default, and the only state when federation
    is unconfigured) keeps the miss path byte-identical to single-host.
    """

    #: optional cross-host lookup consulted on the owner path of a miss
    #: (set by the federation boot wiring; None = single-host behavior).
    peer_lookup: Callable[[str, bytes], tuple[bool, Any]] | None = None

    def __init__(
        self,
        max_bytes: int | None = None,
        disk_dir: str | None = None,
        name: str = "result_cache",
    ):
        self.max_bytes = cache_bytes() if max_bytes is None else max(0, max_bytes)
        self.disk_dir = disk_dir if disk_dir is not None else cache_dir()
        if self.max_bytes == 0:
            # LUMEN_CACHE_BYTES=0 is the ONE kill switch, as documented:
            # it disables both tiers. A lingering LUMEN_CACHE_DIR must not
            # silently keep a disk-backed cache (and single-flight) alive
            # on a deployment that turned caching off.
            self.disk_dir = None
        self.name = name
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self._bytes = 0
        self._inflight: dict[str, Future] = {}
        # Invalidation fence: a monotonic sequence bumped by invalidate(),
        # with the last-invalidation seq per prefix. A computation that
        # STARTED before an invalidation of its namespace must not store
        # its (predecessor-model) result after it — get_or_compute captures
        # the fence pre-compute and put() rejects anything stale. Bounded:
        # one entry per distinct prefix (service families).
        self._inval_seq = 0
        self._inval_marks: dict[str, int] = {}
        self._waiting = 0  # callers currently blocked on another's flight
        # Per-tenant RAM-tier byte accounting (entry tenant is intrinsic
        # to its key): drives fair-share-first eviction and the
        # ``bytes:{tenant}`` gauges. Only tenants with live entries keep
        # a row — a drained tenant's row is deleted, so churn through
        # many tenant ids cannot grow this without bound.
        self._tenant_bytes: dict[str, int] = {}
        # Per-tenant LRU key order mirroring ``_entries`` (same recency
        # updates, same lock): victim selection in fair-share eviction is
        # a first-key lookup instead of a scan over every other tenant's
        # entries — churn under one tenant must not hold the cache lock
        # for O(total entries) per eviction.
        self._tenant_lru: dict[str, OrderedDict[str, None]] = {}
        # Local mirrors of the global event counters, for gauges/bench.
        self.stats = {
            "hits": 0,
            "disk_hits": 0,
            "misses": 0,
            "coalesced": 0,
            "evictions": 0,
            "cross_tenant_evictions": 0,
            "stores": 0,
        }
        self._pickle_warned = False
        if self.disk_dir:
            try:
                os.makedirs(self.disk_dir, exist_ok=True)
            except OSError as e:
                logger.warning("cache disk tier disabled (%s): %s", self.disk_dir, e)
                self.disk_dir = None
        # Occupancy gauges next to the batcher/decode-pool providers; the
        # weakref keeps the global registry from pinning a dropped cache.
        ref = weakref.ref(self)

        def _gauges() -> dict:
            c = ref()
            return {} if c is None else c.gauges()

        self._gauge_fn = _gauges
        metrics.register_gauges(name, _gauges)

    # -- properties --------------------------------------------------------

    @property
    def enabled(self) -> bool:
        """False when both tiers are off — callers then run compute()
        directly (not even single-flight: an explicitly disabled cache
        must leave the serving path byte-for-byte as before)."""
        return self.max_bytes > 0 or self.disk_dir is not None

    def gauges(self) -> dict:
        with self._lock:
            out = {
                **self.stats,
                "bytes": self._bytes,
                "budget_bytes": self.max_bytes,
                "entries": len(self._entries),
                "inflight": len(self._inflight),
                "waiting": self._waiting,
            }
            # Per-tenant residency only when a non-default tenant holds
            # entries — single-tenant deployments keep the exact pre-QoS
            # gauge payload.
            if len(self._tenant_bytes) > 1 or (
                self._tenant_bytes and DEFAULT_TENANT not in self._tenant_bytes
            ):
                for tenant, n in sorted(self._tenant_bytes.items()):
                    out[f"bytes:{tenant}"] = n
        return out

    def hit_rate(self) -> float:
        with self._lock:
            hits = self.stats["hits"] + self.stats["disk_hits"]
            total = hits + self.stats["misses"]
        return hits / total if total else 0.0

    # -- core lookup -------------------------------------------------------

    def _count(self, stat: str, metric: str) -> None:
        self.stats[stat] += 1  # caller holds no lock; int += is fine for telemetry
        metrics.count(metric)

    def get(self, key: str, clone: Callable[[Any], Any] | None = None) -> tuple[bool, Any]:
        """RAM-then-disk probe. Returns ``(found, value)``; a disk hit is
        promoted into the RAM tier. Marks the request-note scope on hit,
        and records a ``cache.lookup`` span on the active request trace."""
        tr = current_trace()
        if tr is None:
            return self._get(key, clone)
        h = tr.begin("cache.lookup")
        found = False
        try:
            found, value = self._get(key, clone)
            return found, value
        finally:
            h.end(hit="1" if found else "0")

    def _get(self, key: str, clone: Callable[[Any], Any] | None = None) -> tuple[bool, Any]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._lru_touch_locked(entry.tenant, key)
                value = entry.value
            else:
                value = None
        if entry is not None:
            self._count("hits", "cache_hits")
            _mark("hit")
            return True, clone(value) if clone else value
        if self.disk_dir is not None:
            # Fence the promotion: a disk read racing an invalidation's
            # rmtree must neither serve nor re-promote the swept entry.
            fence = self.current_fence()
            found, value, nbytes = self._disk_read(key)
            if found and not self._stale(key, fence):
                self._store_ram(key, value, nbytes, fence=fence)
                self._count("disk_hits", "cache_disk_hits")
                _mark("hit")
                return True, clone(value) if clone else value
        return False, None

    def current_fence(self) -> int:
        """Snapshot of the invalidation sequence; pass to :meth:`put` to
        guarantee a result computed before a later invalidation of its
        namespace is never stored after it."""
        with self._lock:
            return self._inval_seq

    def _stale_locked(self, key: str, fence: int) -> bool:
        """Caller holds ``self._lock``."""
        return any(
            seq > fence and key.startswith(prefix)
            for prefix, seq in self._inval_marks.items()
        )

    def _stale(self, key: str, fence: int) -> bool:
        with self._lock:
            return self._stale_locked(key, fence)

    def put(
        self,
        key: str,
        value: Any,
        clone: Callable[[Any], Any] | None = None,
        fence: int | None = None,
    ) -> None:
        """Store a computed value in both tiers. ``clone`` (when given) is
        applied to the stored copy so the caller keeps exclusive ownership
        of the object it just computed — later mutation must not corrupt
        what other requests will be served. ``fence`` (from
        :meth:`current_fence`, taken before the compute) drops the store
        when the namespace was invalidated mid-compute — e.g. a model
        hot-swap racing an in-flight request on the old instance."""
        if fence is not None and self._stale(key, fence):
            return  # fast reject; the tiers re-check authoritatively
        blob = None
        if self.disk_dir is not None:
            blob = self._encode(value)
            if blob is None:
                return  # unpicklable: warned once, not cached
            nbytes = len(blob)
        else:
            # RAM-only: a structural size estimate avoids paying a full
            # pickle per store just to weigh the entry (the ingest settle
            # loop stores every record — this is a hot path).
            est = self._approx_nbytes(value)
            if est is None:
                return
            nbytes = est
        if clone is not None and blob is not None:
            # The pickle round-trip IS a deep copy — don't traverse the
            # value a second time (clone on hits still applies, giving
            # VLM-style custom clones their marker semantics there).
            stored = pickle.loads(blob)
        else:
            stored = clone(value) if clone else value
        self._store_ram(key, stored, nbytes, fence=fence)
        self._count("stores", "cache_stores")
        if blob is not None:
            self._disk_write(key, blob, fence=fence)

    def hot_keys(self, n: int = 8) -> list[str]:
        """The ``n`` most-recently-used RAM-tier keys, hottest first — the
        drain-handoff manifest a draining host gossips so the front can
        prefetch exactly these onto ring successors. ``_entries`` is kept
        in LRU order (MRU at the end), so the reversal is the recency
        ranking; no touch, no promotion — reading the manifest must not
        reorder the cache it describes."""
        with self._lock:
            keys = list(self._entries)
        return keys[::-1][:n]

    def _approx_nbytes(self, value: Any, _depth: int = 0) -> int | None:
        """Structural RAM weight for common result shapes (arrays, bytes,
        records, dataclasses); odd types fall back to one pickle."""
        if _depth > 8:
            blob = self._encode(value)
            return None if blob is None else len(blob)
        if isinstance(value, np.ndarray):
            return value.nbytes + 128
        if isinstance(value, (bytes, bytearray, str)):
            return len(value) + 64
        if value is None or isinstance(value, (bool, int, float, complex)):
            return 32
        if isinstance(value, (list, tuple, set, frozenset)):
            total = 64
            for v in value:
                n = self._approx_nbytes(v, _depth + 1)
                if n is None:
                    return None
                total += n
            return total
        if isinstance(value, dict):
            total = 64
            for k, v in value.items():
                nk = self._approx_nbytes(k, _depth + 1)
                nv = self._approx_nbytes(v, _depth + 1)
                if nk is None or nv is None:
                    return None
                total += nk + nv
            return total
        inner = getattr(value, "__dict__", None)
        if inner is not None:  # dataclass-style records (FaceDetection, ...)
            return self._approx_nbytes(inner, _depth + 1)
        blob = self._encode(value)
        return None if blob is None else len(blob)

    def get_or_compute(
        self,
        namespace: str,
        options: Mapping[str, Any] | None,
        payload: bytes,
        compute: Callable[[], Any],
        clone: Callable[[Any], Any] | None = None,
        key: str | None = None,
    ) -> Any:
        """The serving-path entry point: content-addressed lookup with
        single-flight coalescing around ``compute``.

        - **hit** (RAM or disk): the stored value (cloned when ``clone``)
          returns immediately — no decode, no batcher, no admission or
          deadline accounting.
        - **miss, first caller**: computes, stores, resolves the shared
          flight. Failures propagate to the caller and fan out to waiters
          (never cached — a poison verdict in particular can never be
          served as a "result").
        - **miss, concurrent duplicate**: waits on the owner's flight —
          one batcher submission serves the whole burst. If the owner
          failed with a *caller-specific* overload error (deadline/shed)
          or a containment verdict (poison isolation/quarantine), the
          waiter retries the compute itself instead of inheriting an
          error shaped by someone else's flight; a poison retry then hits
          the quarantine gate up front and earns its OWN properly-worded
          rejection, not a secondhand cache error.

        ``key`` skips the internal :func:`make_key` when the caller
        already hashed the payload (e.g. for the quarantine gate) — the
        sha256 over megabytes of image bytes should run once, not twice.
        """
        if not self.enabled:
            return compute()
        if key is None:
            key = make_key(namespace, options, payload)
        while True:
            found, value = self.get(key, clone=clone)
            if found:
                return value
            with self._lock:
                flight = self._inflight.get(key)
                if flight is None:
                    flight = Future()
                    self._inflight[key] = flight
                    owner = True
                else:
                    owner = False
            if owner:
                break
            with self._lock:
                self._waiting += 1
            tr = current_trace()
            wspan = tr.begin("cache.wait") if tr is not None else None
            try:
                # Bounded by the WAITER's own ambient request deadline
                # (None = wait for the owner, whose resolution is
                # guaranteed): the PR-1 deadline contract must survive
                # coalescing — a 50ms-budget duplicate must not ride out
                # the owner's multi-second queue wait on a gRPC thread.
                # Clamped: a no-deadline request can surface as a HUGE
                # time_remaining() on some gRPC stacks, and that number
                # fed raw into Future.result overflows C time
                # (_PyTime_t) — observed live as INTERNAL errors on a
                # coalesced burst.
                rem = remaining()
                value = flight.result(
                    timeout=None if rem is None else min(rem, 86400.0)
                )
            except FuturesTimeout:
                metrics.count("deadline_drops")
                metrics.count("deadline_drops:result_cache")
                raise DeadlineExpired(
                    "request deadline expired waiting on a coalesced "
                    "identical request"
                ) from None
            except (DeadlineExpired, QueueFull, PoisonInput) as e:
                if isinstance(e, PoisonInput):
                    from .quarantine import get_quarantine

                    if not get_quarantine().enabled:
                        # With quarantine disabled there is no up-front
                        # gate to make the re-owned recompute cheap: each
                        # waiter would serially re-run the poison batch
                        # (plus a full bisection pass) at device cost. The
                        # verdict is payload-determined — identical bytes,
                        # identical poison — so share it instead.
                        raise
                # The OWNER was shed, ran out of ITS deadline budget, or
                # had its item isolated/quarantined as poison — none of
                # those verdicts are ours to replay as a cache answer.
                # Retire the failed flight (the owner's own cleanup may
                # not have run yet) and loop: re-probe, then race to
                # become the new owner. For poison that recompute is
                # cheap: the fingerprint is quarantined by now, so the
                # re-owning waiter is rejected before admission with the
                # real quarantine message.
                with self._lock:
                    if self._inflight.get(key) is flight:
                        self._inflight.pop(key)
                continue
            else:
                # Counted/marked only when the shared flight actually
                # SERVED this request — a waiter that re-owns after an
                # owner overload computes for itself and must not inflate
                # the absorption telemetry (or its response meta).
                self._count("coalesced", "cache_coalesced")
                _mark("coalesced")
                return clone(value) if clone else value
            finally:
                if wspan is not None:
                    wspan.end()
                with self._lock:
                    self._waiting -= 1
        # -- owner path
        self._count("misses", "cache_misses")
        fence = self.current_fence()
        try:
            value = None
            served_by_peer = False
            hook = self.peer_lookup
            if hook is not None:
                # Cross-host dedupe: ask the ring owner's cache before
                # burning device time. A hook failure of ANY kind is a
                # miss — federation must never break local serving.
                try:
                    served_by_peer, value = hook(key, payload)
                except Exception:  # noqa: BLE001 - peer lookup is best-effort
                    logger.exception("peer cache lookup failed; computing locally")
                    served_by_peer = False
            if served_by_peer:
                # Surfaces as ``cache_peer_hit`` response meta — the
                # client-observed proof that a duplicate cost no device
                # work anywhere in the fleet.
                _mark("peer_hit")
            else:
                value = compute()
        except BaseException as e:
            flight.set_exception(e)
            raise
        else:
            # Storing is best-effort and must never leave the flight
            # unresolved: a clone/pickle failure inside put() would
            # otherwise wedge every coalesced waiter on a Future nobody
            # will ever complete. The flight is resolved with a PRIVATE
            # copy when clone is set — the owner's caller owns `value` and
            # may mutate it the instant we return, racing waiters that
            # are still deep-copying the shared object.
            shared = value
            try:
                self.put(key, value, clone=clone, fence=fence)
                if clone is not None:
                    shared = clone(value)
            except Exception:  # noqa: BLE001 - caching must never break serving
                logger.exception("cache store failed; serving uncached")
            flight.set_result(shared)
            return value
        finally:
            # Object-guarded: a waiter that recovered from this flight's
            # overload failure may already own a NEW flight under the same
            # key — popping blindly would orphan its waiters into a
            # duplicate computation.
            with self._lock:
                if self._inflight.get(key) is flight:
                    self._inflight.pop(key)

    def peek_or_wait(self, key: str, wait_s: float = 0.0) -> tuple[bool, Any]:
        """Tier probe for the federation cache-lookup RPC: RAM-then-disk
        ``get``, and — when ``wait_s`` > 0 and an identical computation is
        in flight HERE — ride that flight instead of answering miss. This
        is what extends single-flight coalescing across the fleet: N hosts
        asking the owner for a key the owner is currently computing get
        ONE device submission total. Owner-overload failures on the flight
        (shed/deadline/poison) answer miss — those verdicts are the
        owner's, never the remote requester's."""
        found, value = self.get(key)
        if found or wait_s <= 0:
            return found, value
        with self._lock:
            flight = self._inflight.get(key)
        if flight is None:
            return False, None
        with self._lock:
            self._waiting += 1
        try:
            value = flight.result(timeout=min(wait_s, 86400.0))
        except BaseException:  # noqa: BLE001 - any flight failure is a miss here
            return False, None
        finally:
            with self._lock:
                self._waiting -= 1
        self._count("coalesced", "cache_coalesced")
        return True, value

    # -- invalidation ------------------------------------------------------

    def invalidate(self, prefix: str) -> int:
        """Drop every entry whose namespace starts with ``prefix`` (both
        tiers) and return how many RAM entries went. ``prefix`` is matched
        against the clear-text namespace half of the key, so
        ``invalidate("clip/")`` after a hot-swap clears every task and
        revision the swapped service ever served."""
        with self._lock:
            self._inval_seq += 1
            self._inval_marks[prefix] = self._inval_seq
            doomed = [k for k in self._entries if k.startswith(prefix)]
            for k in doomed:
                e = self._entries.pop(k)
                self._bytes -= e.nbytes
                self._account_locked(e.tenant, -e.nbytes)
                self._lru_forget_locked(e.tenant, k)
            # Retire matching in-flight computations too: a caller
            # arriving AFTER the invalidation must not coalesce onto a
            # pre-swap flight and be served the predecessor model's
            # output. Existing waiters keep their reference (they joined
            # pre-swap; the owner still resolves them), and the owner's
            # cleanup is object-guarded, so dropping the dict entry here
            # is safe.
            for k in [k for k in self._inflight if k.startswith(prefix)]:
                self._inflight.pop(k)
        if doomed:
            metrics.count("cache_invalidations", len(doomed))
        if self.disk_dir is not None:
            self._disk_invalidate(prefix)
        return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._tenant_bytes.clear()
            self._tenant_lru.clear()
            self._bytes = 0

    def close(self) -> None:
        metrics.unregister_gauges(self.name, self._gauge_fn)

    # -- RAM tier ----------------------------------------------------------

    def _accounting_tenant_locked(self, tenant: str) -> str:
        """Accounting identity for a stored entry, bounded at the same
        64-id cap as the quota/WFQ stat tables: overflow tenant ids
        collapse onto the shared ``_other`` identity. Without the cap a
        client spraying fabricated ``lumen-tenant`` ids would (a) shrink
        ``fair = max_bytes / #tenants`` until the legitimate largest
        tenant becomes the perpetual eviction victim while the
        ``cross_tenant_evictions`` watchdog stays silent, and (b) grow the
        ``bytes:{tenant}`` gauge payload without bound. Entries keep the
        identity they were stored under (it rides the ``_Entry``), so
        accounting stays consistent even as the mapping saturates."""
        if tenant in self._tenant_bytes or len(self._tenant_bytes) < _MAX_TENANT_STATS:
            return tenant
        return "_other"

    def _account_locked(self, tenant: str, delta: int) -> None:
        n = self._tenant_bytes.get(tenant, 0) + delta
        if n > 0:
            self._tenant_bytes[tenant] = n
        else:
            self._tenant_bytes.pop(tenant, None)

    def _lru_track_locked(self, tenant: str, key: str) -> None:
        self._tenant_lru.setdefault(tenant, OrderedDict())[key] = None

    def _lru_touch_locked(self, tenant: str, key: str) -> None:
        order = self._tenant_lru.get(tenant)
        if order is not None and key in order:
            order.move_to_end(key)

    def _lru_forget_locked(self, tenant: str, key: str) -> None:
        order = self._tenant_lru.get(tenant)
        if order is not None:
            order.pop(key, None)
            if not order:
                del self._tenant_lru[tenant]

    def _pop_victim_locked(self) -> _Entry:
        """Fair-share-first eviction: the victim is the least-recently-
        used entry of the tenant holding the MOST bytes. With one tenant
        (the common single-tenant deployment) this IS plain LRU. The
        largest tenant necessarily holds at least the mean share, so an
        under-fair-share tenant is never the victim — one tenant's churn
        cannot evict another's hot set. O(#tenants) via the per-tenant
        LRU mirror, never O(#entries)."""
        victim = None
        if len(self._tenant_bytes) > 1:
            fattest = max(self._tenant_bytes, key=self._tenant_bytes.get)
            order = self._tenant_lru.get(fattest)
            if order:  # accounting drift guard; always populated
                k = next(iter(order))
                victim = self._entries.pop(k)
                self._lru_forget_locked(fattest, k)
        if victim is None:
            k, victim = self._entries.popitem(last=False)
            self._lru_forget_locked(victim.tenant, k)
        self._bytes -= victim.nbytes
        self._account_locked(victim.tenant, -victim.nbytes)
        return victim

    def _store_ram(
        self, key: str, value: Any, nbytes: int, fence: int | None = None
    ) -> None:
        if self.max_bytes <= 0 or nbytes > self.max_bytes:
            return  # RAM tier off, or a single value that outweighs it
        tenant = key_tenant(key)
        evicted = 0
        cross = 0
        with self._lock:
            # Authoritative fence check, under the same lock invalidate()
            # sweeps with: either this insert lands before the sweep (and
            # is swept) or after the bump (and is rejected) — no window.
            if fence is not None and self._stale_locked(key, fence):
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
                self._account_locked(old.tenant, -old.nbytes)
                self._lru_forget_locked(old.tenant, key)
            tenant = self._accounting_tenant_locked(tenant)
            self._entries[key] = _Entry(value, nbytes, tenant)
            self._bytes += nbytes
            self._account_locked(tenant, nbytes)
            self._lru_track_locked(tenant, key)
            while self._bytes > self.max_bytes and self._entries:
                fair = self.max_bytes / max(1, len(self._tenant_bytes))
                victim = self._pop_victim_locked()
                evicted += 1
                if victim.tenant != tenant and (
                    self._tenant_bytes.get(victim.tenant, 0) + victim.nbytes < fair
                ):
                    # An under-fair-share tenant lost an entry to another
                    # tenant's store — the isolation violation the
                    # fair-share policy exists to prevent. Zero by
                    # construction; counted so the bench can prove it.
                    cross += 1
        if evicted:
            self.stats["evictions"] += evicted
            metrics.count("cache_evictions", evicted)
        if cross:
            self.stats["cross_tenant_evictions"] += cross
            metrics.count("cache_cross_tenant_evictions", cross)

    # -- disk tier ---------------------------------------------------------

    def _encode(self, value: Any) -> bytes | None:
        """Pickle once: the blob length is the (honest) RAM-tier weight and
        the blob itself is the disk-tier payload."""
        try:
            return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:  # noqa: BLE001 - caching must never break serving
            if not self._pickle_warned:
                self._pickle_warned = True
                logger.warning("unpicklable cache value (%s); not caching", e)
            return None

    def _disk_path(self, key: str) -> str:
        namespace, _, digest = key.rpartition(":")
        return os.path.join(self.disk_dir, quote(namespace, safe=""), digest + ".pkl")

    def _disk_read(self, key: str) -> tuple[bool, Any, int]:
        path = self._disk_path(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
            return True, pickle.loads(blob), len(blob)
        except FileNotFoundError:
            return False, None, 0
        except Exception as e:  # noqa: BLE001 - a corrupt file is a miss, not a crash
            logger.warning("cache disk read failed for %s: %s", path, e)
            try:
                os.unlink(path)
            except OSError:
                pass
            return False, None, 0

    def _disk_write(self, key: str, blob: bytes, fence: int | None = None) -> None:
        path = self._disk_path(key)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)  # atomic: readers never see a torn file
            # Post-replace fence: if an invalidation's rmtree swept this
            # namespace between our pre-checks and the replace, the file
            # just landed AFTER the sweep — undo it (the bump
            # happens-before the sweep, so a stale fence is visible here).
            if fence is not None and self._stale(key, fence):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        except OSError as e:
            logger.warning("cache disk write failed for %s: %s", path, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _disk_invalidate(self, prefix: str) -> None:
        import shutil

        try:
            subdirs = os.listdir(self.disk_dir)
        except OSError:
            return
        for sub in subdirs:
            if unquote(sub).startswith(prefix):
                shutil.rmtree(os.path.join(self.disk_dir, sub), ignore_errors=True)


# -- process-wide instance ---------------------------------------------------

_shared: ResultCache | None = None
_shared_lock = threading.Lock()


def get_result_cache() -> ResultCache:
    """The process-wide cache (lazily built from the env)."""
    global _shared
    if _shared is None:
        with _shared_lock:
            if _shared is None:
                _shared = ResultCache(name="result_cache")
    return _shared


def reset_result_cache() -> None:
    """Drop the shared cache (tests / clean shutdown); the next
    :func:`get_result_cache` rebuilds from the current env."""
    global _shared
    with _shared_lock:
        cache, _shared = _shared, None
    if cache is not None:
        cache.close()


def peer_export(key: str, wait_s: float = 0.0) -> bytes | None:
    """Wire-format (pickle) export of one entry for the federation
    cache-lookup RPC — ``None`` is a miss. Reads the shared cache WITHOUT
    instantiating one (a process that never cached owns nothing to
    export), honors the bounded flight wait (:meth:`ResultCache.peek_or_wait`),
    and answers miss for unpicklable values. Jax-free and cheap: this is
    answered by the hub router before any admission accounting."""
    with _shared_lock:
        cache = _shared
    if cache is None or not cache.enabled:
        return None
    found, value = cache.peek_or_wait(key, wait_s=wait_s)
    if not found:
        return None
    blob = cache._encode(value)
    if blob is not None:
        metrics.count("fed_cache_serves")
    return blob


def hot_keys(n: int = 8) -> list[str]:
    """Module-level hot-key manifest for the capacity gossip: the shared
    cache's MRU keys WITHOUT instantiating a cache that was never used
    (same posture as :func:`peer_export` — a process that never cached
    has nothing hot)."""
    with _shared_lock:
        cache = _shared
    if cache is None or not cache.enabled:
        return []
    return cache.hot_keys(n)


def peer_import(key: str, blob: bytes) -> bool:
    """Store a pickle blob pushed by the federation drain handoff (the
    write half of the peer-cache protocol; :func:`peer_export` is the
    read half). Unlike the export this DOES build the shared cache on
    first use — the push targets a ring successor that is about to
    inherit the drained host's arcs, and an empty cache is exactly the
    state the handoff exists to fix. Returns True when stored."""
    if not key or not blob:
        return False
    cache = get_result_cache()
    if not cache.enabled:
        return False
    try:
        value = pickle.loads(blob)
    except Exception as e:  # noqa: BLE001 - a bad peer blob is a no-op, not a crash
        logger.warning("federation cache import failed for %r: %s", key, e)
        return False
    cache.put(key, value)
    metrics.count("fed_cache_imports")
    return True


def detach_peer_lookup(hook) -> None:
    """Remove a federation peer-lookup hook IF it is still the installed
    one (server teardown; a later boot may have installed its own).
    Bound methods are compared by (__self__, __func__): CPython
    materializes a FRESH bound-method object per attribute access, so a
    plain ``is`` on ``manager.peer_cache_lookup`` never matches the one
    installed at boot — and a stale hook left behind would keep routing
    every cache miss at a torn-down fleet."""
    with _shared_lock:
        cache = _shared
    if cache is None:
        return
    cur = cache.peer_lookup
    if cur is None:
        return
    same = cur is hook or (
        getattr(cur, "__func__", None) is getattr(hook, "__func__", object())
        and getattr(cur, "__self__", None) is getattr(hook, "__self__", object())
    )
    if same:
        cache.peer_lookup = None


def invalidate_namespace(prefix: str) -> int:
    """Prefix-invalidate WITHOUT instantiating a cache that was never
    used: the hot-swap hook calls this unconditionally, and a process that
    never cached anything should not allocate one just to clear it."""
    with _shared_lock:
        cache = _shared
    return cache.invalidate(prefix) if cache is not None else 0
