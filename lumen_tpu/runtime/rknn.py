"""RKNN runtime placeholder.

The reference ships Rockchip-NPU (.rknn) execution as a Linux-only
optional backend and keeps a typed stub in every build so configs and
type checkers see the full interface
(``packages/lumen-clip/src/lumen_clip/backends/rknn_backend.py:32-87``).
This framework targets TPU: configs may *declare* ``runtime: rknn``
(the manifest schema, downloader patterns, and per-device file dicts all
support it, so one config can drive a mixed fleet), but this process
never executes .rknn graphs. The stub documents that contract and turns
an accidental attempt into a clear, typed error instead of a missing-
attribute crash deep in a manager.
"""

from __future__ import annotations


from ..core.config import ModelConfig

_MESSAGE = (
    "runtime 'rknn' is declared for model {model!r} (device {device!r}), but "
    "lumen-tpu executes models with JAX/XLA on TPU only.\n"
    "- .rknn graphs run on Rockchip NPUs via rknn-toolkit2; serve them with "
    "the reference's Linux/RKNN build on the edge device.\n"
    "- This config can still be used here: set runtime: jax for the "
    "service(s) this host should serve, and let the edge device consume the "
    "rknn entries (model_info.json carries per-device rknn file dicts "
    "either way).\n"
    "- The downloader DOES understand rknn entries, so `lumen-tpu-resources "
    "download` can pre-fetch edge bundles from this host."
)


class RknnBackend:
    """Typed placeholder mirroring the reference's RKNNBackend shim: the
    constructor raises immediately with the documented guidance."""

    def __init__(self, model_cfg: ModelConfig) -> None:
        raise ImportError(
            _MESSAGE.format(model=model_cfg.model, device=model_cfg.rknn_device)
        )


def require_executable_runtime(model_cfg: ModelConfig) -> None:
    """Gate used by the service ``from_config`` paths: every runtime this
    process can execute passes through; ``rknn`` raises the documented
    error (the reference raises ImportError from its stub constructor —
    same shape here)."""
    if model_cfg.runtime == "rknn":
        raise ImportError(
            _MESSAGE.format(model=model_cfg.model, device=model_cfg.rknn_device)
        )
