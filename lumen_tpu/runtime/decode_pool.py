"""Shared host-decode pool: the serving path's first lane.

BENCH_r05 showed the device ~100x ahead of the serving path (CLIP embeds
9k images/sec/chip device-only vs 77 rps through gRPC): the gap is host
serialization, and the first serialized step is image decode. Every gRPC
handler thread used to decode its own payload inline, so decode
concurrency was whatever the RPC thread pool happened to be — unbounded
CPU oversubscription under load, single-threaded decode under light
concurrency, and always on the thread that should be going straight back
to the batcher.

This module owns ONE process-wide sized pool that all decode/preprocess
work routes through: the four model managers' decode calls and the
:class:`~lumen_tpu.pipeline.ingest.IngestPipeline` producer's per-item
``decode``/``preprocess`` fan-out. It runs in one of two modes:

- **Thread mode** (``LUMEN_DECODE_WORKERS``; default ``cpu_count - 1``,
  floor 1): a sized :class:`ThreadPoolExecutor`. PIL and cv2 release the
  GIL for parts of a decode, but the surrounding Python (header probes,
  color conversion, numpy glue) does not — measured decode scaling
  plateaus well under the core count. This stays the default on small
  hosts and the tier-1 suite default.
- **Process mode** (``LUMEN_DECODE_PROCS``; unset = auto: ``cpu_count-1``
  workers when the host has >2 cores, else thread mode; ``0`` forces
  thread mode): decode **specs** (named, picklable-by-reference recipes
  from :mod:`lumen_tpu.utils.host_decode`) run in spawned worker
  processes — no GIL anywhere near the decode — and the decoded pixels
  come back through parent-owned shared-memory arena slots
  (:mod:`lumen_tpu.utils.shm_arena`), so the only pickle on the hop is
  a tuple of metadata. Arbitrary callables (``run``/``map``) still use
  the thread lane; a crashed worker fails its items as retryable sheds
  (:class:`QueueFull` — never a poison verdict) and the process pool is
  rebuilt on the next submission.

Queue-wait telemetry is exported as metrics gauges (``decode_pool``
provider: ``queue_depth``, ``wait_ms_p50``, arena accounting, spill and
crash counters), so an operator can see when the decode lane — not the
device — binds, and whether zero-copy transport is actually engaged.

Deliberately jax-free: the pool is pure host plumbing and must stay
importable from the serving layer without pulling in a backend.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterable

from ..utils import host_decode
from ..utils.deadline import DeadlineExpired, QueueFull, get_deadline
from ..utils.env import env_int
from ..utils.metrics import metrics
from ..utils.shm_arena import ShmArena
from . import telemetry
from .trace import current_trace

DECODE_WORKERS_ENV = "LUMEN_DECODE_WORKERS"
DECODE_PROCS_ENV = "LUMEN_DECODE_PROCS"


def decode_workers() -> int:
    """Thread-lane size: ``LUMEN_DECODE_WORKERS`` when set to a positive
    int, else ``cpu_count - 1`` with a floor of 1 — decode is CPU-bound,
    so the default claims every core but one, reserved for the thread
    that must keep draining the gRPC/batcher side (a decode lane that
    saturates ALL cores starves the very consumer it feeds)."""
    n = env_int(DECODE_WORKERS_ENV, 0)
    if n > 0:
        return n
    return max(1, (os.cpu_count() or 2) - 1)


def decode_procs() -> int:
    """Process-lane size: ``LUMEN_DECODE_PROCS`` (0 = thread mode). Unset
    means auto: ``cpu_count - 1`` worker processes when the host has more
    than 2 cores — where the GIL is the measured decode ceiling — and
    thread mode otherwise (on 1-2 cores the spawn/IPC overhead buys no
    parallelism back)."""
    n = env_int(DECODE_PROCS_ENV, None, minimum=0)
    if n is not None:
        return n
    cpus = os.cpu_count() or 1
    return max(1, cpus - 1) if cpus > 2 else 0


class DecodedTensor:
    """One decoded result: ``array`` (possibly a view over a shared-memory
    arena slot), optional ``extras`` provenance from the spec, and a
    ``release()`` the caller MUST invoke once the pixels have been
    consumed (stacked by the batcher / copied device-side) — it recycles
    the arena slot. No-op in thread mode and for spilled results."""

    __slots__ = ("array", "extras", "_release")

    def __init__(self, array, extras=None, release: Callable[[], None] | None = None):
        self.array = array
        self.extras = extras
        self._release = release

    def release(self) -> None:
        if self._release is not None:
            self._release()
            self._release = None


def _call_spec(spec: str, payload: bytes, params: dict | None):
    return host_decode.resolve_decode_spec(spec)(payload, dict(params or {}))


class DecodePool:
    """Sized decode pool with queue-wait telemetry and nested-call safety.

    ``run``/``map`` called FROM a pool worker thread execute inline — a
    pooled task that fans out again (e.g. an ingest ``decode`` that
    itself calls a manager helper) must not deadlock a fully-occupied
    pool waiting on itself.
    """

    def __init__(
        self,
        workers: int | None = None,
        name: str = "decode-pool",
        procs: int | None = None,
    ):
        self.workers = workers if workers and workers > 0 else decode_workers()
        self.procs = procs if procs is not None and procs >= 0 else decode_procs()
        self.name = name
        self._pool = ThreadPoolExecutor(self.workers, thread_name_prefix=name)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._pending = 0  # submitted, not yet started (queue depth)
        self._tasks = 0
        self._wait_ms: deque[float] = deque(maxlen=512)
        # Process lane (built lazily on first spec decode: spawning
        # workers costs ~0.5s each and a thread-mode-only deployment must
        # never pay it). The arena is parent-owned; workers only attach.
        self._proc_lock = threading.Lock()
        self._workers_cond = threading.Condition(self._proc_lock)
        self._proc_threads: ThreadPoolExecutor | None = None
        self._workers_idle: list[_PipeWorker] = []
        self._workers_all: set[_PipeWorker] = set()
        self._workers_alive = 0
        self._closed = False
        self._arena: ShmArena | None = None
        self._spills = 0
        self._crashes = 0
        self._crash_streak = 0
        # Gauges close over a weakref: the global metrics registry must not
        # be what keeps a dropped pool's threads reachable.
        ref = weakref.ref(self)

        def _gauges() -> dict:
            pool = ref()
            return {} if pool is None else pool.gauges()

        self._gauge_fn = _gauges
        metrics.register_gauges(name, _gauges)
        # Worker duty meter: per-task run time sums against the pool's
        # total decode concurrency (threads + worker processes), so
        # /stats reports the lane's busy fraction — the "is the host
        # decode lane the wall right now" signal — identically in both
        # modes.
        self._duty_name = f"decode:{name}"
        telemetry.set_capacity(self._duty_name, float(self.workers + self.procs))

    @property
    def process_mode(self) -> bool:
        return self.procs > 0

    # -- task plumbing -----------------------------------------------------

    def _task(
        self,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        t_submit: float,
        deadline: float | None,
        qspan=None,
        box: dict | None = None,
    ) -> Any:
        self._local.in_pool = True
        wait_ms = (time.perf_counter() - t_submit) * 1e3
        with self._lock:
            self._pending -= 1
            self._tasks += 1
            self._wait_ms.append(wait_ms)
        # Trace hand-off at the thread hop: the queue span (begun on the
        # submitting thread) ends here on the pool worker, and the run
        # span covers the decode itself.
        if qspan is not None:
            qspan.end()
        # Same contract as the batcher's pre-dispatch gate, one stage
        # earlier: a request whose deadline expired while it sat in the
        # decode queue must not burn a pool worker decoding an image
        # nobody is waiting for (under overload that's ALL the workers).
        if deadline is not None and time.monotonic() >= deadline:
            metrics.count("deadline_drops")
            metrics.count(f"deadline_drops:{self.name}")
            raise DeadlineExpired(
                f"{self.name}: request deadline expired while queued for decode"
            )
        # Worker busy accounting (per task, not per request-stage): the
        # run time sums into the ``decode:{pool}`` duty meter whatever
        # the tracing state is — duty cycles are always-on telemetry.
        t_run = time.monotonic()
        if qspan is None:
            try:
                return fn(*args, **kwargs)
            finally:
                telemetry.busy(self._duty_name, t_run, time.monotonic())
        rspan = qspan.trace.begin("decode", {"pool": self.name})
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:
            rspan.end(error=type(e).__name__)
            telemetry.busy(self._duty_name, t_run, time.monotonic())
            raise
        rspan.end()
        telemetry.busy(self._duty_name, t_run, time.monotonic())
        if box is not None:
            # Completion instant for the caller's ``decode.wake`` span —
            # written before _task returns, so run() can never read a
            # half-stamped box.
            box["settled"] = time.perf_counter()
        return result

    def submit(self, fn: Callable, *args, **kwargs) -> Future:
        # The ambient deadline is a contextvar of the CALLING thread;
        # capture it here, not in the worker. Same for the request trace:
        # the queue span must begin where the contextvar is visible.
        deadline = get_deadline()
        tr = current_trace()
        qspan = box = None
        if tr is not None:
            qspan = tr.begin("decode.queue", {"pool": self.name})
            box = {}
        with self._lock:
            self._pending += 1
        fut = self._pool.submit(
            self._task, fn, args, kwargs, time.perf_counter(), deadline, qspan, box
        )
        if tr is not None:
            fut._lumen_trace = tr
            fut._lumen_box = box
        return fut

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Run ``fn`` in the pool and wait for its result (exceptions
        propagate unchanged). Inline when already on a pool thread."""
        if getattr(self._local, "in_pool", False):
            return fn(*args, **kwargs)
        fut = self.submit(fn, *args, **kwargs)
        result = fut.result()
        # Attribution completeness: on a loaded host the worker finishing
        # and THIS thread resuming are milliseconds apart — charge that
        # scheduler gap to ``decode.wake`` instead of leaving it dark.
        box = getattr(fut, "_lumen_box", None)
        if box is not None and "settled" in box:
            fut._lumen_trace.add_span(
                "decode.wake", box["settled"], time.perf_counter()
            )
        return result

    def map(self, fn: Callable, items: Iterable) -> list:
        """Parallel map preserving input order (inline on a pool thread)."""
        if getattr(self._local, "in_pool", False):
            return [fn(item) for item in items]
        futs = [self.submit(fn, item) for item in items]
        return [f.result() for f in futs]

    # -- spec decode (thread OR process lane) ------------------------------

    def run_decode(
        self, spec: str, payload: bytes, params: dict | None = None
    ) -> DecodedTensor:
        """Run a **named decode spec** (:mod:`lumen_tpu.utils.host_decode`)
        and wait for its result. In process mode the decode runs in a
        worker process and the returned array is a zero-copy view over a
        shared-memory arena slot — the caller must ``release()`` the
        result once the pixels are consumed. Thread mode runs the exact
        same spec function on the thread lane (``release()`` is a no-op),
        so the two modes are bitwise-identical by construction."""
        if not self.process_mode:
            out = self.run(_call_spec, spec, payload, params)
            if isinstance(out, tuple):
                return DecodedTensor(out[0], out[1])
            return DecodedTensor(out)
        return self._proc_decode(spec, payload, params)

    def map_decode(
        self, spec: str, payloads: Iterable[bytes], params: dict | None = None
    ) -> list[DecodedTensor]:
        """Parallel :meth:`run_decode` preserving input order. On any
        per-item failure, already-materialized results are released and
        the error propagates — the caller never has to track half a
        batch's leases."""
        if not self.process_mode:
            outs = self.map(lambda p: _call_spec(spec, p, params), payloads)
            return [
                DecodedTensor(o[0], o[1]) if isinstance(o, tuple) else DecodedTensor(o)
                for o in outs
            ]
        submitted = [self._proc_submit(spec, p, params) for p in payloads]
        results: list[DecodedTensor] = []
        try:
            for entry in submitted:
                results.append(self._proc_settle(*entry))
        except BaseException:
            for r in results:
                r.release()
            raise
        return results

    def _proc_lane(self) -> ThreadPoolExecutor:
        """The process lane's parent-side plumbing, built lazily: a small
        executor of pure-I/O threads (each one blocks on one worker
        process's pipe for the duration of a decode) plus the shared
        arena. Worker PROCESSES themselves are spawned on demand up to
        ``procs`` and recycled across requests."""
        with self._proc_lock:
            if self._proc_threads is None:
                self._proc_threads = ThreadPoolExecutor(
                    self.procs, thread_name_prefix=f"{self.name}-procio"
                )
                self._arena = ShmArena(name=self.name.replace("-", ""))
            return self._proc_threads

    def _checkout_worker(self) -> "_PipeWorker":
        spawn = False
        with self._workers_cond:
            while True:
                # A mid-wait downgrade (crash streak) or pool close must
                # fail waiters rather than park them forever: both paths
                # notify_all, and the re-check here turns the wake into a
                # retryable shed (the retry lands on the thread lane).
                if self._closed or self.procs <= 0:
                    raise _WorkerDied("decode process lane closed")
                if self._workers_idle:
                    return self._workers_idle.pop()
                if self._workers_alive < self.procs:
                    self._workers_alive += 1
                    spawn = True
                    break
                self._workers_cond.wait()
        try:
            w = _PipeWorker()
        except BaseException as e:
            with self._workers_cond:
                self._workers_alive -= 1
                self._workers_cond.notify()
            raise _WorkerDied(f"decode worker spawn failed: {e}") from e
        assert spawn
        with self._workers_cond:
            self._workers_all.add(w)
        return w

    def _checkin_worker(self, w: "_PipeWorker", died: bool) -> None:
        with self._workers_cond:
            if died:
                self._workers_alive -= 1
                self._workers_all.discard(w)
            else:
                self._workers_idle.append(w)
            self._workers_cond.notify()
        if died:
            w.close()

    def _proc_request(self, spec, payload, params, slot, deadline):
        """One decode round-trip to a worker process (runs on a procio
        thread). Worker checkout blocks when all ``procs`` workers are
        busy — that wait IS the process lane's queue, and the worker's
        own pickup stamp measures it."""
        w = self._checkout_worker()
        died = False
        try:
            return w.request((
                spec, payload, params,
                slot.name if slot is not None else None,
                slot.capacity if slot is not None else 0,
                deadline,
            ))
        except _WorkerDied:
            died = True
            raise
        finally:
            self._checkin_worker(w, died)

    def _proc_submit(self, spec: str, payload: bytes, params: dict | None):
        """Submit one spec decode to the process lane. Returns everything
        :meth:`_proc_settle` needs to finish the hop on the caller side."""
        deadline = get_deadline()
        tr = current_trace()
        lane = self._proc_lane()
        slot = self._arena.acquire(
            host_decode.spec_est_nbytes(spec, payload, params or {})
        )
        with self._lock:
            self._pending += 1
        t_submit = time.perf_counter()
        try:
            fut = lane.submit(
                self._proc_request, spec, bytes(payload), params, slot, deadline
            )
        except BaseException:
            with self._lock:
                self._pending -= 1
            if slot is not None:
                slot.release()
            raise
        return (fut, slot, t_submit, tr)

    def _proc_settle(self, fut, slot, t_submit, tr) -> DecodedTensor:
        try:
            res = fut.result()
        except _WorkerDied as e:
            self._proc_account(t_submit, None)
            if slot is not None:
                slot.release()
            raise self._proc_crashed(e) from e
        # Worker pickup stamp per response shape (queue-wait gauge twin
        # of the thread lane's submit->start measurement).
        t0 = {"deadline": 1, "shm": 4, "raw": 3}.get(res[0])
        self._proc_account(t_submit, res[t0] if t0 is not None else None)
        if res[0] == "error":
            # The spec itself raised IN the worker (undecodable payload,
            # unknown spec): re-raise with thread-lane shapes — a
            # ValueError is the decode contract's own verdict, anything
            # else a plain crash. Not a worker-health event.
            if slot is not None:
                slot.release()
            _, kind, msg = res
            if kind == "ValueError":
                raise ValueError(msg)
            raise RuntimeError(f"decode worker: {kind}: {msg}")
        with self._lock:
            self._crash_streak = 0
        if res[0] == "deadline":
            if slot is not None:
                slot.release()
            metrics.count("deadline_drops")
            metrics.count(f"deadline_drops:{self.name}")
            raise DeadlineExpired(
                f"{self.name}: request deadline expired while queued for decode"
            )
        if res[0] == "shm":
            _, shape, dtype, extras, t0_pc, t1_pc, t0_m, t1_m = res
            self._proc_telemetry(tr, t_submit, t0_pc, t1_pc, t0_m, t1_m)
            return DecodedTensor(slot.view(shape, dtype), extras, slot.release)
        # "raw": output did not fit the slot (or the arena declined one) —
        # the array crossed pickled. Correct, observable, not zero-copy.
        _, arr, extras, t0_pc, t1_pc, t0_m, t1_m = res
        if slot is not None:
            slot.release()
        with self._lock:
            self._spills += 1
        metrics.count("decode_shm_spills")
        self._proc_telemetry(tr, t_submit, t0_pc, t1_pc, t0_m, t1_m)
        return DecodedTensor(arr, extras)

    def _proc_account(self, t_submit: float, t_pickup: float | None) -> None:
        """Queue-depth/wait bookkeeping for one settled process task —
        wait is measured submit -> worker pickup, directly comparable
        across processes (CLOCK_MONOTONIC is machine-wide on Linux)."""
        wait_ms = 0.0 if t_pickup is None else max(0.0, (t_pickup - t_submit) * 1e3)
        with self._lock:
            self._pending -= 1
            self._tasks += 1
            self._wait_ms.append(wait_ms)

    def _proc_telemetry(self, tr, t_submit, t0_pc, t1_pc, t0_m, t1_m) -> None:
        """Duty-meter credit + trace spans for a process-lane decode,
        stitched from the worker's clock stamps so ``decode.queue`` /
        ``decode`` / ``decode.wake`` report identically to thread mode
        (the PR 6 cross-thread contract, extended across the process
        hop)."""
        telemetry.busy(self._duty_name, t0_m, t1_m)
        if tr is None:
            return
        meta = {"pool": self.name, "proc": "1"}
        tr.add_span("decode.queue", t_submit, t0_pc, meta)
        tr.add_span("decode", t0_pc, t1_pc, meta)
        tr.add_span("decode.wake", t1_pc, time.perf_counter(), meta)

    def _proc_crashed(self, cause: BaseException) -> QueueFull:
        """A worker process died mid-decode. The payload gets NO verdict —
        a crashed codec says nothing about the bytes (contrast
        PoisonInput, which requires sibling evidence) — so the item fails
        as a retryable shed; the dead worker was already discarded and
        the next request simply spawns a fresh one (siblings keep
        serving throughout). A streak of crashes with no successful
        decode in between means the environment, not a payload, is
        broken: downgrade to thread mode instead of thrashing respawn
        loops."""
        with self._lock:
            self._crashes += 1
            self._crash_streak += 1
            streak = self._crash_streak
        metrics.count("decode_proc_crashes")
        if streak >= 3 and self.procs > 0:
            import logging

            logging.getLogger(__name__).warning(
                "%s: %d consecutive decode-worker crashes; downgrading to "
                "thread mode", self.name, streak,
            )
            self.procs = 0
            # The duty meter's capacity was registered as workers + procs;
            # the lane just shrank to threads only — re-declare it or
            # /stats understates decode busy by the dead procs forever.
            telemetry.set_capacity(self._duty_name, float(self.workers))
            with self._workers_cond:
                self._workers_cond.notify_all()
        return QueueFull(
            f"{self.name}: decode worker process died mid-decode ({cause}); "
            "a fresh worker will serve the retry"
        )

    def _proc_decode(self, spec: str, payload: bytes, params: dict | None) -> DecodedTensor:
        entry = self._proc_submit(spec, payload, params)
        out = self._proc_settle(*entry)
        return out

    # -- telemetry ---------------------------------------------------------

    def wait_ms_p50(self) -> float:
        with self._lock:
            sample = sorted(self._wait_ms)
        return sample[len(sample) // 2] if sample else 0.0

    def gauges(self) -> dict:
        with self._lock:
            pending, tasks = self._pending, self._tasks
            spills, crashes = self._spills, self._crashes
        # Numeric-only: the metrics registry drops non-numeric gauge
        # values at snapshot (Prometheus exposition contract), so the
        # mode flag is an int and the arena block is flattened with an
        # ``arena_`` prefix — the accounting invariant (acquired ==
        # recycled, live == 0 at drain) must be visible on /metrics.
        out = {
            "workers": self.workers,
            "queue_depth": pending,
            "tasks": tasks,
            "wait_ms_p50": round(self.wait_ms_p50(), 3),
            "process_mode": int(self.process_mode),
            "procs": self.procs,
        }
        if spills:
            out["shm_spills"] = spills
        if crashes:
            out["proc_crashes"] = crashes
        arena = self._arena
        if arena is not None:
            out.update({f"arena_{k}": v for k, v in arena.stats().items()})
        return out

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        with self._workers_cond:
            self._closed = True
            workers = list(self._workers_all)
            self._workers_all.clear()
            self._workers_idle.clear()
            self._workers_cond.notify_all()
        for w in workers:
            w.close()
        if self._proc_threads is not None:
            self._proc_threads.shutdown(wait=False)
        if self._arena is not None:
            self._arena.close()
        metrics.unregister_gauges(self.name, self._gauge_fn)


class _WorkerDied(Exception):
    """A decode worker process exited (or its pipe broke) mid-request."""


class _PipeWorker:
    """Parent-side handle for one decode worker subprocess. The child
    runs :func:`lumen_tpu.utils.host_decode.worker_main` — it imports
    exactly that jax-free module (numpy + cv2/PIL), never the parent's
    ``__main__``, never jax. One request is in flight at a time; the
    pool checks workers out per request and recycles them, so a worker's
    module imports are paid once per process lifetime."""

    def __init__(self):
        import subprocess
        import sys

        env = dict(os.environ)
        # lumen_tpu's import root (works from a checkout or site-packages).
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(host_decode.__file__)))
        )
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        self.proc = subprocess.Popen(
            [
                sys.executable, "-c",
                "from lumen_tpu.utils.host_decode import worker_main; worker_main()",
            ],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            env=env,
        )

    def request(self, task: tuple):
        import pickle
        import struct

        try:
            blob = pickle.dumps(task, protocol=pickle.HIGHEST_PROTOCOL)
            self.proc.stdin.write(struct.pack("<Q", len(blob)))
            self.proc.stdin.write(blob)
            self.proc.stdin.flush()
            hdr = self.proc.stdout.read(8)
            if len(hdr) < 8:
                raise _WorkerDied(f"worker exited (rc={self.proc.poll()})")
            (n,) = struct.unpack("<Q", hdr)
            data = self.proc.stdout.read(n)
            if len(data) < n:
                raise _WorkerDied("worker pipe truncated mid-response")
            return pickle.loads(data)
        except (BrokenPipeError, OSError) as e:
            raise _WorkerDied(str(e)) from e

    def close(self) -> None:
        try:
            self.proc.stdin.close()  # EOF = clean shutdown request
        except Exception:  # noqa: BLE001
            pass
        try:
            self.proc.wait(timeout=0.5)
        except Exception:  # noqa: BLE001
            try:
                self.proc.kill()
                self.proc.wait(timeout=0.5)
            except Exception:  # noqa: BLE001
                pass


_shared: DecodePool | None = None
_shared_lock = threading.Lock()


def get_decode_pool() -> DecodePool:
    """The process-wide pool (lazily built from the env)."""
    global _shared
    if _shared is None:
        with _shared_lock:
            if _shared is None:
                _shared = DecodePool(name="decode_pool")
    return _shared


def shutdown_decode_pool() -> None:
    """Drop the shared pool (tests / clean process exit); the next
    :func:`get_decode_pool` builds a fresh one from the current env."""
    global _shared
    with _shared_lock:
        pool, _shared = _shared, None
    if pool is not None:
        pool.close()
