"""Shared host-decode thread pool: the serving path's first lane.

BENCH_r05 showed the device ~100x ahead of the serving path (CLIP embeds
9k images/sec/chip device-only vs 77 rps through gRPC): the gap is host
serialization, and the first serialized step is image decode. Every gRPC
handler thread used to decode its own payload inline, so decode
concurrency was whatever the RPC thread pool happened to be — unbounded
CPU oversubscription under load, single-threaded decode under light
concurrency, and always on the thread that should be going straight back
to the batcher.

This module owns ONE process-wide sized pool (``LUMEN_DECODE_WORKERS``;
default ``min(cpu_count, 16)``) that all decode/preprocess work routes
through: the four model managers' ``decode_image_bytes`` calls and the
:class:`~lumen_tpu.pipeline.ingest.IngestPipeline` producer's per-item
``decode``/``preprocess`` fan-out. PIL and cv2 release the GIL during
decode and the native host-ops resize is GIL-free, so pool workers scale
with cores. Queue-wait telemetry is exported as metrics gauges
(``decode_pool`` provider: ``queue_depth``, ``wait_ms_p50``, ...), so an
operator can see when the decode lane — not the device — binds.

Deliberately jax-free: the pool is pure host plumbing and must stay
importable from the serving layer without pulling in a backend.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterable

from ..utils.deadline import DeadlineExpired, get_deadline
from ..utils.env import env_int
from ..utils.metrics import metrics
from . import telemetry
from .trace import current_trace

DECODE_WORKERS_ENV = "LUMEN_DECODE_WORKERS"


def decode_workers() -> int:
    """Pool size: ``LUMEN_DECODE_WORKERS`` when set to a positive int,
    else ``min(cpu_count, 16)`` (decode is CPU-bound; past the core count
    extra workers only add context switches)."""
    n = env_int(DECODE_WORKERS_ENV, 0)
    if n > 0:
        return n
    return min(os.cpu_count() or 4, 16)


class DecodePool:
    """Sized thread pool with queue-wait telemetry and nested-call safety.

    ``run``/``map`` called FROM a pool worker execute inline — a pooled
    task that fans out again (e.g. an ingest ``decode`` that itself calls
    a manager helper) must not deadlock a fully-occupied pool waiting on
    itself.
    """

    def __init__(self, workers: int | None = None, name: str = "decode-pool"):
        self.workers = workers if workers and workers > 0 else decode_workers()
        self.name = name
        self._pool = ThreadPoolExecutor(self.workers, thread_name_prefix=name)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._pending = 0  # submitted, not yet started (queue depth)
        self._tasks = 0
        self._wait_ms: deque[float] = deque(maxlen=512)
        # Gauges close over a weakref: the global metrics registry must not
        # be what keeps a dropped pool's threads reachable.
        ref = weakref.ref(self)

        def _gauges() -> dict:
            pool = ref()
            return {} if pool is None else pool.gauges()

        self._gauge_fn = _gauges
        metrics.register_gauges(name, _gauges)
        # Worker duty meter: per-task run time sums against a capacity of
        # ``workers``, so /stats reports the pool's busy fraction — the
        # "is the host decode lane the wall right now" signal.
        self._duty_name = f"decode:{name}"
        telemetry.set_capacity(self._duty_name, float(self.workers))

    # -- task plumbing -----------------------------------------------------

    def _task(
        self,
        fn: Callable,
        args: tuple,
        kwargs: dict,
        t_submit: float,
        deadline: float | None,
        qspan=None,
        box: dict | None = None,
    ) -> Any:
        self._local.in_pool = True
        wait_ms = (time.perf_counter() - t_submit) * 1e3
        with self._lock:
            self._pending -= 1
            self._tasks += 1
            self._wait_ms.append(wait_ms)
        # Trace hand-off at the thread hop: the queue span (begun on the
        # submitting thread) ends here on the pool worker, and the run
        # span covers the decode itself.
        if qspan is not None:
            qspan.end()
        # Same contract as the batcher's pre-dispatch gate, one stage
        # earlier: a request whose deadline expired while it sat in the
        # decode queue must not burn a pool worker decoding an image
        # nobody is waiting for (under overload that's ALL the workers).
        if deadline is not None and time.monotonic() >= deadline:
            metrics.count("deadline_drops")
            metrics.count(f"deadline_drops:{self.name}")
            raise DeadlineExpired(
                f"{self.name}: request deadline expired while queued for decode"
            )
        # Worker busy accounting (per task, not per request-stage): the
        # run time sums into the ``decode:{pool}`` duty meter whatever
        # the tracing state is — duty cycles are always-on telemetry.
        t_run = time.monotonic()
        if qspan is None:
            try:
                return fn(*args, **kwargs)
            finally:
                telemetry.busy(self._duty_name, t_run, time.monotonic())
        rspan = qspan.trace.begin("decode", {"pool": self.name})
        try:
            result = fn(*args, **kwargs)
        except BaseException as e:
            rspan.end(error=type(e).__name__)
            telemetry.busy(self._duty_name, t_run, time.monotonic())
            raise
        rspan.end()
        telemetry.busy(self._duty_name, t_run, time.monotonic())
        if box is not None:
            # Completion instant for the caller's ``decode.wake`` span —
            # written before _task returns, so run() can never read a
            # half-stamped box.
            box["settled"] = time.perf_counter()
        return result

    def submit(self, fn: Callable, *args, **kwargs) -> Future:
        # The ambient deadline is a contextvar of the CALLING thread;
        # capture it here, not in the worker. Same for the request trace:
        # the queue span must begin where the contextvar is visible.
        deadline = get_deadline()
        tr = current_trace()
        qspan = box = None
        if tr is not None:
            qspan = tr.begin("decode.queue", {"pool": self.name})
            box = {}
        with self._lock:
            self._pending += 1
        fut = self._pool.submit(
            self._task, fn, args, kwargs, time.perf_counter(), deadline, qspan, box
        )
        if tr is not None:
            fut._lumen_trace = tr
            fut._lumen_box = box
        return fut

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Run ``fn`` in the pool and wait for its result (exceptions
        propagate unchanged). Inline when already on a pool thread."""
        if getattr(self._local, "in_pool", False):
            return fn(*args, **kwargs)
        fut = self.submit(fn, *args, **kwargs)
        result = fut.result()
        # Attribution completeness: on a loaded host the worker finishing
        # and THIS thread resuming are milliseconds apart — charge that
        # scheduler gap to ``decode.wake`` instead of leaving it dark.
        box = getattr(fut, "_lumen_box", None)
        if box is not None and "settled" in box:
            fut._lumen_trace.add_span(
                "decode.wake", box["settled"], time.perf_counter()
            )
        return result

    def map(self, fn: Callable, items: Iterable) -> list:
        """Parallel map preserving input order (inline on a pool thread)."""
        if getattr(self._local, "in_pool", False):
            return [fn(item) for item in items]
        futs = [self.submit(fn, item) for item in items]
        return [f.result() for f in futs]

    # -- telemetry ---------------------------------------------------------

    def wait_ms_p50(self) -> float:
        with self._lock:
            sample = sorted(self._wait_ms)
        return sample[len(sample) // 2] if sample else 0.0

    def gauges(self) -> dict:
        with self._lock:
            pending, tasks = self._pending, self._tasks
        return {
            "workers": self.workers,
            "queue_depth": pending,
            "tasks": tasks,
            "wait_ms_p50": round(self.wait_ms_p50(), 3),
        }

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        metrics.unregister_gauges(self.name, self._gauge_fn)


_shared: DecodePool | None = None
_shared_lock = threading.Lock()


def get_decode_pool() -> DecodePool:
    """The process-wide pool (lazily built from the env)."""
    global _shared
    if _shared is None:
        with _shared_lock:
            if _shared is None:
                _shared = DecodePool(name="decode_pool")
    return _shared


def shutdown_decode_pool() -> None:
    """Drop the shared pool (tests / clean process exit); the next
    :func:`get_decode_pool` builds a fresh one from the current env."""
    global _shared
    with _shared_lock:
        pool, _shared = _shared, None
    if pool is not None:
        pool.close()
