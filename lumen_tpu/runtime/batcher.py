"""Micro-batching queue: the core TPU throughput mechanism.

The reference serves exactly one payload per ONNX session call
(`SURVEY.md` §2.8 "Batching"); on TPU that strands the MXU. This batcher
sits between gRPC worker threads and a jit-compiled model function:

- callers ``submit()`` single items and block on a future,
- a collector thread drains the queue until ``max_batch`` items or
  ``max_latency_ms`` elapsed since the first item,
- items are stacked, padded to a static *bucket* size (so XLA compiles one
  program per bucket, not per batch size), and DISPATCHED as one device
  call — JAX dispatch is async, so the collector hands the un-fetched
  result to a bounded in-flight deque and immediately goes back to
  collecting,
- a fetch/settle worker drains the deque in dispatch order: ONE blocking
  device->host transfer per batch (``jax.device_get`` on the whole result
  tree), then the rows are scattered back to the callers.

The two lanes overlap: batch *k+1* is being collected, stacked, and
dispatched while batch *k* computes on device and its transfer completes.
``LUMEN_BATCH_INFLIGHT`` (default 2) bounds how many dispatched-but-
unfetched batches may pile up — enough to hide the transfer latency,
small enough that a slow consumer exerts backpressure on collection
instead of queueing unbounded device results in HBM.

Shape buckets default to powers of two up to ``max_batch``; a warmup call
per bucket at startup turns the reference's "model load time" into our
"compile time" (SURVEY.md §7 hard part 2).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, InvalidStateError as futures_InvalidState, TimeoutError as FuturesTimeout
from typing import Any, Callable

import jax
import numpy as np

from ..utils.deadline import DeadlineExpired, QueueFull, get_deadline, remaining
from ..utils.metrics import metrics

logger = logging.getLogger(__name__)


def default_buckets(max_batch: int) -> list[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def mesh_buckets(max_batch: int, dp: int) -> list[int]:
    """Batch-size buckets for a data-parallel mesh: every bucket must be a
    multiple of the ``data`` axis size so the leading dim shards evenly."""
    if dp <= 1:
        return default_buckets(max_batch)
    max_batch = max(max_batch, dp)
    if max_batch % dp:
        max_batch = ((max_batch // dp) + 1) * dp
    return [dp * b for b in default_buckets(max_batch // dp)]


def mesh_sharded(fn, mesh):
    """Wrap a ``fn(batched_tree, n)`` so the stacked batch is placed with a
    ``data``-axis sharding before the device call (serving-side DP: one
    micro-batch spreads across all mesh devices). Both the ``device_put``
    and the wrapped call dispatch async — the wrapper returns un-fetched
    results, which is exactly what the pipelined collector wants."""
    from .mesh import data_sharding

    sharding = data_sharding(mesh)

    def wrapped(tree, n):
        tree = jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
        return fn(tree, n)

    return wrapped


def warmup_batcher(batcher: "MicroBatcher", make_dummy: Callable[[int], Any]) -> None:
    """Compile every bucket through the batcher's OWN callable — the same
    code path real traffic takes, so the compile cache is guaranteed to hit
    (a hand-rolled warmup twin could silently drift from the serving fn).
    Batcher fns dispatch async (the fetch worker owns the blocking
    transfer), so block here: warmup must not return with compiles queued."""
    for b in batcher.buckets:
        jax.block_until_ready(batcher.fn(make_dummy(b), b))


def batch_wait_timeout() -> float:
    """Default seconds a caller waits on a batched-call future — must
    tolerate a cold bucket compile through the tunnel (see
    :meth:`MicroBatcher.__call__`). ``LUMEN_BATCH_TIMEOUT_S`` overrides."""
    try:
        return float(os.environ.get("LUMEN_BATCH_TIMEOUT_S", "300"))
    except ValueError:
        return 300.0


def batch_queue_depth() -> int:
    """Default queue-depth limit for admission control:
    ``LUMEN_BATCH_QUEUE_DEPTH`` (0 / unset / malformed = unbounded, the
    pre-resilience behavior)."""
    try:
        return max(0, int(os.environ.get("LUMEN_BATCH_QUEUE_DEPTH", "0")))
    except ValueError:
        return 0


def batch_inflight() -> int:
    """Default bound on dispatched-but-unfetched batches:
    ``LUMEN_BATCH_INFLIGHT`` (default 2 — one computing, one settling;
    1 = no dispatch pipelining, malformed = default)."""
    try:
        return max(1, int(os.environ.get("LUMEN_BATCH_INFLIGHT", "2")))
    except ValueError:
        return 2


def _settle(fut: Future, result: Any = None, exception: BaseException | None = None) -> bool:
    """Resolve a caller future, tolerating the cancel race: a
    deadline-bounded caller may cancel() between the collector's state
    check and its set — set_result/set_exception on a cancelled Future
    raises InvalidStateError, which must not kill the collector thread.
    Returns True when the future was actually settled."""
    if fut.cancelled():
        return False
    try:
        if exception is not None:
            fut.set_exception(exception)
        else:
            fut.set_result(result)
        return True
    except futures_InvalidState:
        return False


def bucket_for(n: int, buckets: list[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


class _Inflight:
    """One dispatched-but-unfetched batch riding the in-flight deque."""

    __slots__ = ("futures", "result", "n", "size")

    def __init__(self, futures: list[Future], result: Any, n: int, size: int):
        self.futures = futures
        self.result = result  # un-fetched device result tree
        self.n = n
        self.size = size


class MicroBatcher:
    """Batch single-item pytrees through a batched function.

    ``fn(batched_tree, n_valid) -> batched_result_tree`` where every leaf of
    ``batched_tree`` has a leading bucket-size dim; the result's leaves must
    share that leading dim (rows past ``n_valid`` are padding and dropped).

    ``fn`` should DISPATCH and return without fetching (return the jax
    arrays as-is — no ``np.asarray``): the fetch/settle worker performs the
    one blocking device->host transfer per batch, so up to ``inflight``
    batches compute while the collector stacks the next one. A blocking
    ``fn`` still works (numpy trees pass through the fetch untouched); it
    just forfeits the overlap.
    """

    def __init__(
        self,
        fn: Callable[[Any, int], Any],
        max_batch: int = 8,
        max_latency_ms: float = 5.0,
        buckets: list[int] | None = None,
        name: str = "batcher",
        max_queue: int | None = None,
        inflight: int | None = None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.fn = fn
        self.max_batch = max_batch
        self.max_latency_s = max_latency_ms / 1e3
        self.buckets = sorted(buckets) if buckets else default_buckets(max_batch)
        if self.buckets[-1] < max_batch:
            self.buckets.append(max_batch)
        self.name = name
        # Admission control: bound the number of waiting items so overload
        # becomes explicit shed errors (callers can back off) instead of an
        # unbounded queue whose latency grows without limit. 0 = unbounded.
        self.max_queue = batch_queue_depth() if max_queue is None else max(0, max_queue)
        self.inflight = batch_inflight() if inflight is None else max(1, inflight)
        self._queue: queue.Queue[tuple[Any, Future, float | None] | None] = queue.Queue()
        self._thread: threading.Thread | None = None
        self._fetch_thread: threading.Thread | None = None
        self._closed = threading.Event()
        # Guards the closed-check + enqueue pair in submit() against a
        # concurrent close() draining the queue in between.
        self._submit_lock = threading.Lock()
        # Dispatched-but-unfetched batches, FIFO (dispatch order == settle
        # order); the condition variable carries both the bound (collector
        # waits when full) and the fetch hand-off (worker waits when empty).
        self._inflight: deque[_Inflight] = deque()
        self._inflight_cv = threading.Condition()
        self._fetch_stop = False
        # Telemetry for capability metadata / benchmarks.
        self.stats = {"batches": 0, "items": 0, "padded": 0, "shed": 0, "expired": 0}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._fetch_thread = threading.Thread(
            target=self._fetch_loop, name=f"{self.name}-fetch", daemon=True
        )
        self._thread.start()
        self._fetch_thread.start()
        # Live state on /metrics: queue depth + batch/padding telemetry
        # (latency histograms can't show a backed-up or waste-heavy queue).
        # The provider closes over a weakref so the global registry never
        # pins a dropped batcher (and its captured params) in memory.
        ref = weakref.ref(self)

        def _gauges() -> dict:
            b = ref()
            if b is None:
                return {}
            return {
                **b.stats,
                "queue_depth": b._queue.qsize(),
                "inflight": len(b._inflight),
                "inflight_limit": b.inflight,
            }

        self._gauge_fn = _gauges
        metrics.register_gauges(f"batcher:{self.name}", _gauges)
        return self

    def close(self) -> None:
        with self._submit_lock:
            if self._closed.is_set():
                return
            self._closed.set()
            # The sentinel lands after any already-submitted item, so the
            # collector's drain pass sees them all.
            self._queue.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        # Stop the fetch worker only AFTER the collector exits: every batch
        # it dispatched must still settle (in-flight results drain; the
        # worker's loop runs until the deque is empty AND stop is set).
        with self._inflight_cv:
            self._fetch_stop = True
            self._inflight_cv.notify_all()
        if self._fetch_thread:
            self._fetch_thread.join(timeout=60)
            # A fetch worker killed by an escaping BaseException leaves its
            # in-flight batches unsettled, and after close() nothing else
            # will ever settle them — drain here so close() upholds the
            # "every dispatched batch settles" contract even when the
            # settling lane itself died. Guarded on death: a merely-slow
            # worker (join timed out) keeps ownership of its entries.
            if not self._fetch_thread.is_alive():
                with self._inflight_cv:
                    stranded = list(self._inflight)
                    self._inflight.clear()
                if stranded:
                    err = RuntimeError(
                        f"{self.name}: fetch worker died; batcher closed "
                        "with unsettled in-flight batches"
                    )
                    logger.error("%s", err)
                    for entry in stranded:
                        for f in entry.futures:
                            _settle(f, exception=err)
        # Ownership-guarded: a newer same-name batcher keeps its gauges.
        # A never-started instance has no _gauge_fn — it must not pass
        # None (= unconditional) and evict a live same-name batcher's.
        if fn := getattr(self, "_gauge_fn", None):
            metrics.unregister_gauges(f"batcher:{self.name}", fn)

    # -- client side ------------------------------------------------------

    def submit(self, item: Any, deadline: float | None = None) -> Future:
        """Enqueue one item. ``deadline`` is an absolute ``time.monotonic()``
        instant; unset, it is inherited from the ambient request context
        (:func:`lumen_tpu.utils.deadline.get_deadline`, installed by the
        gRPC layer from ``context.time_remaining()``). Expired entries are
        dropped before the device call instead of burning a batch slot.

        Raises :class:`QueueFull` when ``max_queue`` items are already
        waiting (load shed — the caller should surface a retryable
        RESOURCE_EXHAUSTED-style error) and :class:`DeadlineExpired` when
        the deadline has already passed at submit time."""
        if deadline is None:
            deadline = get_deadline()
        if deadline is not None and time.monotonic() >= deadline:
            self.stats["expired"] += 1
            metrics.count("deadline_drops")
            metrics.count(f"deadline_drops:{self.name}")
            raise DeadlineExpired(f"{self.name}: request deadline already expired at submit")
        fut: Future = Future()
        with self._submit_lock:
            if self._closed.is_set():
                raise RuntimeError(f"{self.name} is closed")
            if self.max_queue and self._queue.qsize() >= self.max_queue:
                self.stats["shed"] += 1
                metrics.count("sheds")
                metrics.count(f"sheds:{self.name}")
                raise QueueFull(
                    f"{self.name}: admission queue full ({self.max_queue} waiting); request shed"
                )
            self._queue.put((item, fut, deadline))
        return fut

    def __call__(self, item: Any, timeout: float | None = None) -> Any:
        """Submit and wait. The default wait must tolerate a cold XLA
        compile of a new bucket THROUGH the axon tunnel (observed >60s on
        a v5e: the first on-chip gRPC bench died on exactly this) — the
        client's own RPC deadline, not this timeout, bounds user-visible
        latency. ``LUMEN_BATCH_TIMEOUT_S`` overrides; unset → 300s. An
        ambient request deadline, when sooner, bounds the wait instead
        (no point blocking a gRPC thread past its caller's hangup)."""
        if timeout is None:
            timeout = batch_wait_timeout()
        rem = remaining()
        deadline_bounded = rem is not None and rem < timeout
        if deadline_bounded:
            timeout = max(rem, 0.0)
        fut = self.submit(item)
        try:
            return fut.result(timeout=timeout)
        except FuturesTimeout:
            if not deadline_bounded:
                raise
            # The caller's deadline — not the batch-wait budget — expired.
            # Cancel so the collector skips the dead entry (when it hasn't
            # started) and surface the wire-mappable deadline error, not a
            # generic timeout that reads as a handler crash.
            if fut.cancel():
                self.stats["expired"] += 1
                metrics.count("deadline_drops")
                metrics.count(f"deadline_drops:{self.name}")
            raise DeadlineExpired(
                f"{self.name}: request deadline expired while waiting for a batch slot"
            ) from None

    # -- collector thread -------------------------------------------------

    def _run(self) -> None:
        while not self._closed.is_set():
            first = self._queue.get()
            if first is None:
                break
            batch = [first]
            deadline = time.monotonic() + self.max_latency_s
            while len(batch) < self.max_batch:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._closed.set()
                    break
                batch.append(nxt)
            self._dispatch(batch)
        # Drain anything left after close.
        while True:
            try:
                entry = self._queue.get_nowait()
            except queue.Empty:
                break
            if entry is not None:
                _settle(entry[1], exception=RuntimeError(f"{self.name} closed"))

    def _dispatch(self, batch: list[tuple[Any, Future, float | None]]) -> None:
        # Reserve an in-flight slot FIRST: this wait is where the collector
        # blocks under backpressure (possibly for a full device-batch
        # latency), so it must come before the deadline gate — an entry
        # whose deadline expires while we wait here still gets dropped
        # below instead of burning the batch it no longer wants. Exactness:
        # at most `inflight` un-fetched device results exist at any instant
        # (the HBM bound an operator sizes against), and inflight=1 really
        # does serialize dispatch. Only this thread appends, so reserving
        # by waiting for space cannot race another producer.
        dead = False
        with self._inflight_cv:
            while len(self._inflight) >= self.inflight:
                # A dead fetch worker can never drain the deque: fail
                # loudly instead of wedging the collector (and every
                # caller) in a silent 300s-timeout limbo.
                if self._fetch_thread is not None and not self._fetch_thread.is_alive():
                    dead = True
                    break
                self._inflight_cv.wait(timeout=1.0)
        if dead:
            self._abort_dead_fetch([fut for _, fut, _ in batch])
            return
        # Deadline gate: entries whose caller deadline passed while they
        # queued are failed here — BEFORE stacking and the device call — so
        # an overloaded server does not spend TPU time computing answers
        # nobody is waiting for (their gRPC stream is already torn down).
        # The gate runs per dispatch even with earlier batches still in
        # flight: a deadline that expires while batch k computes still
        # drops the k+1 entry it covers.
        live: list[tuple[Any, Future]] = []
        now = time.monotonic()
        for item, fut, deadline in batch:
            if fut.cancelled():
                # The waiting caller already gave up (and accounted the
                # drop); counting here too would double-book the event.
                continue
            if deadline is not None and now >= deadline:
                if _settle(
                    fut,
                    exception=DeadlineExpired(
                        f"{self.name}: deadline expired while queued"
                    ),
                ):
                    self.stats["expired"] += 1
                    metrics.count("deadline_drops")
                    metrics.count(f"deadline_drops:{self.name}")
            else:
                live.append((item, fut))
        if not live:
            return
        items = [b[0] for b in live]
        futures = [b[1] for b in live]
        n = len(items)
        size = bucket_for(n, self.buckets)
        try:
            from ..testing.faults import faults

            # No-op unless a test/harness armed the point; lets the suite
            # exercise the fan-out-failure path below deterministically.
            # With inflight > 1 an injected failure lands on exactly this
            # batch's callers — earlier in-flight batches settle normally.
            faults.check("batch_execute", self.name)
            stacked = stack_and_pad(items, size)
            result = self.fn(stacked, n)  # async dispatch; fetch worker settles
        except Exception as e:  # noqa: BLE001 - fan the failure out to callers
            logger.exception("%s: batched dispatch failed (n=%d)", self.name, n)
            for f in futures:
                _settle(f, exception=e)
            return
        with self._inflight_cv:
            if self._fetch_thread is not None and not self._fetch_thread.is_alive():
                dead = True  # nobody left to settle this result
            else:
                self._inflight.append(_Inflight(futures, result, n, size))
                self._inflight_cv.notify_all()
        if dead:
            self._abort_dead_fetch(futures)

    def _abort_dead_fetch(self, futures: list[Future]) -> None:
        """The fetch worker died (a BaseException escaped its loop):
        settle its stranded in-flight batches AND the current batch with a
        loud error — callers must not ride out the full batch-wait timeout
        for results that can never arrive."""
        err = RuntimeError(
            f"{self.name}: fetch worker died; batcher cannot settle results"
        )
        logger.error("%s", err)
        with self._inflight_cv:
            stranded = list(self._inflight)
            self._inflight.clear()
            self._inflight_cv.notify_all()
        for entry in stranded:
            for f in entry.futures:
                _settle(f, exception=err)
        for f in futures:
            _settle(f, exception=err)

    # -- fetch/settle worker ----------------------------------------------

    def _fetch_loop(self) -> None:
        """Drain the in-flight deque in dispatch order: one blocking
        device->host transfer per batch, then settle that batch's futures
        (submission order within the batch). Runs until close() has both
        stopped the collector and set the stop flag — every dispatched
        batch settles before close() returns."""
        while True:
            with self._inflight_cv:
                while not self._inflight:
                    # Exit only once close() asked AND the collector can no
                    # longer dispatch (its thread is dead) — a collector
                    # stuck past close()'s join timeout in a long compile
                    # must still get its final batch settled, not orphaned.
                    if self._fetch_stop:
                        if not (self._thread and self._thread.is_alive()):
                            return
                        self._inflight_cv.wait(timeout=0.05)
                    else:
                        self._inflight_cv.wait()
                # Peek — the entry leaves the deque only after its fetch
                # completes, so the in-flight bound counts batches whose
                # device work (or transfer) is genuinely outstanding.
                entry = self._inflight[0]
            try:
                rows = unstack(entry.result, entry.n)
            except Exception as e:  # noqa: BLE001 - fan out to THIS batch only
                logger.exception(
                    "%s: batched fetch failed (n=%d)", self.name, entry.n
                )
                for f in entry.futures:
                    _settle(f, exception=e)
            else:
                self.stats["batches"] += 1
                self.stats["items"] += entry.n
                self.stats["padded"] += entry.size - entry.n
                for f, row in zip(entry.futures, rows):
                    _settle(f, result=row)
            with self._inflight_cv:
                self._inflight.popleft()
                self._inflight_cv.notify_all()


# -- pytree stacking helpers ------------------------------------------------


def stack_and_pad(items: list[Any], size: int) -> Any:
    """Stack a list of same-structure pytrees into one tree with leading dim
    ``size``; rows past ``len(items)`` repeat the last item (repeating keeps
    padding numerically harmless for ops like softmax over the batch)."""
    n = len(items)
    pad = size - n

    def stack(*leaves):
        arrs = [np.asarray(x) for x in leaves]
        if pad:
            arrs = arrs + [arrs[-1]] * pad
        return np.stack(arrs)

    return jax.tree_util.tree_map(stack, *items)


def unstack(tree: Any, n: int) -> list[Any]:
    """Split a batched result tree back into ``n`` single-item trees (host
    numpy). ``jax.device_get`` on the WHOLE tree makes one blocking
    transfer per batch (a per-leaf ``np.asarray`` loop would round-trip
    the device once per leaf — the fetch worker calls this on every
    settled batch, so the difference is on the serving hot path); numpy
    and array-like leaves pass through as plain arrays."""
    tree = jax.device_get(tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [
        jax.tree_util.tree_unflatten(treedef, [leaf[i] for leaf in leaves])
        for i in range(n)
    ]
