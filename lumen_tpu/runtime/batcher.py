"""Micro-batching queue: the core TPU throughput mechanism.

The reference serves exactly one payload per ONNX session call
(`SURVEY.md` §2.8 "Batching"); on TPU that strands the MXU. This batcher
sits between gRPC worker threads and a jit-compiled model function:

- callers ``submit()`` single items and block on a future,
- a collector thread drains the queue until ``max_batch`` items or the
  collection window closes. The window is ADAPTIVE by default
  (``LUMEN_BATCH_ADAPTIVE``): an EWMA of the submit arrival rate predicts
  how long the rest of the batch takes to arrive — the wait stretches
  (bounded by ``LUMEN_BATCH_WINDOW_MS``, default the fixed
  ``max_latency_ms``) when traffic can fill ``max_batch`` and collapses to
  ~0 for a lone request. Batch fill is exported as the
  ``batch-occupancy:<name>`` gauge provider (mean fill % against
  ``max_batch`` + per-bucket batch counts),
- items are stacked into reusable per-bucket staging arenas (no per-batch
  allocation on the hot path), padded to a static *bucket* size (so XLA
  compiles one program per bucket, not per batch size), and DISPATCHED as
  one device call — JAX dispatch is async, so the collector hands the
  un-fetched result to a bounded in-flight deque and immediately goes back
  to collecting,
- a fetch/settle worker drains the deque in dispatch order: ONE blocking
  device->host transfer per batch (``jax.device_get`` on the whole result
  tree), then the rows are scattered back to the callers.

The two lanes overlap: batch *k+1* is being collected, stacked, and
dispatched while batch *k* computes on device and its transfer completes.
``LUMEN_BATCH_INFLIGHT`` (default 2) bounds how many dispatched-but-
unfetched batches may pile up — enough to hide the transfer latency,
small enough that a slow consumer exerts backpressure on collection
instead of queueing unbounded device results in HBM.

Shape buckets default to powers of two up to ``max_batch``; a warmup call
per bucket at startup turns the reference's "model load time" into our
"compile time" (SURVEY.md §7 hard part 2).

Fault containment (three mechanisms, all per-batcher):

- **batch bisection** — a failing batch of N no longer fails all N
  callers: the two halves are re-dispatched (synchronously, bounded by
  ``LUMEN_BISECT_DEPTH`` levels) until the offending item(s) are
  isolated. Innocent co-batched requests get their real results; only the
  poison items fail (:class:`~lumen_tpu.utils.deadline.PoisonInput`), and
  their fingerprints land in the quarantine registry so repeats are
  rejected before admission. When NO item in the failing batch succeeds,
  the failure is the device's, not an input's — everyone gets the original
  error and nothing is quarantined.
- **quarantine rejection** — ``submit(fingerprint=...)`` consults
  :mod:`~lumen_tpu.runtime.quarantine` before the admission queue: a
  known-poison payload costs a dict lookup, never a batch slot.
- **watchdog** — with ``LUMEN_BATCH_WATCHDOG_S`` set (>0; 0 = off, the
  CPU/test default), a monitor thread fails any single device dispatch or
  fetch that exceeds the budget: pending futures get
  :class:`~lumen_tpu.utils.deadline.WatchdogTimeout`, queued and in-flight
  work is drained loudly, and the batcher refuses new submits instead of
  wedging — mirroring the dead-fetch-worker containment.

Multi-tenant QoS: the admission queue is tenant-aware by default
(``LUMEN_QOS``, :mod:`~lumen_tpu.runtime.qos`) — per-(tenant, lane)
sub-queues popped by virtual-time weighted-fair queuing, interactive
outranking bulk, with the bulk lane browning out first under sustained
pressure. ``QueueFull`` sheds carry the queue depth and a drain-time
estimate from the measured service rate, so clients (and the serving
layer's ``lumen-retry-after-ms`` hint) back off proportionally.
"""

from __future__ import annotations

import logging
import math
import os
import queue
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, InvalidStateError as futures_InvalidState, TimeoutError as FuturesTimeout
from contextlib import contextmanager
from typing import Any, Callable

import jax
import numpy as np

from ..utils.deadline import (
    DeadlineExpired,
    PoisonInput,
    QueueFull,
    WatchdogTimeout,
    get_deadline,
    remaining,
)
from ..utils.env import env_float, env_int
from ..utils.metrics import metrics
from . import telemetry
from .qos import WFQAdmissionQueue, wfq_enabled
from .quarantine import QuarantineRegistry, get_quarantine
from .trace import current_trace

logger = logging.getLogger(__name__)


def _end_trace_spans(fut: Future) -> None:
    """Done-callback backstop for the request-trace span handles riding a
    caller future: whatever settles the future (fetch worker, bisection,
    watchdog, close-time drain, a caller's cancel) also closes its open
    spans — ``SpanHandle.end`` is idempotent, so the explicit ends on the
    happy path stay authoritative and this only catches the error lanes."""
    if getattr(fut, "_lumen_settled", None) is None:
        fut._lumen_settled = time.perf_counter()  # cancel path: no _settle ran
    if fut.cancelled():
        err: str | None = "cancelled"
    else:
        e = fut.exception()
        err = type(e).__name__ if e is not None else None
    for attr in ("_lumen_collect", "_lumen_device"):
        h = getattr(fut, attr, None)
        if h is not None:
            h.end(error=err)


def default_buckets(max_batch: int) -> list[int]:
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return out


def mesh_buckets(max_batch: int, dp: int) -> list[int]:
    """Batch-size buckets for a data-parallel mesh: every bucket must be a
    multiple of the ``data`` axis size so the leading dim shards evenly."""
    if dp <= 1:
        return default_buckets(max_batch)
    max_batch = max(max_batch, dp)
    if max_batch % dp:
        max_batch = ((max_batch // dp) + 1) * dp
    return [dp * b for b in default_buckets(max_batch // dp)]


def mesh_sharded(fn, mesh):
    """Wrap a ``fn(batched_tree, n)`` so the stacked batch is placed with a
    ``data``-axis sharding before the device call (serving-side DP: one
    micro-batch spreads across all mesh devices). Both the ``device_put``
    and the wrapped call dispatch async — the wrapper returns un-fetched
    results, which is exactly what the pipelined collector wants."""
    from .mesh import data_sharding

    sharding = data_sharding(mesh)

    def wrapped(tree, n):
        tree = jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
        return fn(tree, n)

    return wrapped


def warmup_batcher(batcher: "MicroBatcher", make_dummy: Callable[[int], Any]) -> None:
    """Compile every bucket through the batcher's OWN callable — the same
    code path real traffic takes, so the compile cache is guaranteed to hit
    (a hand-rolled warmup twin could silently drift from the serving fn).
    Batcher fns dispatch async (the fetch worker owns the blocking
    transfer), so block here: warmup must not return with compiles queued."""
    for b in batcher.buckets:
        jax.block_until_ready(batcher.fn(make_dummy(b), b))


def batch_adaptive() -> bool:
    """``LUMEN_BATCH_ADAPTIVE`` (default on): the collector's wait window
    tracks the measured arrival rate instead of sitting at the fixed
    ``max_latency_ms`` — stretched (bounded by ``LUMEN_BATCH_WINDOW_MS``)
    when traffic can fill ``max_batch``, collapsed to ~0 when idle.
    ``0`` restores the fixed window everywhere."""
    return os.environ.get("LUMEN_BATCH_ADAPTIVE", "1") != "0"


def batch_window_ms() -> float | None:
    """``LUMEN_BATCH_WINDOW_MS``: upper bound on the adaptive collection
    window. Unset/malformed = each batcher's own ``max_latency_ms`` (the
    adaptive controller then never waits LONGER than the fixed window did,
    only shorter); explicit values let an operator stretch the window past
    the fixed default when occupancy matters more than tail latency."""
    return env_float("LUMEN_BATCH_WINDOW_MS", None, minimum=0.0)


class AdaptiveWindow:
    """EWMA arrival-rate controller for the collector's batch window.

    ``observe()`` is called at every ``submit()`` (cheap: one EWMA update
    under the submit lock the caller already holds is avoided — this has
    its own tiny lock so hot submitters don't serialize on the collector).
    ``window_s(have)`` answers: with ``have`` items already collected, how
    long is it worth waiting for the rest of the batch?

    - **No history yet** → the fixed window (cold start must not dispatch
      singletons before the rate is known).
    - **Idle** (inter-arrival EWMA beyond ``IDLE_FACTOR`` caps) → ~0: a
      lone request pays dispatch latency, not a window it cannot fill.
      The factor matters: closed-loop callers (a worker pool that submits
      the next item when the previous settles) measure an arrival
      interval ≈ the service interval, slightly ABOVE a tight cap — that
      is a convoy to coalesce, not idleness.
    - **Traffic** → the predicted time for the REST of the batch to
      arrive, clamped to the cap: a saturating producer fills ``max_batch``
      and the window never stretches past ``cap_s``.

    ``clock`` is injectable for deterministic tests."""

    #: "idle" = the next arrival is expected beyond this many cap-widths
    #: away; between 1 and this, waiting one cap still buys co-batching.
    IDLE_FACTOR = 8.0
    #: multiplier on the predicted fill time: the EWMA is a point estimate
    #: and closed-loop arrival jitter is on the order of the interval
    #: itself — without headroom the window closes exactly when the last
    #: item was DUE, losing it to the next batch half the time. Bounded by
    #: the cap either way, so tail latency is unchanged.
    HEADROOM = 2.0

    __slots__ = ("max_batch", "cap_s", "fixed_s", "alpha", "clock", "_interval", "_last", "_lock")

    def __init__(
        self,
        max_batch: int,
        cap_s: float,
        fixed_s: float,
        alpha: float = 0.2,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.max_batch = max_batch
        self.cap_s = cap_s
        self.fixed_s = fixed_s
        self.alpha = alpha
        self.clock = clock
        self._interval: float | None = None  # EWMA inter-arrival seconds
        self._last: float | None = None
        self._lock = threading.Lock()

    def observe(self) -> None:
        now = self.clock()
        with self._lock:
            if self._last is not None:
                # Clamp a long idle gap to 2x the idle threshold before
                # folding it in: the gap still reads as idle (above the
                # window_s threshold), but resumed traffic needs ~3
                # observations to decay back under it instead of ~20 —
                # one 10s pause must not make the next burst dispatch as
                # singletons while a poisoned EWMA recovers.
                dt = min(now - self._last, self.cap_s * self.IDLE_FACTOR * 2)
                self._interval = (
                    dt
                    if self._interval is None
                    else (1.0 - self.alpha) * self._interval + self.alpha * dt
                )
            self._last = now

    def window_s(self, have: int) -> float:
        with self._lock:
            interval = self._interval
        if interval is None:
            return min(self.fixed_s, self.cap_s) if self.cap_s > 0 else self.fixed_s
        if self.cap_s <= 0:
            return 0.0
        if interval > self.cap_s * self.IDLE_FACTOR:
            return 0.0  # idle: the next arrival is nowhere near
        need = max(0, self.max_batch - have)
        return min(self.cap_s, need * interval * self.HEADROOM)


class _Occupancy:
    """Batch-fill telemetry: mean fill % against ``max_batch`` plus a
    per-bucket batch count, exported as the ``batch-occupancy:<name>``
    gauge provider. A fixed-window batcher under bursty traffic shows its
    padding waste here; the adaptive window's whole point is making this
    gauge read high under load."""

    __slots__ = ("max_batch", "batches", "items", "by_bucket", "_lock")

    def __init__(self, max_batch: int):
        self.max_batch = max_batch
        self.batches = 0
        self.items = 0
        self.by_bucket: dict[int, int] = {}
        self._lock = threading.Lock()

    def record(self, n: int, size: int) -> None:
        with self._lock:
            self.batches += 1
            self.items += n
            self.by_bucket[size] = self.by_bucket.get(size, 0) + 1

    def gauges(self) -> dict:
        with self._lock:
            if not self.batches:
                return {"batches": 0, "items": 0, "mean_fill_pct": 0.0}
            out = {
                "batches": self.batches,
                "items": self.items,
                "mean_fill_pct": round(
                    100.0 * self.items / (self.batches * self.max_batch), 1
                ),
                "mean_items": round(self.items / self.batches, 2),
            }
            for size, count in sorted(self.by_bucket.items()):
                out[f"bucket_{size}"] = count
            return out


class _DrainRate:
    """EWMA of settled items/second — the service-rate signal behind the
    ``QueueFull`` drain-time estimate. A shed used to say only "queue
    full"; with this, the error (and the ``lumen-retry-after-ms`` hint the
    serving layer derives from it) says *when the backlog will clear*, so
    clients back off proportionally instead of guessing."""

    __slots__ = ("alpha", "_rate", "_last", "_lock")

    #: inter-settle gaps above this are idle time, not service time — an
    #: unclamped 5-minute lull before a burst would read as a ~0 rate and
    #: tell the burst's shed clients to come back in minutes for a queue
    #: that drains in under a second (same idiom as AdaptiveWindow's
    #: idle-gap clamp). Clamping only ever UNDER-estimates drain time,
    #: and an early retry is a cheap O(1) shed.
    MAX_GAP_S = 5.0
    #: hint ceiling: past this the estimate is stale-rate noise, and a
    #: retry-after floor of minutes hurts more than an extra shed.
    MAX_ESTIMATE_S = 30.0

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self._rate: float | None = None  # items/second
        self._last: float | None = None
        self._lock = threading.Lock()

    def record(self, n: int) -> None:
        now = time.monotonic()
        with self._lock:
            if self._last is not None:
                dt = min(now - self._last, self.MAX_GAP_S)
                if dt > 1e-6:
                    inst = n / dt
                    self._rate = (
                        inst
                        if self._rate is None
                        else (1.0 - self.alpha) * self._rate + self.alpha * inst
                    )
            self._last = now

    def estimate_s(self, queued: int) -> float | None:
        """Seconds to drain ``queued`` items at the measured service rate
        (capped at :data:`MAX_ESTIMATE_S`); ``None`` before any rate is
        known (cold batcher)."""
        with self._lock:
            rate = self._rate
        if rate is None or rate <= 0:
            return None
        return min(queued / rate, self.MAX_ESTIMATE_S)


def batch_wait_timeout() -> float:
    """Default seconds a caller waits on a batched-call future — must
    tolerate a cold bucket compile through the tunnel (see
    :meth:`MicroBatcher.__call__`). ``LUMEN_BATCH_TIMEOUT_S`` overrides."""
    return env_float("LUMEN_BATCH_TIMEOUT_S", 300.0)


def batch_queue_depth() -> int:
    """Default queue-depth limit for admission control:
    ``LUMEN_BATCH_QUEUE_DEPTH`` (0 / unset = unbounded, the
    pre-resilience behavior; a malformed value degrades to unbounded WITH
    a one-shot warning — a typo'd depth limit must not silently remove
    admission control)."""
    return env_int("LUMEN_BATCH_QUEUE_DEPTH", 0, minimum=0)


def batch_inflight() -> int:
    """Default bound on dispatched-but-unfetched batches:
    ``LUMEN_BATCH_INFLIGHT`` (default 2 — one computing, one settling;
    1 = no dispatch pipelining, malformed = default)."""
    return env_int("LUMEN_BATCH_INFLIGHT", 2, minimum=1)


def bisect_depth_default(max_batch: int) -> int:
    """Default batch-bisection depth: ``LUMEN_BISECT_DEPTH`` when set
    (0 disables bisection — a failing batch fans out to every caller, the
    pre-containment behavior); otherwise ``ceil(log2(max_batch))``, enough
    to isolate a single poison item out of a full batch."""
    raw = env_int("LUMEN_BISECT_DEPTH", None, minimum=0)
    if raw is not None:
        return raw
    return max(1, math.ceil(math.log2(max(2, max_batch))))


def batch_watchdog_s() -> float:
    """``LUMEN_BATCH_WATCHDOG_S``: seconds one device dispatch or fetch
    may run before the watchdog fails the batch and disables the batcher
    (0 / unset / malformed = off — the CPU/test default; on TPU, size it
    above the worst warmed-bucket batch latency, and remember a cold
    compile through a tunnel can take >60s: warm up first)."""
    return env_float("LUMEN_BATCH_WATCHDOG_S", 0.0, minimum=0.0)


def _settle(fut: Future, result: Any = None, exception: BaseException | None = None) -> bool:
    """Resolve a caller future, tolerating the cancel race: a
    deadline-bounded caller may cancel() between the collector's state
    check and its set — set_result/set_exception on a cancelled Future
    raises InvalidStateError, which must not kill the collector thread.
    Returns True when the future was actually settled."""
    # Settle instant for the traced caller's ``batch.wake`` span — stamped
    # BEFORE set_result because the waiter wakes before done-callbacks run.
    if getattr(fut, "_lumen_trace", None) is not None:
        fut._lumen_settled = time.perf_counter()
    if fut.cancelled():
        return False
    try:
        if exception is not None:
            fut.set_exception(exception)
        else:
            fut.set_result(result)
        return True
    except futures_InvalidState:
        return False


def bucket_for(n: int, buckets: list[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def wait_for_batch(fut: Future, name: str, stats: dict, timeout: float | None = None) -> Any:
    """Wait on a batcher future — the blocking half of
    ``MicroBatcher.__call__``, shared with the replica fleet's routed
    dispatch (``ReplicaSet.__call__`` submits through whichever replica
    the policy picked and waits here with that replica's name/stats).

    The default wait must tolerate a cold XLA compile of a new bucket
    THROUGH the axon tunnel (observed >60s on a v5e: the first on-chip
    gRPC bench died on exactly this) — the client's own RPC deadline, not
    this timeout, bounds user-visible latency. ``LUMEN_BATCH_TIMEOUT_S``
    overrides; unset → 300s. An ambient request deadline, when sooner,
    bounds the wait instead (no point blocking a gRPC thread past its
    caller's hangup)."""
    if timeout is None:
        timeout = batch_wait_timeout()
    rem = remaining()
    deadline_bounded = rem is not None and rem < timeout
    if deadline_bounded:
        timeout = max(rem, 0.0)
    try:
        result = fut.result(timeout=timeout)
        # Close the span handles HERE, not only in the done-callback:
        # set_result wakes this waiter BEFORE callbacks run, so the
        # request could otherwise finish its trace while the fetch
        # worker is still descheduled — dropping the device span from
        # exactly the slow trace being captured. end() is idempotent;
        # whichever side runs first wins.
        if getattr(fut, "_lumen_trace", None) is not None:
            _end_trace_spans(fut)
            # Attribution completeness: on a loaded host the gap
            # between the fetch worker settling the future and THIS
            # thread being rescheduled is real milliseconds — charge
            # it to ``batch.wake`` instead of leaving it dark.
            settled = getattr(fut, "_lumen_settled", None)
            if settled is not None:
                fut._lumen_trace.add_span("batch.wake", settled, time.perf_counter())
        return result
    except FuturesTimeout:
        if not deadline_bounded:
            raise
        # The caller's deadline — not the batch-wait budget — expired.
        # Cancel so the collector skips the dead entry (when it hasn't
        # started) and surface the wire-mappable deadline error, not a
        # generic timeout that reads as a handler crash.
        if fut.cancel():
            stats["expired"] += 1
            metrics.count("deadline_drops")
            metrics.count(f"deadline_drops:{name}")
        raise DeadlineExpired(
            f"{name}: request deadline expired while waiting for a batch slot"
        ) from None
    except BaseException:
        # Settled-with-exception path (poison, watchdog, shed at
        # dispatch...): same span-close determinism as the success
        # path — the error verdict must reach the trace before the
        # request finishes it.
        if fut.done() and getattr(fut, "_lumen_trace", None) is not None:
            _end_trace_spans(fut)
        raise


class _Inflight:
    """One dispatched-but-unfetched batch riding the in-flight deque.
    ``entries`` keeps the (item, future, fingerprint) triples so a
    fetch-time failure can still bisect (re-dispatching needs the host
    items, which are tiny next to the device result they produced).
    ``arena`` lists the staging buffers the batch was stacked into (when
    the collector's reusable arenas were used) so the fetch path can
    detect — and copy out of — a result that aliases them."""

    __slots__ = ("futures", "result", "n", "size", "entries", "arena", "t_dispatch")

    def __init__(
        self,
        futures: list[Future],
        result: Any,
        n: int,
        size: int,
        entries: list[tuple] | None = None,
        arena: list | None = None,
        t_dispatch: float = 0.0,
    ):
        self.futures = futures
        self.result = result  # un-fetched device result tree
        self.n = n
        self.size = size
        self.entries = entries or []
        self.arena = arena
        # Dispatch instant (monotonic): the fetch worker credits the
        # dispatch->settle envelope to the ``device:{name}`` duty meter —
        # the same envelope the ``batch.device`` trace span covers.
        self.t_dispatch = t_dispatch


#: live batchers by name (weakrefs — the registry must not pin a dropped
#: batcher): the autopilot's window loop and any future controller read
#: the process's batcher population from here, the same idiom as
#: ``utils/qos.py``'s WFQ-queue registry.
_batcher_registry: dict[str, "weakref.ref[MicroBatcher]"] = {}
_batcher_reg_lock = threading.Lock()


def live_batchers() -> list["MicroBatcher"]:
    """Every started, not-yet-closed MicroBatcher in the process (dead
    refs are pruned on the way out)."""
    with _batcher_reg_lock:
        items = list(_batcher_registry.items())
    out: list[MicroBatcher] = []
    for name, ref in items:
        b = ref()
        if b is None:
            with _batcher_reg_lock:
                if _batcher_registry.get(name) is ref:
                    del _batcher_registry[name]
        elif not b._closed.is_set():
            out.append(b)
    return out


class MicroBatcher:
    """Batch single-item pytrees through a batched function.

    ``fn(batched_tree, n_valid) -> batched_result_tree`` where every leaf of
    ``batched_tree`` has a leading bucket-size dim; the result's leaves must
    share that leading dim (rows past ``n_valid`` are padding and dropped).

    ``fn`` should DISPATCH and return without fetching (return the jax
    arrays as-is — no ``np.asarray``): the fetch/settle worker performs the
    one blocking device->host transfer per batch, so up to ``inflight``
    batches compute while the collector stacks the next one. A blocking
    ``fn`` still works (numpy trees pass through the fetch untouched); it
    just forfeits the overlap.
    """

    def __init__(
        self,
        fn: Callable[[Any, int], Any],
        max_batch: int = 8,
        max_latency_ms: float = 5.0,
        buckets: list[int] | None = None,
        name: str = "batcher",
        max_queue: int | None = None,
        inflight: int | None = None,
        bisect_depth: int | None = None,
        watchdog_s: float | None = None,
        quarantine: QuarantineRegistry | None = None,
        adaptive: bool | None = None,
        window_ms: float | None = None,
        clock: Callable[[], float] = time.monotonic,
        replica: str | None = None,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.fn = fn
        # Replica tag when this batcher is one slice of a ReplicaSet
        # (runtime/fleet.py): rides the ``batch.device`` trace span so a
        # slow trace names the chip slice that served it.
        self.replica = replica
        self.max_batch = max_batch
        self.max_latency_s = max_latency_ms / 1e3
        self.buckets = sorted(buckets) if buckets else default_buckets(max_batch)
        if self.buckets[-1] < max_batch:
            self.buckets.append(max_batch)
        self.name = name
        # Admission control: bound the number of waiting items so overload
        # becomes explicit shed errors (callers can back off) instead of an
        # unbounded queue whose latency grows without limit. 0 = unbounded.
        self.max_queue = batch_queue_depth() if max_queue is None else max(0, max_queue)
        self.inflight = batch_inflight() if inflight is None else max(1, inflight)
        # Containment: bisection depth (0 = off), watchdog budget (0 = off)
        # and the quarantine registry isolated offenders land in (None =
        # the process-wide one, resolved lazily so tests can reset it).
        self.bisect_depth = (
            bisect_depth_default(max_batch) if bisect_depth is None else max(0, bisect_depth)
        )
        self.watchdog_s = batch_watchdog_s() if watchdog_s is None else max(0.0, watchdog_s)
        self._quarantine = quarantine
        # Adaptive collection window: the EWMA controller replaces the
        # fixed wait when enabled (LUMEN_BATCH_ADAPTIVE, default on); the
        # cap is LUMEN_BATCH_WINDOW_MS or this batcher's own fixed window.
        self.adaptive = batch_adaptive() if adaptive is None else adaptive
        cap_ms = batch_window_ms() if window_ms is None else max(0.0, window_ms)
        self.window_cap_s = (cap_ms / 1e3) if cap_ms is not None else self.max_latency_s
        # The configured cap, remembered: the autopilot's window loop
        # retunes window_cap_s around this anchor and returns to it when
        # padding waste clears (never drifting from an already-drifted
        # value).
        self.base_window_cap_s = self.window_cap_s
        self._clock = clock
        self._window = AdaptiveWindow(
            max_batch, self.window_cap_s, self.max_latency_s, clock=clock
        )
        self._occupancy = _Occupancy(max_batch)
        # Reusable per-bucket staging arenas: (size, treedef, leaf sig) ->
        # ring of buffer lists. Ring length inflight+2 guarantees a slot is
        # only rewritten after its batch's device work has been fetched
        # (the collector blocks once `inflight` batches are un-fetched), so
        # a backend that zero-copy-aliases host numpy stays correct.
        self._arenas: dict[tuple, list[list[np.ndarray]]] = {}
        self._arena_seq: dict[tuple, int] = {}
        # Admission queue: tenant-aware weighted-fair by default
        # (LUMEN_QOS, runtime/qos.py) — per-(tenant, lane) sub-queues
        # popped by virtual-time WFQ, with the bulk lane browning out
        # first under pressure. With only default-tenant interactive
        # traffic the schedule IS the old FIFO; LUMEN_QOS=0 restores the
        # plain stdlib queue outright.
        self._queue: Any
        if wfq_enabled():
            self._queue = WFQAdmissionQueue(name=name, max_queue=self.max_queue)
        else:
            self._queue = queue.Queue()
        # Service-rate EWMA feeding the QueueFull drain-time estimate.
        self._drain = _DrainRate()
        self._thread: threading.Thread | None = None
        self._fetch_thread: threading.Thread | None = None
        self._watchdog_thread: threading.Thread | None = None
        self._closed = threading.Event()
        # Guards the closed-check + enqueue pair in submit() against a
        # concurrent close() draining the queue in between.
        self._submit_lock = threading.Lock()
        # Dispatched-but-unfetched batches, FIFO (dispatch order == settle
        # order); the condition variable carries both the bound (collector
        # waits when full) and the fetch hand-off (worker waits when empty).
        self._inflight: deque[_Inflight] = deque()
        self._inflight_cv = threading.Condition()
        self._fetch_stop = False
        # Watchdog state: lane (thread id) -> (start, futures) for every
        # risky device call currently running, and the wedge verdict once
        # the watchdog has fired (submit refuses new work from then on).
        self._watch_lock = threading.Lock()
        self._watching: dict[int, tuple[float, list[Future]]] = {}
        self._wedged: WatchdogTimeout | None = None
        # Telemetry for capability metadata / benchmarks.
        self.stats = {
            "batches": 0,
            "items": 0,
            "padded": 0,
            "shed": 0,
            "expired": 0,
            "bisects": 0,
            "poisoned": 0,
            "quarantine_rejected": 0,
            "watchdog": 0,
        }

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(target=self._run, name=self.name, daemon=True)
        self._fetch_thread = threading.Thread(
            target=self._fetch_loop, name=f"{self.name}-fetch", daemon=True
        )
        # Fetch worker FIRST: the collector's dead-fetch-worker guard reads
        # a not-yet-started thread as dead, and with pre-queued items and a
        # collapsed adaptive window the collector can reach its first
        # dispatch within microseconds of starting.
        self._fetch_thread.start()
        self._thread.start()
        if self.watchdog_s > 0:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog_loop, name=f"{self.name}-watchdog", daemon=True
            )
            self._watchdog_thread.start()
        # Live state on /metrics: queue depth + batch/padding telemetry
        # (latency histograms can't show a backed-up or waste-heavy queue).
        # The provider closes over a weakref so the global registry never
        # pins a dropped batcher (and its captured params) in memory.
        ref = weakref.ref(self)

        def _gauges() -> dict:
            b = ref()
            if b is None:
                return {}
            return {
                **b.stats,
                "queue_depth": b._queue.qsize(),
                "inflight": len(b._inflight),
                "inflight_limit": b.inflight,
            }

        self._gauge_fn = _gauges
        metrics.register_gauges(f"batcher:{self.name}", _gauges)
        # Controller registry (last-writer-wins per name, like the gauge
        # providers): a revive's fresh same-name batcher supersedes the
        # wedge it replaces.
        with _batcher_reg_lock:
            _batcher_registry[self.name] = ref
        # Duty meter for this batcher's device stream: capacity 1 in
        # union mode (dispatch->settle envelopes overlap under
        # pipelining; settle order == dispatch order, so union-clamping
        # yields true busy wall-time and the fraction can never top 1).
        telemetry.set_capacity(f"device:{self.name}", 1.0, union=True)

        def _occupancy_gauges() -> dict:
            b = ref()
            return {} if b is None else b._occupancy.gauges()

        self._occupancy_gauge_fn = _occupancy_gauges
        metrics.register_gauges(f"batch-occupancy:{self.name}", _occupancy_gauges)
        if isinstance(self._queue, WFQAdmissionQueue):
            # Per-tenant admission telemetry (queued/admitted/shed by
            # tenant, lane totals, brownout level) next to the batcher's
            # own gauges. The queue is reached through the batcher weakref
            # like the sibling providers — capturing it directly would let
            # the registry pin a dropped batcher's queue (and its queued
            # entry tuples) forever.

            def _qos_gauges() -> dict:
                b = ref()
                return {} if b is None else b._queue.gauges()

            self._qos_gauge_fn = _qos_gauges
            metrics.register_gauges(f"qos:{self.name}", _qos_gauges)
        return self

    def close(self) -> None:
        with self._submit_lock:
            if self._closed.is_set():
                return
            self._closed.set()
            # The sentinel lands after any already-submitted item, so the
            # collector's drain pass sees them all.
            self._queue.put(None)
        if self._thread:
            # A wedged batcher's collector may be parked inside the stuck
            # device call forever — the watchdog already settled its
            # futures, so close() must not ride out the full join budget.
            self._thread.join(timeout=1.0 if self._wedged is not None else 10)
        # Stop the fetch worker only AFTER the collector exits: every batch
        # it dispatched must still settle (in-flight results drain; the
        # worker's loop runs until the deque is empty AND stop is set).
        with self._inflight_cv:
            self._fetch_stop = True
            self._inflight_cv.notify_all()
        if self._fetch_thread:
            self._fetch_thread.join(timeout=1.0 if self._wedged is not None else 60)
            # A fetch worker killed by an escaping BaseException leaves its
            # in-flight batches unsettled, and after close() nothing else
            # will ever settle them — drain here so close() upholds the
            # "every dispatched batch settles" contract even when the
            # settling lane itself died. Guarded on death: a merely-slow
            # worker (join timed out) keeps ownership of its entries.
            if not self._fetch_thread.is_alive():
                with self._inflight_cv:
                    stranded = list(self._inflight)
                    self._inflight.clear()
                if stranded:
                    err = RuntimeError(
                        f"{self.name}: fetch worker died; batcher closed "
                        "with unsettled in-flight batches"
                    )
                    logger.error("%s", err)
                    for entry in stranded:
                        for f in entry.futures:
                            _settle(f, exception=err)
        # Ownership-guarded: a newer same-name batcher keeps its gauges.
        # A never-started instance has no _gauge_fn — it must not pass
        # None (= unconditional) and evict a live same-name batcher's.
        if fn := getattr(self, "_gauge_fn", None):
            metrics.unregister_gauges(f"batcher:{self.name}", fn)
        if fn := getattr(self, "_occupancy_gauge_fn", None):
            metrics.unregister_gauges(f"batch-occupancy:{self.name}", fn)
        if fn := getattr(self, "_qos_gauge_fn", None):
            metrics.unregister_gauges(f"qos:{self.name}", fn)
        # Same ownership guard for the controller registry: only drop the
        # entry if it still points at THIS instance.
        with _batcher_reg_lock:
            ref = _batcher_registry.get(self.name)
            if ref is not None and ref() is self:
                del _batcher_registry[self.name]

    # -- client side ------------------------------------------------------

    @property
    def quarantine(self) -> QuarantineRegistry:
        """The registry isolated offenders land in (the process-wide one
        unless the constructor pinned an explicit instance)."""
        return self._quarantine if self._quarantine is not None else get_quarantine()

    def submit(
        self, item: Any, deadline: float | None = None, fingerprint: str | None = None
    ) -> Future:
        """Enqueue one item. ``deadline`` is an absolute ``time.monotonic()``
        instant; unset, it is inherited from the ambient request context
        (:func:`lumen_tpu.utils.deadline.get_deadline`, installed by the
        gRPC layer from ``context.time_remaining()``). Expired entries are
        dropped before the device call instead of burning a batch slot.

        ``fingerprint`` is the payload's content address (the result-cache
        key) — it is both the quarantine gate (a known-poison payload is
        rejected HERE, before the admission queue and the device) and the
        identity that gets quarantined if bisection later isolates this
        item as the one that fails its batch.

        Raises :class:`QueueFull` when ``max_queue`` items are already
        waiting (load shed — the caller should surface a retryable
        RESOURCE_EXHAUSTED-style error), :class:`DeadlineExpired` when
        the deadline has already passed at submit time,
        :class:`PoisonInput` when the fingerprint is quarantined, and
        :class:`WatchdogTimeout` when the watchdog has disabled the
        batcher."""
        if deadline is None:
            deadline = get_deadline()
        if deadline is not None and time.monotonic() >= deadline:
            self.stats["expired"] += 1
            metrics.count("deadline_drops")
            metrics.count(f"deadline_drops:{self.name}")
            raise DeadlineExpired(f"{self.name}: request deadline already expired at submit")
        if fingerprint is not None:
            try:
                self.quarantine.check(fingerprint)
            except PoisonInput:
                self.stats["quarantine_rejected"] += 1
                raise
        if self.adaptive:
            self._window.observe()
        fut: Future = Future()
        # Request tracing: the collect span begins HERE (caller thread,
        # where the contextvar is visible) and ends when the collector
        # picks the batch for dispatch — queue wait + collect window, one
        # number. The handle rides the future because contextvars do not
        # cross into the collector/fetch threads.
        tr = current_trace()
        if tr is not None:
            fut._lumen_trace = tr
            fut._lumen_collect = tr.begin("batch.collect", {"batcher": self.name})
            fut.add_done_callback(_end_trace_spans)
        with self._submit_lock:
            # Wedge check INSIDE the lock: _fire_watchdog sets _wedged and
            # drains the queue under the same lock, so an entry can never
            # slip in between the drain and this check and hang unsettled
            # (same race the lock already closes for close()'s drain).
            if self._wedged is not None:
                raise WatchdogTimeout(str(self._wedged))
            if self._closed.is_set():
                raise RuntimeError(f"{self.name} is closed")
            if self.max_queue and self._queue.qsize() >= self.max_queue:
                self.stats["shed"] += 1
                metrics.count("sheds")
                metrics.count(f"sheds:{self.name}")
                # Flight-recorder breadcrumb, rate-limited per batcher: a
                # shed storm is one line a second in the ring, not a
                # flood that churns breaker transitions out of it.
                telemetry.record_event(
                    "shed", self.name,
                    f"admission queue full ({self.max_queue} waiting)",
                    min_interval_s=1.0,
                )
                raise self._queue_full_error(self.max_queue)
            try:
                self._queue.put((item, fut, deadline, fingerprint))
            except QueueFull as e:
                # WFQ brownout: the bulk lane sheds below the full depth
                # so interactive traffic keeps the remaining headroom.
                # Same accounting and drain-context contract as the
                # full-queue shed above.
                self.stats["shed"] += 1
                metrics.count("sheds")
                metrics.count(f"sheds:{self.name}")
                telemetry.record_event(
                    "shed", self.name, str(e), min_interval_s=1.0,
                )
                self._attach_drain_hint(e, self._queue.qsize())
                raise
        return fut

    def _queue_full_error(self, depth: int) -> QueueFull:
        """Build the full-queue shed error WITH backoff context: queue
        depth plus the drain-time estimate from the measured service rate
        (when one exists), so the client — and the serving layer's
        ``lumen-retry-after-ms`` hint — can back off proportionally
        instead of re-knocking on a queue that needs seconds to clear."""
        est = self._drain.estimate_s(depth)
        detail = f"{depth} waiting"
        if est is not None:
            detail += f", est drain {est:.2f}s"
        e = QueueFull(
            f"{self.name}: admission queue full ({detail}); request shed"
        )
        e.queue_depth = depth
        if est is not None:
            e.retry_after_s = est
        return e

    def _attach_drain_hint(self, e: QueueFull, depth: int) -> None:
        e.queue_depth = getattr(e, "queue_depth", depth)
        if getattr(e, "retry_after_s", None) is None:
            est = self._drain.estimate_s(depth)
            if est is not None:
                e.retry_after_s = est

    def __call__(
        self, item: Any, timeout: float | None = None, fingerprint: str | None = None
    ) -> Any:
        """Submit and wait (see :func:`wait_for_batch` for the wait
        semantics — shared with the replica fleet's routed dispatch)."""
        fut = self.submit(item, fingerprint=fingerprint)
        return wait_for_batch(fut, self.name, self.stats, timeout)

    def load(self) -> int:
        """Queued + dispatched-but-unsettled items — the signal the
        fleet's least-loaded dispatch policy ranks replicas by."""
        with self._inflight_cv:
            inflight = sum(e.n for e in self._inflight)
        return self._queue.qsize() + inflight

    def drain_estimate_s(self) -> float | None:
        """Seconds the CURRENT backlog needs to clear at the measured
        service rate (None before any batch settled) — the queue-drain
        sensor the autopilot's scale loop reads, the same estimate the
        ``QueueFull`` retry hint is built from."""
        return self._drain.estimate_s(self.load())

    def set_window_cap_s(self, cap_s: float) -> float:
        """Retarget the adaptive window's cap (the autopilot's batch-window
        actuator). Floored at 0; returns the applied value. Takes effect on
        the collector's next ``window_s`` read — no lock needed, a float
        store is atomic and the controller tick is the only writer."""
        cap = max(0.0, float(cap_s))
        self.window_cap_s = cap
        self._window.cap_s = cap
        return cap

    # -- collector thread -------------------------------------------------

    def _run(self) -> None:
        while not self._closed.is_set() and self._wedged is None:
            first = self._queue.get()
            if first is None:
                break
            batch = [first]
            # Window from the FIRST item's pickup. Fixed mode keeps the
            # historical ``max_latency_ms`` wait; adaptive mode asks the
            # EWMA controller and re-asks after each arrival (more items in
            # hand = less of the batch left to wait for), always bounded by
            # ``window_cap_s`` from the first item.
            t_first = time.monotonic()
            if self.adaptive:
                deadline = t_first + min(self._window.window_s(1), self.window_cap_s)
            else:
                deadline = t_first + self.max_latency_s
            while len(batch) < self.max_batch:
                # Drain-first: items ALREADY queued join the batch
                # regardless of the window — a collapsed (~0) adaptive
                # window must mean "don't wait for traffic that isn't
                # coming", never "strand waiting items for a later batch".
                try:
                    nxt = self._queue.get_nowait()
                except queue.Empty:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        nxt = self._queue.get(timeout=remaining)
                    except queue.Empty:
                        break
                if nxt is None:
                    self._closed.set()
                    break
                batch.append(nxt)
                if self.adaptive:
                    deadline = min(
                        t_first + self.window_cap_s,
                        time.monotonic() + self._window.window_s(len(batch)),
                    )
            self._dispatch(batch)
        # Drain anything left after close.
        while True:
            try:
                entry = self._queue.get_nowait()
            except queue.Empty:
                break
            if entry is not None:
                _settle(entry[1], exception=RuntimeError(f"{self.name} closed"))

    def _dispatch(self, batch: list[tuple[Any, Future, float | None, str | None]]) -> None:
        # Reserve an in-flight slot FIRST: this wait is where the collector
        # blocks under backpressure (possibly for a full device-batch
        # latency), so it must come before the deadline gate — an entry
        # whose deadline expires while we wait here still gets dropped
        # below instead of burning the batch it no longer wants. Exactness:
        # at most `inflight` un-fetched device results exist at any instant
        # (the HBM bound an operator sizes against), and inflight=1 really
        # does serialize dispatch. Only this thread appends, so reserving
        # by waiting for space cannot race another producer.
        dead = False
        with self._inflight_cv:
            while len(self._inflight) >= self.inflight:
                # A dead fetch worker can never drain the deque: fail
                # loudly instead of wedging the collector (and every
                # caller) in a silent 300s-timeout limbo.
                if self._fetch_thread is not None and not self._fetch_thread.is_alive():
                    dead = True
                    break
                if self._wedged is not None:
                    break  # the watchdog drained the deque; abort below
                self._inflight_cv.wait(timeout=1.0)
        if self._wedged is not None:
            for _, fut, _, _ in batch:
                _settle(fut, exception=WatchdogTimeout(str(self._wedged)))
            return
        if dead:
            self._abort_dead_fetch([fut for _, fut, _, _ in batch])
            return
        # Deadline gate: entries whose caller deadline passed while they
        # queued are failed here — BEFORE stacking and the device call — so
        # an overloaded server does not spend TPU time computing answers
        # nobody is waiting for (their gRPC stream is already torn down).
        # The gate runs per dispatch even with earlier batches still in
        # flight: a deadline that expires while batch k computes still
        # drops the k+1 entry it covers.
        live: list[tuple[Any, Future, str | None]] = []
        now = time.monotonic()
        for item, fut, deadline, fingerprint in batch:
            if fut.cancelled():
                # The waiting caller already gave up (and accounted the
                # drop); counting here too would double-book the event.
                continue
            if deadline is not None and now >= deadline:
                if _settle(
                    fut,
                    exception=DeadlineExpired(
                        f"{self.name}: deadline expired while queued"
                    ),
                ):
                    self.stats["expired"] += 1
                    metrics.count("deadline_drops")
                    metrics.count(f"deadline_drops:{self.name}")
            else:
                live.append((item, fut, fingerprint))
        if not live:
            return
        items = [b[0] for b in live]
        futures = [b[1] for b in live]
        n = len(items)
        size = bucket_for(n, self.buckets)
        self._occupancy.record(n, size)
        # Trace hand-off at the thread hop: collect ends on THIS (collector)
        # thread; the device span opens here and is closed by whatever
        # settles the future (fetch worker on the happy path — see
        # ``_end_trace_spans``), so it covers dispatch + device compute +
        # the one device->host transfer, bisection passes included.
        for _, fut, _ in live:
            h = getattr(fut, "_lumen_collect", None)
            if h is not None:
                h.end()
                attrs = {"batcher": self.name, "n": n, "size": size}
                if self.replica is not None:
                    attrs["replica"] = self.replica
                fut._lumen_device = fut._lumen_trace.begin("batch.device", attrs)
        arena = None
        t_dispatch = time.monotonic()
        try:
            stacked, arena = self._stack(items, size)
            if telemetry.enabled():
                # Host->device payload for this batch (the staged numpy
                # tree the backend will transfer). Per-batch, not
                # per-request; a windowed byte rate on /stats.
                telemetry.count(
                    f"transfer_h2d:{self.name}", _tree_nbytes(stacked)
                )
            result = self._execute(live, n, size, stacked=stacked)
        except Exception as e:  # noqa: BLE001 - contain, or fan out to callers
            self._contain_failure(live, e)
            return
        with self._inflight_cv:
            if self._fetch_thread is not None and not self._fetch_thread.is_alive():
                dead = True  # nobody left to settle this result
            else:
                self._inflight.append(
                    _Inflight(
                        futures, result, n, size, entries=live, arena=arena,
                        t_dispatch=t_dispatch,
                    )
                )
                self._inflight_cv.notify_all()
        if dead:
            self._abort_dead_fetch(futures)

    def _execute(
        self,
        entries: list[tuple[Any, Future, str | None]],
        n: int,
        size: int,
        stacked: Any | None = None,
    ):
        """Fault checks + stack + dispatch for one (sub-)batch, watched by
        the watchdog. Shared by the normal dispatch path (which pre-stacks
        into a reusable arena and passes ``stacked``) and bisection probes
        (which re-stack their sub-batch here), so an armed fault point (or
        a real per-item failure, e.g. a shape mismatch surfacing in
        ``stack_and_pad``) fires identically for every sub-batch that
        still contains the offending item."""
        from ..testing.faults import faults

        with self._watched([e[1] for e in entries]):
            # No-op unless a test/harness armed the point; lets the suite
            # exercise the containment paths below deterministically.
            # With inflight > 1 an injected failure lands on exactly this
            # batch's callers — earlier in-flight batches settle normally.
            faults.check("batch_execute", self.name)
            for _, _, fingerprint in entries:
                if fingerprint:
                    faults.check("batch_poison", f"{self.name}:{fingerprint}")
            if faults.fires("batch_hang", self.name):
                self._hang()
            if stacked is None:
                stacked = stack_and_pad([e[0] for e in entries], size)
            return self.fn(stacked, n)  # async dispatch; fetch worker settles

    #: bound on distinct (bucket, leaf-signature) arena keys; past it new
    #: shapes fall back to allocating stacks (a shape-churning caller must
    #: not grow pinned staging memory without limit).
    _MAX_ARENA_KEYS = 8

    def _stack(self, items: list[Any], size: int):
        """Stack ``items`` into a reusable per-bucket staging arena
        (collector thread only — bisection probes and salvage paths use the
        allocating :func:`stack_and_pad`). Returns ``(stacked_tree,
        arena_buffers | None)``; the buffers ride the in-flight entry so
        the fetch path can copy out of a result that aliases them.

        A ring of ``inflight + 2`` buffer sets per signature makes reuse
        safe even when the backend zero-copy-aliases host numpy: a slot is
        rewritten only after its batch left the in-flight deque (the
        collector blocks at ``inflight`` un-fetched batches), i.e. after
        its device work was fetched. Any shape/structure surprise falls
        back to ``stack_and_pad`` so error semantics (and bisection) are
        exactly the pre-arena ones."""
        try:
            flat = [jax.tree_util.tree_flatten(it) for it in items]
            leaves0 = [np.asarray(l) for l in flat[0][0]]
            treedef0 = flat[0][1]
            key = (size, treedef0, tuple((a.shape, a.dtype.str) for a in leaves0))
            ring = self._arenas.get(key)
            if ring is None:
                if len(self._arenas) >= self._MAX_ARENA_KEYS:
                    return stack_and_pad(items, size), None
                ring = [
                    [np.empty((size, *a.shape), a.dtype) for a in leaves0]
                    for _ in range(self.inflight + 2)
                ]
                self._arenas[key] = ring
                self._arena_seq[key] = 0
            seq = self._arena_seq[key]
            self._arena_seq[key] = seq + 1
            bufs = ring[seq % len(ring)]
            n = len(items)
            for i, (leaves, treedef) in enumerate(flat):
                if treedef != treedef0:
                    raise ValueError("mixed pytree structures in batch")
                for j, leaf in enumerate(leaves):
                    arr = np.asarray(leaf)
                    # Exact-match gate, like np.stack's: a broadcastable
                    # (or castable) mismatch must fall through to the
                    # allocating path and RAISE there — never silently
                    # broadcast/truncate into a wrong device result.
                    if arr.shape != leaves0[j].shape or arr.dtype != leaves0[j].dtype:
                        raise ValueError(
                            f"item {i} leaf {j} shape/dtype "
                            f"{arr.shape}/{arr.dtype} != arena "
                            f"{leaves0[j].shape}/{leaves0[j].dtype}"
                        )
                    bufs[j][i] = arr
            if n < size:
                for buf in bufs:
                    buf[n:size] = buf[n - 1]  # repeat-last padding
            return jax.tree_util.tree_unflatten(treedef0, bufs), bufs
        except Exception:  # noqa: BLE001 - degrade to the allocating path
            return stack_and_pad(items, size), None

    def _hang(self) -> None:
        """Simulate a wedged device call (``batch_hang`` fault point):
        park where the real stall would sit until the watchdog fires or
        the batcher closes, then surface the corresponding error."""
        logger.warning("%s: batch_hang fault armed; parking dispatch", self.name)
        while not self._closed.is_set() and self._wedged is None:
            time.sleep(0.005)
        raise self._wedged or RuntimeError(f"{self.name}: closed while hung")

    def _contain_failure(
        self, entries: list[tuple[Any, Future, str | None]], error: Exception
    ) -> None:
        """A dispatched (sub-)batch raised: bisect when possible, otherwise
        fan the failure out to every caller (single item, or bisection
        disabled)."""
        n = len(entries)
        if n > 1 and self.bisect_depth > 0 and not isinstance(error, WatchdogTimeout):
            logger.warning(
                "%s: batch of %d failed (%s: %s); bisecting to isolate",
                self.name, n, type(error).__name__, error,
            )
            self._bisect(entries, error)
            return
        logger.exception("%s: batched dispatch failed (n=%d)", self.name, n)
        for _, fut, _ in entries:
            _settle(fut, exception=error)

    def _bisect(self, entries: list[tuple[Any, Future, str | None]], error: Exception) -> None:
        """Isolate the item(s) that make a batch fail.

        Runs SYNCHRONOUSLY on the calling thread (collector or fetch
        worker — whichever observed the failure): each probe dispatches a
        half and blocks on its fetch, so the pass costs at most
        ``2 * bisect_depth`` sub-batch device calls. Sub-batch sizes round
        up to existing buckets, so no new XLA compiles are triggered on a
        warmed batcher. Containment verdicts:

        - a group that succeeds settles its futures with real rows
          (innocent co-batched callers lose latency, not their answers);
        - a single item that fails while ANY sibling succeeded is poison:
          :class:`PoisonInput` + quarantine registration;
        - a failing group at the depth bound fails together with its
          probe's error (isolation gave up — no quarantine on guesses);
        - if NOTHING succeeded, the device (not an input) is broken: every
          caller gets the original error and nothing is quarantined.
        """
        self.stats["bisects"] += 1
        metrics.count("batch_bisects")
        metrics.count(f"batch_bisects:{self.name}")
        isolated: list[tuple[tuple[Any, Future, str | None], Exception]] = []
        exhausted: list[tuple[list[tuple[Any, Future, str | None]], Exception]] = []
        succeeded = 0
        work: deque[tuple[list[tuple[Any, Future, str | None]], Exception, int]] = deque(
            [(entries, error, self.bisect_depth)]
        )
        while work:
            if self._wedged is not None:
                # A probe tripped the watchdog mid-pass: EVERYTHING still
                # unresolved — queued work, isolated candidates awaiting
                # their verdict, and depth-exhausted groups awaiting their
                # group error — fails with the wedge verdict, loudly.
                # Nothing else will ever settle these futures (they are in
                # neither the queue nor the in-flight deque).
                for group, _, _ in work:
                    for _, fut, _ in group:
                        _settle(fut, exception=WatchdogTimeout(str(self._wedged)))
                for entry, _ in isolated:
                    _settle(entry[1], exception=WatchdogTimeout(str(self._wedged)))
                for group, _ in exhausted:
                    for _, fut, _ in group:
                        _settle(fut, exception=WatchdogTimeout(str(self._wedged)))
                return
            group, err, depth = work.popleft()
            group = [e for e in group if not e[1].cancelled()]
            if not group:
                continue
            if len(group) == 1:
                isolated.append((group[0], err))
                continue
            if depth <= 0:
                exhausted.append((group, err))
                continue
            mid = (len(group) + 1) // 2
            for half in (group[:mid], group[mid:]):
                try:
                    rows = self._probe(half)
                except Exception as e:  # noqa: BLE001 - recurse into the half
                    work.append((half, e, depth - 1))
                else:
                    # Sibling evidence = the probe ran CLEAN on device,
                    # independent of whether its callers still wanted the
                    # rows (_settle on a cancelled/expired future returns
                    # False, but the device just proved these items
                    # healthy — the poison verdict below relies on it).
                    succeeded += len(half)
                    for (item, fut, _), row in zip(half, rows):
                        _settle(fut, result=row)
                    self.stats["batches"] += 1
                    self.stats["items"] += len(half)
        for group, err in exhausted:
            logger.error(
                "%s: bisection depth exhausted with %d items still "
                "co-failing; failing the group",
                self.name, len(group),
            )
            for _, fut, _ in group:
                _settle(fut, exception=err)
        if not succeeded:
            # NOTHING in the batch ran clean — that is a broken device
            # call, not poison inputs. A poison verdict requires sibling
            # evidence ("fails while others succeed"); without it, every
            # isolated item gets the original batch error and nothing is
            # quarantined. This holds at ANY depth: a depth-bounded pass
            # whose groups all co-failed proves just as little about the
            # one item it happened to isolate.
            if isolated:
                logger.error(
                    "%s: bisection found no healthy item in a batch of %d; "
                    "treating as a batch-level failure (%s)",
                    self.name, len(entries), error,
                )
                for entry, _ in isolated:
                    _settle(entry[1], exception=error)
            return
        for (item, fut, fingerprint), err in isolated:
            poison = PoisonInput(
                f"{self.name}: input isolated by batch bisection as the "
                f"item that fails its batch ({type(err).__name__}: {err})"
            )
            self.stats["poisoned"] += 1
            metrics.count("poison_isolated")
            metrics.count(f"poison_isolated:{self.name}")
            if fingerprint:
                self.quarantine.add(
                    fingerprint, f"{self.name}: {type(err).__name__}: {err}"
                )
            _settle(fut, exception=poison)

    def _probe(self, entries: list[tuple[Any, Future, str | None]]) -> list[Any]:
        """One synchronous bisection probe: dispatch the group and block on
        its fetch. Returns per-item rows; raises what the group raises.
        Probe device time feeds the same duty meter as normal batches —
        a bisection storm IS device load an operator should see."""
        n = len(entries)
        t0 = time.monotonic()
        try:
            result = self._execute(entries, n, bucket_for(n, self.buckets))
            with self._watched([e[1] for e in entries]):
                return unstack(result, n)
        finally:
            telemetry.busy(f"device:{self.name}", t0, time.monotonic())

    # -- watchdog ----------------------------------------------------------

    @contextmanager
    def _watched(self, futures: list[Future]):
        """Register the enclosed device call with the watchdog: if it runs
        past ``watchdog_s``, the monitor thread fails ``futures`` and
        disables the batcher. Free when the watchdog is off."""
        if self.watchdog_s <= 0:
            yield
            return
        lane = threading.get_ident()
        with self._watch_lock:
            self._watching[lane] = (time.monotonic(), futures)
        try:
            yield
        finally:
            with self._watch_lock:
                self._watching.pop(lane, None)

    def _watchdog_loop(self) -> None:
        interval = min(1.0, max(0.01, self.watchdog_s / 8))
        while not self._closed.is_set() and self._wedged is None:
            time.sleep(interval)
            now = time.monotonic()
            with self._watch_lock:
                overdue = [
                    futs
                    for _, (t0, futs) in self._watching.items()
                    if now - t0 > self.watchdog_s
                ]
            if overdue:
                self._fire_watchdog([f for futs in overdue for f in futs])
                return

    def _fire_watchdog(self, futures: list[Future]) -> None:
        """A device call blew its budget: presume the device stream is
        wedged. Fail the stuck batch's callers, drain everything queued or
        in flight (nothing downstream of a wedged lane will ever settle),
        and refuse new work — an operator (or the circuit breaker's
        recovery handoff) must reload the service."""
        err = WatchdogTimeout(
            f"{self.name}: batch execution exceeded the watchdog budget "
            f"({self.watchdog_s:.1f}s); batcher disabled pending reload"
        )
        queued_entries = []
        with self._submit_lock:
            # Set the wedge flag and drain the queue under the submit lock
            # (the same pairing close() uses): submit() re-checks _wedged
            # inside the lock, so no entry can land after this drain and
            # hang with nobody left to settle it.
            self._wedged = err
            while True:
                try:
                    queued = self._queue.get_nowait()
                except queue.Empty:
                    break
                if queued is not None:
                    queued_entries.append(queued)
        self.stats["watchdog"] += 1
        metrics.count("watchdog_timeouts")
        metrics.count(f"watchdog_timeouts:{self.name}")
        telemetry.record_event(
            "watchdog", self.name,
            f"batch exceeded the {self.watchdog_s:.1f}s watchdog budget; "
            "batcher disabled pending reload",
        )
        logger.error("%s", err)
        for f in futures:
            _settle(f, exception=err)
        with self._inflight_cv:
            stranded = list(self._inflight)
            self._inflight.clear()
            self._inflight_cv.notify_all()
        for entry in stranded:
            for f in entry.futures:
                _settle(f, exception=err)
        # The collector is either the stuck thread or about to observe
        # _wedged: queued entries would sit forever — fail them now.
        for queued in queued_entries:
            _settle(queued[1], exception=err)

    def _abort_dead_fetch(self, futures: list[Future]) -> None:
        """The fetch worker died (a BaseException escaped its loop):
        settle its stranded in-flight batches AND the current batch with a
        loud error — callers must not ride out the full batch-wait timeout
        for results that can never arrive."""
        err = RuntimeError(
            f"{self.name}: fetch worker died; batcher cannot settle results"
        )
        logger.error("%s", err)
        with self._inflight_cv:
            stranded = list(self._inflight)
            self._inflight.clear()
            self._inflight_cv.notify_all()
        for entry in stranded:
            for f in entry.futures:
                _settle(f, exception=err)
        for f in futures:
            _settle(f, exception=err)

    # -- fetch/settle worker ----------------------------------------------

    def _fetch_loop(self) -> None:
        """Drain the in-flight deque in dispatch order: one blocking
        device->host transfer per batch, then settle that batch's futures
        (submission order within the batch). Runs until close() has both
        stopped the collector and set the stop flag — every dispatched
        batch settles before close() returns."""
        while True:
            with self._inflight_cv:
                while not self._inflight:
                    # Exit only once close() asked AND the collector can no
                    # longer dispatch (its thread is dead) — a collector
                    # stuck past close()'s join timeout in a long compile
                    # must still get its final batch settled, not orphaned.
                    if self._fetch_stop:
                        # A wedged collector may be parked in a stuck
                        # device call forever; its futures are settled, so
                        # there is nothing left to wait for.
                        if self._wedged is not None or not (
                            self._thread and self._thread.is_alive()
                        ):
                            return
                        self._inflight_cv.wait(timeout=0.05)
                    else:
                        self._inflight_cv.wait()
                # Peek — the entry leaves the deque only after its fetch
                # completes, so the in-flight bound counts batches whose
                # device work (or transfer) is genuinely outstanding.
                entry = self._inflight[0]
            try:
                with self._watched(entry.futures):
                    rows = _unstack_guarded(entry.result, entry.n, entry.arena)
            except Exception as e:  # noqa: BLE001 - contain, or fan out to THIS batch only
                # A device error often surfaces at the FETCH, not the
                # dispatch (XLA dispatch is async): bisection runs here
                # too, re-dispatching halves of the original items.
                if entry.entries:
                    self._contain_failure(entry.entries, e)
                else:
                    logger.exception(
                        "%s: batched fetch failed (n=%d)", self.name, entry.n
                    )
                    for f in entry.futures:
                        _settle(f, exception=e)
            else:
                self.stats["batches"] += 1
                self.stats["items"] += entry.n
                self.stats["padded"] += entry.size - entry.n
                self._drain.record(entry.n)
                if telemetry.enabled():
                    # Capacity telemetry, all per-batch: the device duty
                    # envelope (dispatch->settle, union-merged so the
                    # pipelined overlap isn't double-counted), windowed
                    # batch fill vs padding, the bucket the batch
                    # compiled into, and the device->host result bytes.
                    now = time.monotonic()
                    if entry.t_dispatch:
                        telemetry.busy(
                            f"device:{self.name}", entry.t_dispatch, now
                        )
                    telemetry.count(f"batch_items:{self.name}", entry.n)
                    telemetry.count(
                        f"batch_padded:{self.name}", entry.size - entry.n
                    )
                    telemetry.count(
                        f"batch_bucket:{self.name}:{entry.size}"
                    )
                    if rows:
                        telemetry.count(
                            f"transfer_d2h:{self.name}",
                            _tree_nbytes(rows[0]) * entry.n,
                        )
                for f, row in zip(entry.futures, rows):
                    _settle(f, result=row)
            with self._inflight_cv:
                # Identity-guarded: _fire_watchdog may have cleared the
                # deque while this entry was being unstacked (it was only
                # PEEKED, not popped) — a blind popleft would then raise
                # on the empty deque, or eat a successor batch's entry.
                if self._inflight and self._inflight[0] is entry:
                    self._inflight.popleft()
                self._inflight_cv.notify_all()


# -- pytree stacking helpers ------------------------------------------------


def _tree_nbytes(tree: Any) -> int:
    """Total bytes across a pytree's array leaves (host-side accounting
    for the transfer-byte telemetry; leaves without ``nbytes`` count 0).
    One flatten per BATCH — never on the per-request path."""
    leaves, _ = jax.tree_util.tree_flatten(tree)
    return sum(int(getattr(leaf, "nbytes", 0) or 0) for leaf in leaves)


def stack_and_pad(items: list[Any], size: int) -> Any:
    """Stack a list of same-structure pytrees into one tree with leading dim
    ``size``; rows past ``len(items)`` repeat the last item (repeating keeps
    padding numerically harmless for ops like softmax over the batch).

    Items may be views over recycled buffers — notably the process decode
    pool's shared-memory arena slots (``DecodePool.run_decode``): stacking
    copies each row out, so the view is not needed AFTER the stack. But a
    submitter must still hold its lease until ``submit()``'s future
    settles, not just until dispatch: batch **bisection** re-stacks halves
    from the ORIGINAL item references at dispatch or fetch time, and a
    slot recycled early would feed the re-run garbage. The managers'
    ``try: batcher(view) finally: release()`` shape satisfies this by
    construction."""
    n = len(items)
    pad = size - n

    def stack(*leaves):
        arrs = [np.asarray(x) for x in leaves]
        if pad:
            arrs = arrs + [arrs[-1]] * pad
        return np.stack(arrs)

    return jax.tree_util.tree_map(stack, *items)


def _unstack_guarded(tree: Any, n: int, arena: list | None) -> list[Any]:
    """``unstack`` with an arena-alias guard: a passthrough/zero-copy
    backend can hand back host arrays that ALIAS the reusable staging
    buffers the batch was stacked into — rows sliced from those would be
    silently rewritten when the arena slot cycles. Any fetched leaf that
    may share memory with an arena buffer is copied out first (real device
    results are fresh host arrays, so the check is a no-op bounds test on
    the hot path)."""
    tree = jax.device_get(tree)
    if arena:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        leaves = [
            np.array(leaf, copy=True)
            if isinstance(leaf, np.ndarray)
            and any(np.may_share_memory(leaf, buf) for buf in arena)
            else leaf
            for leaf in leaves
        ]
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [
        jax.tree_util.tree_unflatten(treedef, [leaf[i] for leaf in leaves])
        for i in range(n)
    ]


def unstack(tree: Any, n: int) -> list[Any]:
    """Split a batched result tree back into ``n`` single-item trees (host
    numpy). ``jax.device_get`` on the WHOLE tree makes one blocking
    transfer per batch (a per-leaf ``np.asarray`` loop would round-trip
    the device once per leaf — the fetch worker calls this on every
    settled batch, so the difference is on the serving hot path); numpy
    and array-like leaves pass through as plain arrays."""
    tree = jax.device_get(tree)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return [
        jax.tree_util.tree_unflatten(treedef, [leaf[i] for leaf in leaves])
        for i in range(n)
    ]
