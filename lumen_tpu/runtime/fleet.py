"""Replica fleet: device-aware multi-replica serving.

The parallel layer (``lumen_tpu/parallel/``, :mod:`~lumen_tpu.runtime.mesh`)
proved an 8-device mesh with working dp/tp, but until this module the
serving stack fed exactly one batcher per model tower — one chip did all
the work while its siblings idled (ROADMAP item 1). The fleet turns each
model family into **N data-parallel replicas**, one per chip or per mesh
slice:

- :func:`plan_replicas` partitions the host's local devices into N slices
  (``LUMEN_REPLICAS`` / per-family ``LUMEN_REPLICAS_<FAMILY>`` override,
  ``max`` = one replica per slice) and builds one
  :class:`~jax.sharding.Mesh` per slice. Non-``data`` axes in the service's
  mesh config (tensor parallelism for models that need it) are kept
  *inside* every replica: ``LUMEN_REPLICAS=max`` with ``model=2`` on 8
  chips yields 4 replicas of 2-chip TP slices. A replica count that does
  not divide the device count degrades to the largest one that does, with
  a one-shot warning — ``LUMEN_REPLICAS=8`` on a 4-chip host serves 4
  replicas instead of failing boot.
- :class:`ReplicaSet` is a drop-in for the single
  :class:`~lumen_tpu.runtime.batcher.MicroBatcher` a manager used to own:
  ``submit``/``__call__`` route each request to one replica through a
  pluggable dispatch policy (``round_robin`` | ``least_loaded``,
  ``LUMEN_REPLICA_POLICY``; :func:`register_policy` for custom ones).
  Every replica keeps its own MicroBatcher — own admission queue, own
  collector/fetch threads, own staging arenas, own
  ``batcher:{name}-r{i}`` / ``batch-occupancy:{name}-r{i}`` gauges — so a
  poisoned or wedged replica is contained while siblings keep serving.
- **Per-replica health**: backend failures (watchdog timeouts, device
  errors) count against the replica that served them; after
  ``LUMEN_REPLICA_FAILURES`` consecutive failures (or immediately on a
  wedged batcher) the replica is marked *down*, the dispatcher skips it,
  and a queue-full or wedge at submit time fails over to a sibling.
  A background revive swaps ONLY the dead replica's batcher for a fresh
  one after ``LUMEN_REPLICA_REVIVE_S`` (the replica-granular analog of
  the RecoveryManager's whole-service hot-swap) — siblings never notice.

The fleet is CPU-testable: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
gives the suite 8 host "chips" (the tier-1 conftest already does), and the
``replica_scaling`` bench phase drives gRPC c10 against 1/2/4 forced-host
replicas per policy.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

from ..utils.deadline import DeadlineExpired, PoisonInput, QueueFull, WatchdogTimeout
from ..utils.env import env_float, env_int
from ..utils.metrics import metrics
from . import telemetry
from .batcher import MicroBatcher, wait_for_batch

logger = logging.getLogger(__name__)

REPLICAS_ENV = "LUMEN_REPLICAS"
POLICY_ENV = "LUMEN_REPLICA_POLICY"
FAILURES_ENV = "LUMEN_REPLICA_FAILURES"
REVIVE_ENV = "LUMEN_REPLICA_REVIVE_S"

#: replica health states (surface in ``Health`` trailing metadata and the
#: ``replica:{name}`` gauge set as the numeric codes below). PARKED is
#: voluntary idleness — the autopilot's scale-down released the replica's
#: mesh slice (batcher closed, chips free for a hot sibling family);
#: unlike DOWN it is healthy, never auto-revived, and only a scale-up
#: (or an operator's :meth:`ReplicaSet.unpark`) brings it back.
SERVING = "serving"
REVIVING = "reviving"
DOWN = "down"
PARKED = "parked"
_STATE_CODES = {SERVING: 0, REVIVING: 1, DOWN: 2, PARKED: 3}


# -- knobs -------------------------------------------------------------------


def replicas_for(family: str) -> int:
    """Requested replica count for one model family:
    ``LUMEN_REPLICAS_<FAMILY>`` (e.g. ``LUMEN_REPLICAS_CLIP``) wins over
    the global ``LUMEN_REPLICAS``; unset/malformed = 1 (the single-batcher
    behavior every PR before the fleet shipped). ``max`` = -1, meaning one
    replica per available mesh slice (resolved by :func:`plan_replicas`
    against the device count and any TP axes)."""
    for key in (f"{REPLICAS_ENV}_{family.upper()}", REPLICAS_ENV):
        raw = os.environ.get(key)
        if raw is None or not raw.strip():
            continue
        if raw.strip().lower() == "max":
            return -1
        try:
            return max(1, int(raw))
        except ValueError:
            logger.warning("ignoring malformed %s=%r", key, raw)
    return 1


def replica_failures() -> int:
    """``LUMEN_REPLICA_FAILURES``: consecutive backend failures that mark
    one replica down (default 3; 0 = replicas are never marked down by
    outcome — a wedged batcher still fails over at submit time)."""
    return env_int(FAILURES_ENV, 3, minimum=0)


def replica_revive_s() -> float:
    """``LUMEN_REPLICA_REVIVE_S``: cooldown before a downed replica's
    batcher is rebuilt in the background (default 5s; 0 disables automatic
    revival — :meth:`ReplicaSet.revive` stays available to operators)."""
    return env_float(REVIVE_ENV, 5.0, minimum=0.0)


def largest_dividing(requested: int, n: int) -> int:
    """Largest replica count <= ``requested`` that divides ``n`` evenly
    (>= 1). The graceful-degrade rule for replica counts that do not fit
    the device count."""
    r = max(1, min(requested, n))
    while n % r:
        r -= 1
    return r


# -- dispatch policies -------------------------------------------------------


class RoundRobinPolicy:
    """Cycle through live replicas — fair and cache-friendly when request
    costs are uniform (the CLIP/face embed workloads)."""

    name = "round_robin"

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._i = 0

    def pick(self, live: list["Replica"]) -> "Replica":
        with self._lock:
            self._i += 1
            return live[self._i % len(live)]


class LeastLoadedPolicy:
    """Pick the replica with the fewest queued + in-flight items — rides
    over stragglers (one replica stuck in a cold compile, a skewed batch)
    at the cost of one load probe per pick."""

    name = "least_loaded"

    def pick(self, live: list["Replica"]) -> "Replica":
        return min(live, key=lambda r: r.load())


#: pluggable policy registry: name -> zero-arg factory.
POLICIES: dict[str, Callable[[], Any]] = {
    "round_robin": RoundRobinPolicy,
    "least_loaded": LeastLoadedPolicy,
}


def register_policy(name: str, factory: Callable[[], Any]) -> None:
    """Register a custom dispatch policy (a zero-arg factory returning an
    object with ``name`` and ``pick(live_replicas)``)."""
    POLICIES[name] = factory


def dispatch_policy_name() -> str:
    """``LUMEN_REPLICA_POLICY`` resolved against the registry; unknown
    names degrade to ``round_robin`` with a warning, not a crash."""
    raw = (os.environ.get(POLICY_ENV) or "round_robin").strip().lower()
    if raw not in POLICIES:
        logger.warning(
            "unknown %s=%r (known: %s); using round_robin",
            POLICY_ENV, raw, sorted(POLICIES),
        )
        return "round_robin"
    return raw


def make_policy(name: str | None = None):
    return POLICIES[name or dispatch_policy_name()]()


# -- device planning ---------------------------------------------------------


@dataclass
class FleetPlan:
    """Resolved replica layout for one model family."""

    family: str
    replicas: int
    meshes: list  # one jax.sharding.Mesh per replica
    policy: str
    device_count: int
    devices_per_replica: int
    requested: int = 0


_clamp_warned: set[str] = set()


def plan_replicas(
    family: str,
    mesh_axes: dict[str, int] | None = None,
    devices: list | None = None,
) -> FleetPlan:
    """Partition the host's devices into the family's replica slices.

    With 1 replica (the default) this is byte-for-byte the pre-fleet
    behavior: one mesh over every local device, built from the service's
    configured axes. With N > 1, devices split into N contiguous slices;
    each replica's mesh keeps the configured non-``data`` axes (TP slices
    stay intact inside a replica) and absorbs the rest of its slice on
    ``data``. Counts that don't fit degrade to the largest that does
    (one-shot warning per family)."""
    import jax

    from .mesh import DATA_AXIS, build_mesh

    devices = list(devices if devices is not None else jax.local_devices())
    n = len(devices)
    axes = dict(mesh_axes or {})
    requested = replicas_for(family)
    policy = dispatch_policy_name()
    # Non-data axes (TP/SP/...) live INSIDE each replica: a slice must hold
    # at least one full copy of them.
    fixed = math.prod(s for a, s in axes.items() if a != DATA_AXIS and s != -1)
    slots = max(1, n // max(1, fixed))
    want = slots if requested == -1 else requested
    replicas = largest_dividing(want, slots)
    if replicas != want and family not in _clamp_warned:
        _clamp_warned.add(family)
        logger.warning(
            "%s: %d replica(s) requested but %d device(s) hold %d slice(s) "
            "of %d device(s) each; degrading to %d replica(s)",
            family, want, n, slots, max(1, fixed), replicas,
        )
    if replicas <= 1:
        mesh = build_mesh(mesh_axes, devices=devices) if mesh_axes else build_mesh(devices=devices)
        return FleetPlan(family, 1, [mesh], policy, n, n, requested=want)
    per = n // replicas
    rep_axes = {a: s for a, s in axes.items() if a != DATA_AXIS}
    if not any(s == -1 for s in rep_axes.values()):
        # A wildcard non-data axis (e.g. {"model": -1}, TP over whatever
        # is available) already absorbs the whole slice — adding a second
        # -1 axis would make the mesh unresolvable.
        rep_axes[DATA_AXIS] = -1
    meshes = [
        build_mesh(rep_axes, devices=devices[i * per : (i + 1) * per])
        for i in range(replicas)
    ]
    logger.info(
        "%s: replica fleet of %d x %d-device slice(s) (policy=%s)",
        family, replicas, per, policy,
    )
    return FleetPlan(family, replicas, meshes, policy, n, per, requested=want)


def replicate_all(host_tree: Any, plan: FleetPlan, primary: Any | None = None) -> list[Any]:
    """Place one host param tree on EVERY replica mesh (replicated within
    each slice). ``primary`` reuses an already-placed tree for replica 0 so
    the common path never double-places."""
    from ..parallel.sharding import replicate

    out = [primary if primary is not None else replicate(host_tree, plan.meshes[0])]
    out.extend(replicate(host_tree, m) for m in plan.meshes[1:])
    return out


def batcher_name(base: str, rid: int | None) -> str:
    """Per-replica batcher/gauge name; a singleton (rid None) keeps the
    plain pre-fleet name so existing dashboards don't move."""
    return base if rid is None else f"{base}-r{rid}"


def each_batcher(dispatcher) -> Iterator[MicroBatcher]:
    """Iterate the underlying MicroBatcher(s) of a dispatcher that is
    either a plain batcher or a :class:`ReplicaSet` (warmup and telemetry
    helpers stay agnostic)."""
    if isinstance(dispatcher, ReplicaSet):
        for r in dispatcher.replicas:
            if r.batcher is not None:
                yield r.batcher
    elif dispatcher is not None:
        yield dispatcher


def build_fleet(plan: FleetPlan, name: str, build: Callable[[int | None, Any], MicroBatcher]):
    """Build one dispatcher for ``plan``: the plain started MicroBatcher
    for a 1-replica plan (``build(None, mesh)`` — zero behavior change), a
    :class:`ReplicaSet` otherwise. ``build(rid, mesh)`` must return a
    STARTED batcher; it is also the revive hook, so it must be safe to
    call again for a single replica long after initialization."""
    if plan.replicas <= 1:
        return build(None, plan.meshes[0])
    return ReplicaSet(
        name, build, plan.meshes, policy=plan.policy,
        devices_per_replica=plan.devices_per_replica,
    )


# -- the replica set ---------------------------------------------------------

#: live ReplicaSets by name (weakrefs, last-writer-wins): the autopilot's
#: scale loop discovers the process's fleets here — same idiom as the WFQ
#: queue registry in ``utils/qos.py`` and the batcher registry.
_fleet_registry: dict[str, "weakref.ref[ReplicaSet]"] = {}
_fleet_reg_lock = threading.Lock()


def live_fleets() -> list["ReplicaSet"]:
    """Every live (not-yet-closed) ReplicaSet in the process."""
    with _fleet_reg_lock:
        items = list(_fleet_registry.items())
    out: list[ReplicaSet] = []
    for name, ref in items:
        fs = ref()
        if fs is None:
            with _fleet_reg_lock:
                if _fleet_registry.get(name) is ref:
                    del _fleet_registry[name]
        elif not fs._closed:
            out.append(fs)
    return out


@dataclass
class Replica:
    """One mesh slice + its batcher + health state."""

    rid: int
    mesh: Any
    batcher: MicroBatcher | None
    state: str = SERVING
    streak: int = 0  # consecutive backend failure EVENTS (not futures)
    down_since: float | None = None
    dispatches: int = 0
    error: str | None = None
    #: recently counted exception objects — a failed batch settles every
    #: one of its futures with the SAME exception instance, and each must
    #: count as ONE failure event or a single bad batch of N >= the
    #: threshold would down the replica instantly. A small ring (not one
    #: slot) so two failed batches whose callbacks interleave (dispatch-
    #: thread failure racing a fetch-thread failure) still dedup; holding
    #: references (not id()) keeps identities from being recycled.
    recent_errs: deque = field(default_factory=lambda: deque(maxlen=4))

    @property
    def tag(self) -> str:
        return f"r{self.rid}"

    def load(self) -> float:
        b = self.batcher
        return float(b.load()) if b is not None else float("inf")


class ReplicaSet:
    """N MicroBatcher replicas behind one ``submit()``/``__call__``.

    Drop-in for the single MicroBatcher a manager used to own: the same
    entry points, deadlines, fingerprint quarantine gate and error
    vocabulary — plus dispatch-policy routing, per-replica failure
    accounting, submit-time failover (a full queue or wedged batcher tries
    the next sibling once around the ring) and background single-replica
    revival. With every replica down, ``submit`` raises
    :class:`~lumen_tpu.utils.deadline.WatchdogTimeout` — the serving layer
    maps it to a retryable UNAVAILABLE and the per-service circuit breaker
    counts it as the backend failure it is."""

    def __init__(
        self,
        name: str,
        build: Callable[[int | None, Any], MicroBatcher],
        meshes: list,
        policy: str | Any | None = None,
        failures: int | None = None,
        revive_s: float | None = None,
        clock: Callable[[], float] = time.monotonic,
        devices_per_replica: int = 1,
    ):
        if not meshes:
            raise ValueError("ReplicaSet needs at least one mesh/slot")
        self.name = name
        self.build = build
        self.policy = policy if policy is not None and not isinstance(policy, str) else make_policy(policy)
        self.failures = replica_failures() if failures is None else max(0, failures)
        self.revive_s = replica_revive_s() if revive_s is None else max(0.0, revive_s)
        #: chips one replica's mesh slice claims — the unit the autopilot's
        #: chip ledger accounts scale decisions in.
        self.devices_per_replica = max(1, devices_per_replica)
        self._clock = clock
        self._lock = threading.Lock()
        self._closed = False
        self._revive_thread: threading.Thread | None = None
        self._revive_wake = threading.Event()
        self.replicas = [Replica(i, mesh, build(i, mesh)) for i, mesh in enumerate(meshes)]
        ref = weakref.ref(self)

        def _gauges() -> dict:
            s = ref()
            if s is None:
                return {}
            with s._lock:
                out: dict = {
                    "replicas": len(s.replicas),
                    "down": sum(1 for r in s.replicas if r.state in (DOWN, REVIVING)),
                    "parked": sum(1 for r in s.replicas if r.state == PARKED),
                }
                snap = list(s.replicas)
            for r in snap:
                out[f"{r.tag}_state"] = _STATE_CODES[r.state]
                out[f"{r.tag}_dispatches"] = r.dispatches
                load = r.load()
                out[f"{r.tag}_load"] = -1 if load == float("inf") else int(load)
            return out

        self._gauge_fn = _gauges
        metrics.register_gauges(f"replica:{name}", _gauges)
        with _fleet_reg_lock:
            _fleet_registry[name] = ref

    # -- dispatch ---------------------------------------------------------

    def _pick(self, exclude: set[int]) -> Replica | None:
        with self._lock:
            live = [
                r
                for r in self.replicas
                if r.state == SERVING and r.rid not in exclude and r.batcher is not None
            ]
        if not live:
            return None
        return live[0] if len(live) == 1 else self.policy.pick(live)

    def submit(
        self, item: Any, deadline: float | None = None, fingerprint: str | None = None
    ) -> Future:
        """Route one item to a replica's batcher. Quarantine
        (:class:`PoisonInput`) and expired deadlines raise through
        unchanged — those are verdicts on the REQUEST, identical on every
        replica. A shed (:class:`QueueFull`) or wedge
        (:class:`WatchdogTimeout`) is a verdict on the REPLICA: the
        dispatcher fails over to the next sibling once around the ring
        before surfacing the last error."""
        last: BaseException | None = None
        tried: set[int] = set()
        for _ in range(len(self.replicas)):
            r = self._pick(tried)
            if r is None:
                break
            tried.add(r.rid)
            try:
                fut = r.batcher.submit(item, deadline=deadline, fingerprint=fingerprint)
            except (DeadlineExpired, PoisonInput):
                raise
            except WatchdogTimeout as e:
                # The batcher wedged since its watchdog fired: this replica
                # can never serve again without a revive — contain it now.
                self._mark_down(r, e)
                last = e
                continue
            except (QueueFull, RuntimeError) as e:
                # QueueFull: this replica is saturated, a sibling may not
                # be. RuntimeError("closed"): a revive is swapping the
                # batcher under us. Both: try the next replica.
                last = e
                continue
            if last is not None:
                # A prior replica failed and THIS one served: a request was
                # actually rerouted (counting at the failure site would
                # inflate the metric when no sibling exists to take over).
                metrics.count("replica_failovers")
                metrics.count(f"replica_failovers:{self.name}")
            with self._lock:
                r.dispatches += 1
            fut._lumen_replica_owner = r.batcher
            self._observe(r, fut)
            return fut
        if last is not None:
            raise last
        raise WatchdogTimeout(
            f"{self.name}: all {len(self.replicas)} replicas down; "
            "revival pending"
        )

    def __call__(
        self, item: Any, timeout: float | None = None, fingerprint: str | None = None
    ) -> Any:
        fut = self.submit(item, fingerprint=fingerprint)
        owner: MicroBatcher = fut._lumen_replica_owner
        return wait_for_batch(fut, owner.name, owner.stats, timeout)

    # -- health accounting ------------------------------------------------

    def _observe(self, r: Replica, fut: Future) -> None:
        def _done(f: Future, _r: Replica = r) -> None:
            if f.cancelled():
                return
            e = f.exception()
            if e is None:
                with self._lock:
                    _r.streak = 0
                return
            if isinstance(e, (DeadlineExpired, QueueFull, PoisonInput)):
                return  # caller-budget / payload verdicts: not the replica's fault
            self._record_failure(_r, e)

        fut.add_done_callback(_done)

    def _record_failure(self, r: Replica, err: BaseException) -> None:
        if isinstance(err, WatchdogTimeout):
            # A watchdog verdict wedges the batcher permanently: down now,
            # regardless of the streak threshold.
            self._mark_down(r, err)
            return
        with self._lock:
            if r.state != SERVING:
                return
            if any(err is e for e in r.recent_errs):
                return  # same failed batch: this event was already counted
            r.recent_errs.append(err)
            r.streak += 1
            trip = self.failures > 0 and r.streak >= self.failures
        if trip:
            self._mark_down(r, err)

    def _mark_down(self, r: Replica, err: BaseException) -> None:
        with self._lock:
            if self._closed or r.state != SERVING:
                return
            r.state = DOWN
            r.down_since = self._clock()
            r.error = f"{type(err).__name__}: {err}"
        metrics.count("replica_down")
        metrics.count(f"replica_down:{self.name}")
        telemetry.record_event(
            "replica_down", f"{self.name}/{r.tag}",
            f"replica marked down ({r.error}); siblings keep serving",
        )
        logger.error(
            "%s: replica %s DOWN (%s) — siblings keep serving%s",
            self.name, r.tag, r.error,
            f"; revive in {self.revive_s:.1f}s" if self.revive_s > 0 else "",
        )
        if self.revive_s > 0:
            self._ensure_revive_thread()

    # -- revival ----------------------------------------------------------

    def _ensure_revive_thread(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._revive_thread is None or not self._revive_thread.is_alive():
                self._revive_thread = threading.Thread(
                    target=self._revive_loop, name=f"{self.name}-revive", daemon=True
                )
                self._revive_thread.start()
        self._revive_wake.set()

    def _due(self) -> list[Replica]:
        now = self._clock()
        with self._lock:
            return [
                r
                for r in self.replicas
                if r.state == DOWN
                and r.down_since is not None
                and now - r.down_since >= self.revive_s
            ]

    def _revive_loop(self) -> None:
        while not self._closed:
            # Sleep until the earliest pending cooldown elapses (the wake
            # event covers newly-downed replicas) instead of polling at
            # 20 Hz for the whole down window; capped so a fake/skewed
            # clock can never park the thread past a real due time.
            with self._lock:
                downs = [
                    r.down_since
                    for r in self.replicas
                    if r.state == DOWN and r.down_since is not None
                ]
            if downs:
                delay = min(d + self.revive_s for d in downs) - self._clock()
                timeout = min(max(delay, 0.01), 0.5)
            else:
                timeout = 0.05
            self._revive_wake.wait(timeout=timeout)
            self._revive_wake.clear()
            for r in self._due():
                self.revive(r.rid)
            with self._lock:
                # Retire when nothing is DOWN (a PARKED replica is
                # voluntary idleness, never revived — it must not keep
                # this thread polling forever); clear the slot under the
                # lock BEFORE exiting so _ensure_revive_thread never races
                # a thread that decided to exit but still reports
                # is_alive().
                if self._closed or all(r.state != DOWN for r in self.replicas):
                    self._revive_thread = None
                    return

    def revive(self, rid: int) -> bool:
        """Rebuild ONE replica's batcher through the factory and swap it
        in — the replica-granular hot-swap. Siblings (their batchers,
        queues, compiled programs) are untouched. Returns True on success;
        a failed rebuild re-arms the cooldown and keeps the replica
        down."""
        r = self.replicas[rid]
        with self._lock:
            if self._closed or r.state != DOWN:
                # Only a DOWN replica gets rebuilt: reviving a SERVING one
                # would pull working capacity out of rotation (and a
                # failed rebuild would then down it for nothing).
                return False
            old, r.state = r.batcher, REVIVING
        logger.info("%s: reviving replica %s", self.name, r.tag)
        try:
            fresh = self.build(rid, r.mesh)
        except Exception as e:  # noqa: BLE001 - revive failure is the expected case
            with self._lock:
                r.state = DOWN
                r.down_since = self._clock()
                r.error = f"revive failed: {type(e).__name__}: {e}"
            metrics.count("replica_revive_failures")
            metrics.count(f"replica_revive_failures:{self.name}")
            logger.exception("%s: revive of %s failed", self.name, r.tag)
            return False
        closed_late = False
        with self._lock:
            if self._closed:
                closed_late = True
            else:
                r.batcher = fresh
                r.state = SERVING
                r.streak = 0
                r.down_since = None
                r.error = None
        if closed_late:
            fresh.close()
            return False
        metrics.count("replica_revivals")
        metrics.count(f"replica_revivals:{self.name}")
        telemetry.record_event(
            "replica_revive", f"{self.name}/{r.tag}",
            "dead replica's batcher rebuilt and swapped back in",
        )
        logger.info("%s: replica %s revived", self.name, r.tag)
        if old is not None:
            try:
                old.close()
            except Exception:  # noqa: BLE001 - best-effort teardown of the wedge
                logger.exception("%s: closing dead replica %s failed", self.name, r.tag)
        return True

    # -- scale actuation (park / unpark) ----------------------------------

    def active_count(self) -> int:
        """Replicas currently SERVING (the chip-claim unit count)."""
        with self._lock:
            return sum(1 for r in self.replicas if r.state == SERVING)

    def parked_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.state == PARKED)

    def park(self, rid: int | None = None) -> int | None:
        """Release one SERVING replica's mesh slice: close its batcher and
        mark it PARKED (skipped by dispatch, exempt from auto-revival).
        ``rid`` None parks the highest-rid serving replica — deterministic,
        and the inverse of :meth:`unpark`'s lowest-parked-first. Refuses to
        park the LAST serving replica (cold families keep a floor of 1 —
        an empty fleet would turn every request into a watchdog error).
        Returns the parked rid, or None when nothing was parked."""
        with self._lock:
            if self._closed:
                return None
            serving = [r for r in self.replicas if r.state == SERVING]
            if len(serving) <= 1:
                return None
            if rid is None:
                r = serving[-1]
            else:
                r = self.replicas[rid]
                if r.state != SERVING:
                    return None
            old, r.batcher = r.batcher, None
            r.state = PARKED
            r.streak = 0
            r.down_since = None
            r.error = None
        metrics.count("replica_parked")
        metrics.count(f"replica_parked:{self.name}")
        telemetry.record_event(
            "replica_park", f"{self.name}/{r.tag}",
            f"replica parked: {self.devices_per_replica} chip slice(s) "
            "released; siblings keep serving",
        )
        logger.info("%s: replica %s PARKED (scale-down)", self.name, r.tag)
        if old is not None:
            try:
                # close() drains the queue (queued entries settle loudly)
                # and retires the collector/fetch threads — the slice's
                # compiled programs go with it; an unpark recompiles or
                # hits the persistent compile cache.
                old.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                logger.exception("%s: closing parked replica %s failed", self.name, r.tag)
        return r.rid

    def unpark(self, rid: int | None = None) -> int | None:
        """Claim a slice back: rebuild one PARKED replica's batcher through
        the factory (the same ``build(rid, mesh)`` hook revival uses) and
        return it to dispatch. ``rid`` None unparks the lowest-rid parked
        replica. Returns the unparked rid, or None (nothing parked, closed,
        or the rebuild failed — the replica stays parked; unlike a DOWN
        replica there is no cooldown to re-arm, the next scale-up retries)."""
        with self._lock:
            if self._closed:
                return None
            parked = [r for r in self.replicas if r.state == PARKED]
            if not parked:
                return None
            if rid is None:
                r = parked[0]
            else:
                r = self.replicas[rid]
                if r.state != PARKED:
                    return None
            r.state = REVIVING
        try:
            fresh = self.build(r.rid, r.mesh)
        except Exception as e:  # noqa: BLE001 - rebuild failure keeps it parked
            with self._lock:
                r.state = PARKED
                r.error = f"unpark failed: {type(e).__name__}: {e}"
            metrics.count("replica_revive_failures")
            metrics.count(f"replica_revive_failures:{self.name}")
            logger.exception("%s: unpark of %s failed", self.name, r.tag)
            return None
        closed_late = False
        with self._lock:
            if self._closed:
                closed_late = True
            else:
                r.batcher = fresh
                r.state = SERVING
                r.streak = 0
                r.error = None
        if closed_late:
            fresh.close()
            return None
        metrics.count("replica_unparked")
        metrics.count(f"replica_unparked:{self.name}")
        telemetry.record_event(
            "replica_unpark", f"{self.name}/{r.tag}",
            f"parked replica rebuilt: {self.devices_per_replica} chip "
            "slice(s) claimed",
        )
        logger.info("%s: replica %s unparked (scale-up)", self.name, r.tag)
        return r.rid

    # -- telemetry / lifecycle --------------------------------------------

    def states(self) -> dict[str, str]:
        """``{"r0": "serving", ...}`` — surfaced in ``Health`` trailing
        metadata (``lumen-replica-status``) and capability extra."""
        with self._lock:
            return {r.tag: r.state for r in self.replicas}

    @property
    def stats(self) -> dict:
        """Aggregate of every live replica's batcher stats (capability /
        bench telemetry; per-replica detail lives on the gauges)."""
        agg: dict = {}
        for b in each_batcher(self):
            for k, v in b.stats.items():
                agg[k] = agg.get(k, 0) + v
        return agg

    @property
    def buckets(self) -> list[int]:
        for b in each_batcher(self):
            return b.buckets
        return []

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread = self._revive_thread
        self._revive_wake.set()
        if thread is not None:
            thread.join(timeout=5)
        for r in self.replicas:
            if r.batcher is not None:
                try:
                    r.batcher.close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    logger.exception("%s: closing replica %s failed", self.name, r.tag)
        metrics.unregister_gauges(f"replica:{self.name}", self._gauge_fn)
        with _fleet_reg_lock:
            ref = _fleet_registry.get(self.name)
            if ref is not None and ref() is self:
                del _fleet_registry[self.name]


# -- engine fleets (park/unpark for non-batcher families) --------------------


class EngineFleet:
    """Scale-actuator adapter for model families that dispatch WITHOUT a
    MicroBatcher — the continuous-batching VLM decode engines and the
    OCR direct dispatcher. Speaks exactly the duck type the autopilot's
    scale loop reads (``replicas`` with ``.rid``/``.state``/``.batcher``,
    ``park``/``unpark``, ``devices_per_replica``, ``_closed``) and joins
    the same fleet registry, so chip-ledger reallocation covers all four
    families instead of only the batcher-backed ones.

    The "batcher" slot of each :class:`Replica` holds the engine itself
    (anything with ``.name``/``.load()``/``.close()``). ``build(rid)``
    is the unpark hook rebuilding one engine on its original mesh slice;
    a fleet without one (OCR's single direct dispatcher) can still hold
    its chip claim in the ledger and report duty, but never grows.
    Health surfaces (``replica_states_of``, ``lumen-replica-status``)
    filter on :class:`ReplicaSet`, so an EngineFleet changes none of the
    existing Health payloads."""

    def __init__(
        self,
        name: str,
        engines: list,
        build: Callable[[int], Any] | None = None,
        devices_per_replica: int = 1,
    ):
        if not engines:
            raise ValueError("EngineFleet needs at least one engine")
        self.name = name
        self.build = build
        self.devices_per_replica = max(1, devices_per_replica)
        self._lock = threading.Lock()
        self._closed = False
        self.replicas = [Replica(i, None, eng) for i, eng in enumerate(engines)]
        ref = weakref.ref(self)

        def _gauges() -> dict:
            s = ref()
            if s is None:
                return {}
            with s._lock:
                snap = list(s.replicas)
                out: dict = {
                    "replicas": len(snap),
                    "parked": sum(1 for r in snap if r.state == PARKED),
                }
            for r in snap:
                out[f"{r.tag}_state"] = _STATE_CODES[r.state]
                load = r.load()
                out[f"{r.tag}_load"] = -1 if load == float("inf") else int(load)
            return out

        self._gauge_fn = _gauges
        metrics.register_gauges(f"replica:{name}", _gauges)
        with _fleet_reg_lock:
            _fleet_registry[name] = ref

    def serving_engines(self) -> list:
        """The engines dispatch may use right now (the manager's pick
        loop consults this instead of its boot-time engine list, so a
        parked engine stops receiving work the moment it parks)."""
        with self._lock:
            return [
                r.batcher
                for r in self.replicas
                if r.state == SERVING and r.batcher is not None
            ]

    def active_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.state == SERVING)

    def parked_count(self) -> int:
        with self._lock:
            return sum(1 for r in self.replicas if r.state == PARKED)

    def states(self) -> dict[str, str]:
        with self._lock:
            return {r.tag: r.state for r in self.replicas}

    def park(self, rid: int | None = None) -> int | None:
        """Close one SERVING engine and release its slice — the same
        contract (and event/counter vocabulary) as
        :meth:`ReplicaSet.park`, including the floor of 1: the last
        serving engine is never parked, so a 1-unit family (OCR today)
        holds its ledger claim but can never be scaled to zero."""
        with self._lock:
            if self._closed:
                return None
            serving = [r for r in self.replicas if r.state == SERVING]
            if len(serving) <= 1:
                return None
            if rid is None:
                r = serving[-1]
            else:
                r = self.replicas[rid]
                if r.state != SERVING:
                    return None
            old, r.batcher = r.batcher, None
            r.state = PARKED
            r.error = None
        metrics.count("replica_parked")
        metrics.count(f"replica_parked:{self.name}")
        telemetry.record_event(
            "replica_park", f"{self.name}/{r.tag}",
            f"engine parked: {self.devices_per_replica} chip slice(s) "
            "released; sibling engines keep serving",
        )
        logger.info("%s: engine %s PARKED (scale-down)", self.name, r.tag)
        if old is not None:
            try:
                old.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                logger.exception("%s: closing parked engine %s failed", self.name, r.tag)
        return r.rid

    def unpark(self, rid: int | None = None) -> int | None:
        """Rebuild one PARKED engine through the build hook and return it
        to dispatch. No hook = no growth (the fleet only ever shrinks to
        its floor and back by operator restart)."""
        if self.build is None:
            return None
        with self._lock:
            if self._closed:
                return None
            parked = [r for r in self.replicas if r.state == PARKED]
            if not parked:
                return None
            if rid is None:
                r = parked[0]
            else:
                r = self.replicas[rid]
                if r.state != PARKED:
                    return None
            r.state = REVIVING
        try:
            fresh = self.build(r.rid)
        except Exception as e:  # noqa: BLE001 - rebuild failure keeps it parked
            with self._lock:
                r.state = PARKED
                r.error = f"unpark failed: {type(e).__name__}: {e}"
            metrics.count("replica_revive_failures")
            metrics.count(f"replica_revive_failures:{self.name}")
            logger.exception("%s: unpark of %s failed", self.name, r.tag)
            return None
        closed_late = False
        with self._lock:
            if self._closed:
                closed_late = True
            else:
                r.batcher = fresh
                r.state = SERVING
                r.error = None
        if closed_late:
            try:
                fresh.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
            return None
        metrics.count("replica_unparked")
        metrics.count(f"replica_unparked:{self.name}")
        telemetry.record_event(
            "replica_unpark", f"{self.name}/{r.tag}",
            f"parked engine rebuilt: {self.devices_per_replica} chip "
            "slice(s) claimed",
        )
        logger.info("%s: engine %s unparked (scale-up)", self.name, r.tag)
        return r.rid

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            snap = list(self.replicas)
        for r in snap:
            if r.batcher is not None:
                try:
                    r.batcher.close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    logger.exception("%s: closing engine %s failed", self.name, r.tag)
        metrics.unregister_gauges(f"replica:{self.name}", self._gauge_fn)
        with _fleet_reg_lock:
            ref = _fleet_registry.get(self.name)
            if ref is not None and ref() is self:
                del _fleet_registry[self.name]


# -- capability surface ------------------------------------------------------


def replica_states_of(*dispatchers) -> dict:
    """Per-fleet replica states keyed by dispatcher name — the shared body
    of every service's ``replica_states()`` hook (plain batchers and None
    slots are skipped; names are manager-scoped so multi-manager services
    never collide)."""
    return {
        d.name: d.states() for d in dispatchers if isinstance(d, ReplicaSet)
    }


def topology_extra(primary_mesh=None, *dispatchers) -> dict[str, str]:
    """Device topology + replica layout for a service's capability
    ``extra`` — so fleet-internal clients can pick endpoints without
    probing. ``primary_mesh`` is replica 0's mesh (or the family's only
    mesh); ``dispatchers`` are the family's batchers/ReplicaSets."""
    import jax

    out = {"device_count": str(jax.local_device_count())}
    if primary_mesh is not None:
        out["mesh_axes"] = ",".join(
            f"{k}={v}" for k, v in dict(primary_mesh.shape).items()
        )
        out["devices_per_replica"] = str(math.prod(dict(primary_mesh.shape).values()))
    fleet = next((d for d in dispatchers if isinstance(d, ReplicaSet)), None)
    if fleet is None:
        out["replicas"] = "1"
        return out
    states = fleet.states()
    out["replicas"] = str(len(fleet.replicas))
    out["replica_policy"] = fleet.policy.name
    # states() preserves rid order (r0, r1, ..., r10, ...); position i in
    # the joined string IS replica i — a lexicographic sort would misorder
    # fleets of 10+ replicas.
    out["replica_states"] = ",".join(states.values())
    return out
