"""Multi-tenant QoS layer — runtime-facing entry point.

The implementation lives in :mod:`lumen_tpu.utils.qos` for the same
reason ``utils/deadline.py`` and ``utils/trace.py`` live in ``utils``:
the jax-free serving base class (and the client) must import the tenant
contextvar, the quota gate and the retry-after meta key without dragging
in the jax-importing runtime package ``__init__``. This module re-exports
the surface runtime components use — the micro-batcher builds its
:class:`~lumen_tpu.utils.qos.WFQAdmissionQueue` through here, the ingest
pipeline tags its work ``bulk`` — so runtime code has one local name for
the layer.

See :mod:`lumen_tpu.utils.qos` for the full design notes: virtual-time
weighted-fair queuing over per-tenant sub-queues, the
interactive>bulk priority lanes and the brownout ladder, per-tenant token
buckets with retry-after hints, and the ``tenant_flood`` fault point.
"""

from ..utils.qos import (  # noqa: F401 - re-exported runtime surface
    DEFAULT_TENANT,
    LANE_BULK,
    LANE_INTERACTIVE,
    RETRY_AFTER_META,
    TENANT_META_KEY,
    TenantQuota,
    WFQAdmissionQueue,
    activate,
    current_lane,
    current_qos,
    current_tenant,
    deactivate,
    get_quota,
    live_queues,
    qos_context,
    reset_quota,
    retry_after_ms,
    service_extra,
    status,
    tenant_rps,
    tenant_weight,
    wfq_enabled,
)

__all__ = [
    "DEFAULT_TENANT",
    "LANE_BULK",
    "LANE_INTERACTIVE",
    "RETRY_AFTER_META",
    "TENANT_META_KEY",
    "TenantQuota",
    "WFQAdmissionQueue",
    "activate",
    "current_lane",
    "current_qos",
    "current_tenant",
    "deactivate",
    "get_quota",
    "live_queues",
    "qos_context",
    "reset_quota",
    "retry_after_ms",
    "service_extra",
    "status",
    "tenant_rps",
    "tenant_weight",
    "wfq_enabled",
]
