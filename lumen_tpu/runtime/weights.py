"""Checkpoint loading: safetensors / torch checkpoints -> jnp parameter trees.

Replaces the reference's opaque-graph model loading (ONNX session files,
``onnxrt_backend.py``) with explicit weight trees for Flax modules. Handles:

- ``.safetensors`` (single file or ``*.safetensors.index.json`` shards),
- torch ``.bin``/``.pt`` pickles (``weights_only`` load; torch is CPU-only
  in this image and used purely as a deserializer),
- layout conversion helpers (torch ``Linear [out,in]`` -> jax ``[in,out]``,
  torch conv ``OIHW`` -> flax ``HWIO``),
- a small regex-rule engine for checkpoint-key -> param-tree-path renames
  that model converters build on.
"""

from __future__ import annotations

import json
import logging
import os
import re
from typing import Callable, Iterable

import numpy as np

logger = logging.getLogger(__name__)


class WeightLoadError(Exception):
    pass


# -- raw state-dict loading -------------------------------------------------


def load_safetensors(path: str) -> dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    try:
        return dict(load_file(path))
    except Exception as e:  # noqa: BLE001
        # bf16 tensors are not numpy-native; only that case falls back
        # through torch — anything else (corrupt file, bad path) re-raises.
        msg = str(e).lower()
        if "bfloat16" not in msg and "bf16" not in msg:
            raise WeightLoadError(f"cannot load safetensors file {path}: {e}") from e
        logger.debug("bf16 safetensors %s; loading via torch", path)
        from safetensors.torch import load_file as load_torch

        return {k: _torch_to_numpy(v) for k, v in load_torch(path).items()}


def load_sharded_safetensors(index_path: str) -> dict[str, np.ndarray]:
    with open(index_path, "r", encoding="utf-8") as f:
        index = json.load(f)
    base = os.path.dirname(index_path)
    out: dict[str, np.ndarray] = {}
    for shard in sorted(set(index["weight_map"].values())):
        out.update(load_safetensors(os.path.join(base, shard)))
    return out


def load_torch_checkpoint(path: str) -> dict[str, np.ndarray]:
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(state, dict) and "state_dict" in state and isinstance(state["state_dict"], dict):
        state = state["state_dict"]
    return {k: _torch_to_numpy(v) for k, v in state.items() if hasattr(v, "numpy") or hasattr(v, "detach")}


def _torch_to_numpy(t) -> np.ndarray:
    import torch

    t = t.detach()
    if t.dtype == torch.bfloat16:
        # numpy has no bf16; round-trip through fp32 (values preserved).
        t = t.to(torch.float32)
    return t.cpu().numpy()


def load_state_dict(model_dir: str) -> dict[str, np.ndarray]:
    """Load whatever checkpoint format a model directory carries, preferring
    safetensors (sharded, then single), then torch pickles."""
    index = [f for f in os.listdir(model_dir) if f.endswith(".safetensors.index.json")]
    if index:
        return load_sharded_safetensors(os.path.join(model_dir, index[0]))
    st = sorted(f for f in os.listdir(model_dir) if f.endswith(".safetensors"))
    if st:
        out: dict[str, np.ndarray] = {}
        for f in st:
            out.update(load_safetensors(os.path.join(model_dir, f)))
        return out
    binaries = sorted(
        f for f in os.listdir(model_dir) if f.endswith((".bin", ".pt")) and not f.startswith(".")
    )
    if binaries:
        out = {}
        for f in binaries:
            out.update(load_torch_checkpoint(os.path.join(model_dir, f)))
        return out
    raise WeightLoadError(f"no checkpoint files found in {model_dir}")


# -- layout conversion ------------------------------------------------------


def linear_kernel(w: np.ndarray) -> np.ndarray:
    """torch ``nn.Linear.weight`` [out, in] -> flax ``Dense`` kernel [in, out]."""
    return np.ascontiguousarray(w.T)


def conv_kernel(w: np.ndarray) -> np.ndarray:
    """torch conv weight OIHW -> flax conv kernel HWIO."""
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


# -- rename-rule engine -----------------------------------------------------

#: (regex pattern, replacement-template, optional value transform)
RenameRule = tuple[str, str, Callable[[np.ndarray], np.ndarray] | None]


def apply_rules(
    state: dict[str, np.ndarray],
    rules: Iterable[RenameRule],
    strict: bool = False,
    drop: Iterable[str] = (),
) -> dict[str, np.ndarray]:
    """Map checkpoint keys to param-tree paths via the first matching rule.

    Output keys are '/'-separated param paths (e.g.
    ``vision/blocks_0/attn/qkv/kernel``). ``drop`` patterns are removed
    silently; unmatched keys raise (strict) or are logged and skipped.
    """
    compiled = [(re.compile(p), t, fn) for p, t, fn in rules]
    dropped = [re.compile(p) for p in drop]
    out: dict[str, np.ndarray] = {}
    unmatched: list[str] = []
    for key, value in state.items():
        if any(d.search(key) for d in dropped):
            continue
        for pat, template, fn in compiled:
            m = pat.fullmatch(key)
            if m:
                new_key = m.expand(template)
                out[new_key] = fn(value) if fn else value
                break
        else:
            unmatched.append(key)
    if unmatched:
        msg = f"{len(unmatched)} checkpoint keys unmatched by rename rules: {unmatched[:8]}"
        if strict:
            raise WeightLoadError(msg)
        logger.warning(msg)
    return out


def unflatten(flat: dict[str, np.ndarray]) -> dict:
    """'/'-separated flat keys -> nested param dict (a Flax params tree)."""
    tree: dict = {}
    for key, value in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
            if not isinstance(node, dict):
                raise WeightLoadError(f"key {key!r} conflicts with leaf at {p!r}")
        if isinstance(node.get(parts[-1]), dict):
            raise WeightLoadError(f"key {key!r} conflicts with existing subtree")
        node[parts[-1]] = value
    return tree


def flatten(tree: dict, prefix: str = "") -> dict[str, np.ndarray]:
    out: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else k
        if isinstance(v, dict):
            out.update(flatten(v, key))
        else:
            out[key] = v
    return out


# -- native checkpoint format ------------------------------------------------
#
# The lumen-tpu "jax" runtime format: safetensors whose keys are
# '/'-separated Flax paths prefixed with the variable collection
# (``params/...`` or ``batch_stats/...``). Shared by every model family.


def is_native_checkpoint(state: dict[str, np.ndarray]) -> bool:
    return all(k.startswith(("params/", "batch_stats/")) for k in state)


def split_collections(flat: dict[str, np.ndarray]) -> dict[str, dict]:
    """'params/a/b', 'batch_stats/a/b' flat keys -> {'params': tree, ...}."""
    grouped: dict[str, dict[str, np.ndarray]] = {}
    for key, value in flat.items():
        coll, _, rest = key.partition("/")
        if not rest:
            raise WeightLoadError(f"native checkpoint key missing collection prefix: {key!r}")
        grouped.setdefault(coll, {})[rest] = value
    return {coll: unflatten(tree) for coll, tree in grouped.items()}


def flatten_variables(variables: dict) -> dict[str, np.ndarray]:
    """Inverse of :func:`split_collections` (for saving native checkpoints)."""
    out: dict[str, np.ndarray] = {}
    for coll, tree in variables.items():
        for k, v in flatten(tree).items():
            out[f"{coll}/{k}"] = np.asarray(v)
    return out


def assert_tree_shapes(loaded: dict, initialized: dict) -> None:
    """Fidelity gate: a converted checkpoint must match the module's
    init-time tree exactly (names and shapes) — this is where silent
    conversion bugs die (SURVEY.md §7 hard part 3)."""
    lf, rf = flatten(loaded), flatten(initialized)
    missing = sorted(set(rf) - set(lf))
    extra = sorted(set(lf) - set(rf))
    if missing or extra:
        raise WeightLoadError(
            f"param tree mismatch: missing={missing[:8]} extra={extra[:8]} "
            f"(missing {len(missing)}, extra {len(extra)})"
        )
    bad = [
        f"{k}: ckpt{tuple(lf[k].shape)} vs init{tuple(rf[k].shape)}"
        for k in rf
        if tuple(lf[k].shape) != tuple(rf[k].shape)
    ]
    if bad:
        raise WeightLoadError(f"param shape mismatches: {bad[:8]}")
