"""Device-mesh construction and axis conventions.

Framework-wide logical axis names (used by every sharded model and the
batch-ingest scheduler):

- ``data``   — batch/data parallelism (throughput scaling),
- ``model``  — tensor parallelism (attention heads / MLP shards),
- ``seq``    — sequence/context parallelism (ring attention / Ulysses),
- ``stage``  — pipeline parallelism (GPipe microbatch schedule),
- ``expert`` — expert parallelism (MoE all-to-all dispatch).

The reference has no device mesh at all (its concurrency is a gRPC thread
pool over single-model ONNX sessions, ``src/lumen/server.py:232-235``);
here every model call runs under a ``jax.sharding.Mesh`` even on one chip
(trivial 1-device mesh), so scaling out is a config change, not a code path.
"""

from __future__ import annotations

import logging
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
STAGE_AXIS = "stage"
EXPERT_AXIS = "expert"


_degrade_warned: set[str] = set()


def resolve_axes(axes: dict[str, int], n_devices: int) -> dict[str, int]:
    """Resolve a mesh request ({axis: size, one size may be -1}) against the
    actual device count. The -1 axis absorbs all remaining devices.

    A ``data`` axis that does not fit degrades to the largest size that
    does (one-shot warning per request shape): ``data`` is the replica/
    throughput axis, so ``{"data": 8}`` on a 4-chip host should serve 4
    ways, not fail boot. When no exact cover exists under the requested
    size (e.g. ``{"data": 3}`` on 8 devices) the resolved mesh may use
    FEWER devices than the host has — :func:`build_mesh` slices the device
    list to fit. Non-``data`` axes (tensor/sequence/expert parallelism)
    still raise: silently shrinking a TP axis would change which
    checkpoints even fit, and that IS an operator error."""
    fixed = math.prod(s for s in axes.values() if s != -1)
    degraded = False
    if n_devices % fixed != 0:
        others = math.prod(s for a, s in axes.items() if a != DATA_AXIS and s != -1)
        dp = axes.get(DATA_AXIS, 0)
        if dp > 0 and n_devices % others == 0:
            # Prefer the exact cover (every device used); otherwise the
            # largest dividing size <= the request (idle devices, warned).
            slots = n_devices // others
            new_dp = min(dp, slots)
            while slots % new_dp:
                new_dp -= 1
            key = f"{sorted(axes.items())}@{n_devices}"
            if key not in _degrade_warned:
                _degrade_warned.add(key)
                logger.warning(
                    "mesh axes %s do not divide device count %d; degrading "
                    "data axis %d -> %d",
                    axes, n_devices, dp, new_dp,
                )
            axes = {**axes, DATA_AXIS: new_dp}
            fixed = math.prod(s for s in axes.values() if s != -1)
            degraded = True
        if n_devices % fixed != 0:
            raise ValueError(
                f"mesh axes {axes} do not divide device count {n_devices} "
                f"(fixed product {fixed})"
            )
    resolved = dict(axes)
    for name, size in axes.items():
        if size == -1:
            resolved[name] = n_devices // fixed
            break
    used = math.prod(resolved.values())
    if used != n_devices:
        # Consistent degrade policy: an all-fixed request that covers
        # FEWER devices than the host has (whether asked for directly,
        # e.g. {"data": 4} on 8 chips, or produced by the data-axis
        # degrade above) serves on the device prefix — build_mesh slices
        # the list — instead of failing boot. Over-subscription or a
        # non-sliceable remainder still raises.
        if used < n_devices and n_devices % used == 0:
            key = f"{sorted(resolved.items())}@{n_devices}"
            if not degraded and key not in _degrade_warned:
                _degrade_warned.add(key)
                logger.warning(
                    "mesh %s uses %d of %d device(s); serving on the prefix",
                    resolved, used, n_devices,
                )
        else:
            raise ValueError(
                f"mesh {resolved} uses {used} devices, have {n_devices}"
            )
    return resolved


def build_mesh(
    axes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named mesh; default is every device this process can
    address, on one ``data`` axis. Under multi-process JAX
    (``jax.distributed``) the default is deliberately the LOCAL devices,
    not the global 8+: serving managers built per host must be able to
    fetch their own results (a mesh spanning non-addressable devices
    can't be read from one process), which is the per-host-frontend
    layout of SURVEY §7 step 10. Cross-host programs (training,
    multi-host ingest) pass the global ``jax.devices()`` explicitly."""
    devices = list(devices if devices is not None else jax.local_devices())
    axes = axes or {DATA_AXIS: -1}
    resolved = resolve_axes(axes, len(devices))
    names = tuple(resolved)
    shape = tuple(resolved[n] for n in names)
    used = math.prod(shape)
    if used < len(devices):
        # A degraded data axis (resolve_axes warning) may cover fewer
        # devices than the host has: serve on the prefix instead of
        # failing boot. The idle tail stays available to other services.
        devices = devices[:used]
    if len(devices) == 1:
        arr = np.array(devices).reshape(shape)
    else:
        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    mesh = Mesh(arr, names)
    # Log the device scope explicitly under multi-process JAX: the default
    # is per-host (local) devices, and a cross-host program that meant to
    # pass jax.devices() but didn't is diagnosable only from this line.
    n_global = jax.device_count()
    scope = "local" if len(devices) < n_global else "global"
    logger.info(
        "mesh: %s over %d device(s) (%s scope; %d devices globally)",
        dict(zip(names, shape)), len(devices), scope, n_global,
    )
    return mesh


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over ``data``; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_batch_multiple(mesh: Mesh) -> int:
    """Global batch sizes fed to a data-parallel jit must be a multiple of
    this (the ``data`` axis size)."""
    return mesh.shape.get(DATA_AXIS, 1)
