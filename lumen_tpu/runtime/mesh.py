"""Device-mesh construction and axis conventions.

Framework-wide logical axis names (used by every sharded model and the
batch-ingest scheduler):

- ``data``   — batch/data parallelism (throughput scaling),
- ``model``  — tensor parallelism (attention heads / MLP shards),
- ``seq``    — sequence/context parallelism (ring attention / Ulysses),
- ``stage``  — pipeline parallelism (GPipe microbatch schedule),
- ``expert`` — expert parallelism (MoE all-to-all dispatch).

The reference has no device mesh at all (its concurrency is a gRPC thread
pool over single-model ONNX sessions, ``src/lumen/server.py:232-235``);
here every model call runs under a ``jax.sharding.Mesh`` even on one chip
(trivial 1-device mesh), so scaling out is a config change, not a code path.
"""

from __future__ import annotations

import logging
import math
from typing import Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
STAGE_AXIS = "stage"
EXPERT_AXIS = "expert"


def resolve_axes(axes: dict[str, int], n_devices: int) -> dict[str, int]:
    """Resolve a mesh request ({axis: size, one size may be -1}) against the
    actual device count. The -1 axis absorbs all remaining devices."""
    fixed = math.prod(s for s in axes.values() if s != -1)
    if n_devices % fixed != 0:
        raise ValueError(
            f"mesh axes {axes} do not divide device count {n_devices} "
            f"(fixed product {fixed})"
        )
    resolved = dict(axes)
    for name, size in axes.items():
        if size == -1:
            resolved[name] = n_devices // fixed
            break
    if math.prod(resolved.values()) != n_devices:
        raise ValueError(
            f"mesh {resolved} uses {math.prod(resolved.values())} devices, "
            f"have {n_devices}"
        )
    return resolved


def build_mesh(
    axes: dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a named mesh; default is every device this process can
    address, on one ``data`` axis. Under multi-process JAX
    (``jax.distributed``) the default is deliberately the LOCAL devices,
    not the global 8+: serving managers built per host must be able to
    fetch their own results (a mesh spanning non-addressable devices
    can't be read from one process), which is the per-host-frontend
    layout of SURVEY §7 step 10. Cross-host programs (training,
    multi-host ingest) pass the global ``jax.devices()`` explicitly."""
    devices = list(devices if devices is not None else jax.local_devices())
    axes = axes or {DATA_AXIS: -1}
    resolved = resolve_axes(axes, len(devices))
    names = tuple(resolved)
    shape = tuple(resolved[n] for n in names)
    if len(devices) == 1:
        arr = np.array(devices).reshape(shape)
    else:
        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    mesh = Mesh(arr, names)
    # Log the device scope explicitly under multi-process JAX: the default
    # is per-host (local) devices, and a cross-host program that meant to
    # pass jax.devices() but didn't is diagnosable only from this line.
    n_global = jax.device_count()
    scope = "local" if len(devices) < n_global else "global"
    logger.info(
        "mesh: %s over %d device(s) (%s scope; %d devices globally)",
        dict(zip(names, shape)), len(devices), scope, n_global,
    )
    return mesh


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) dim over ``data``; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_batch_multiple(mesh: Mesh) -> int:
    """Global batch sizes fed to a data-parallel jit must be a multiple of
    this (the ``data`` axis size)."""
    return mesh.shape.get(DATA_AXIS, 1)
