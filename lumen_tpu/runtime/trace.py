"""Request-scoped tracing layer — runtime-facing entry point.

The implementation lives in :mod:`lumen_tpu.utils.trace` for the same
reason ``utils/deadline.py`` and ``utils/request_notes.py`` live in
``utils``: the jax-free serving base class (and the logger, and the
example client) must be able to import the tracing contextvar without
dragging in the jax-importing runtime package ``__init__``. This module
re-exports the small surface runtime components use — the hot-path
contextvar read for span stitching (batcher, decode pool, result cache,
quarantine) and the per-batch trace lifecycle (ingest pipeline) — so
runtime code has one local name for the layer; everything else (the
recorder, Perfetto export, knobs) is :mod:`lumen_tpu.utils.trace`'s.

See :mod:`lumen_tpu.utils.trace` for the full design notes: contextvar
propagation, cross-thread :class:`~lumen_tpu.utils.trace.SpanHandle`
stitching, tail-sampled ring retention, and the Perfetto /
``GET /traces`` export.
"""

from ..utils.trace import (  # noqa: F401 - re-exported runtime surface
    begin_request,
    current_trace,
    enabled,
    finish_request,
    get_recorder,
    reset_recorder,
    span,
)

__all__ = [
    "begin_request",
    "current_trace",
    "enabled",
    "finish_request",
    "get_recorder",
    "reset_recorder",
    "span",
]
