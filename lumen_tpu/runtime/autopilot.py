"""Autopilot: closed-loop self-healing capacity control over the telemetry spine.

PRs 7-9 built every sensor (SLO burn rates, duty-cycle meters, queue-drain
and padding-waste estimates) and every actuator (replica count, brownout
rungs, batch-window caps) a capacity controller needs — but a human read
``/stats`` and edited env knobs. This module closes the loop: a background
controller (``LUMEN_AUTOPILOT=1``; default OFF, so tier-1 and unconfigured
deployments are byte-for-byte unchanged) ticks every
``LUMEN_AUTOPILOT_TICK_S`` seconds and runs three independent control
loops over the live registries:

- **scale** — per-family replica scaling with cross-family chip
  reallocation. Sensors: each fleet's mean ``device:{batcher}`` duty
  fraction and worst queue-drain estimate over the last
  ``LUMEN_AUTOPILOT_SENSE_S`` seconds. Actuators:
  :meth:`~lumen_tpu.runtime.fleet.ReplicaSet.park` /
  :meth:`~lumen_tpu.runtime.fleet.ReplicaSet.unpark` — the fleet's
  replica-granular build/revive machinery. A **chip ledger** makes the
  reallocation honest: its capacity latches to the boot-time claim total
  (so the controller can only *move* slices between families, never
  overcommit), an idle family's park frees ``devices_per_replica`` chips,
  and a hot family's unpark only proceeds when the ledger has that many
  free. Cold families keep a floor of 1 serving replica (``park`` refuses
  the last one).
- **brownout** — descend/ascend the PR 8 brownout ladder from SLO burn
  instead of raw occupancy. Sensor: the worst task ``burn_5m`` from the
  SLO engine. Actuator: :meth:`WFQAdmissionQueue.force_rung` on every live
  admission queue (a floor — occupancy can still push the effective rung
  higher). Hysteresis band: descend above ``LUMEN_AUTOPILOT_BURN_DESCEND``
  (default 1.0 — burning budget faster than sustainable), ascend only
  below ``LUMEN_AUTOPILOT_BURN_ASCEND`` (default 0.5), one rung per
  actuation.
- **window** — auto-tune each batcher's adaptive-window cap from windowed
  padding-waste telemetry (``batch_padded / (batch_items+batch_padded)``):
  waste above ``LUMEN_AUTOPILOT_WASTE_PCT`` grows the cap (wait longer,
  fill fuller batches), waste clearing below a quarter of that shrinks it
  back toward the configured base; the cap never leaves
  ``[base, 4 x base]``.

**Stability contract.** Every loop actuates through one gate: a
per-actuator cooldown (``LUMEN_AUTOPILOT_COOLDOWN_S`` — the same knob the
ISSUE names) keyed ``(loop, component)``, plus a global actuation rate
limit (``LUMEN_AUTOPILOT_RATE_PER_MIN``). Thresholds come in hysteresis
pairs (scale 0.75/0.20 duty, brownout 1.0/0.5 burn, window 30%/7.5%
waste), so an oscillating sensor crosses ONE threshold, not two — tier-1
proves no-flap under oscillation with a fake clock
(``tests/test_autopilot.py``). A loop with no sensor reading performs no
actuation (telemetry off = autopilot blind = autopilot inert), and each
loop has a manual-override knob (``LUMEN_AUTOPILOT_SCALE`` /
``_BROWNOUT`` / ``_WINDOW`` = ``0``) that disables its actuations while
the others keep running.

**Observability.** Every actuation lands in the flight recorder as a typed
event (``autopilot_scale`` / ``autopilot_brownout`` / ``autopilot_window``)
carrying the sensor readings that justified it, is counted on
``autopilot_actions(:loop)``, and is retained in a bounded decision ring
served by ``GET /autopilot`` on the observability sidecar (policy state,
per-loop enable flags, chip ledger, last N decisions). A compact summary
rides ``Health`` trailing metadata as ``lumen-autopilot-status``.

Deliberately duck-typed over the live registries
(:func:`~lumen_tpu.runtime.fleet.live_fleets`,
:func:`~lumen_tpu.runtime.batcher.live_batchers`,
:func:`~lumen_tpu.utils.qos.live_queues`) and injectable for tests: a
fake-clock Autopilot with fake fleets ticks deterministically, no threads,
no jax.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable

from ..utils import telemetry
from ..utils.env import env_float, env_int
from ..utils.metrics import metrics

logger = logging.getLogger(__name__)

AUTOPILOT_ENV = "LUMEN_AUTOPILOT"
TICK_ENV = "LUMEN_AUTOPILOT_TICK_S"
COOLDOWN_ENV = "LUMEN_AUTOPILOT_COOLDOWN_S"
SENSE_ENV = "LUMEN_AUTOPILOT_SENSE_S"
RATE_ENV = "LUMEN_AUTOPILOT_RATE_PER_MIN"
DECISIONS_ENV = "LUMEN_AUTOPILOT_DECISIONS"
SCALE_UP_ENV = "LUMEN_AUTOPILOT_SCALE_UP"
SCALE_DOWN_ENV = "LUMEN_AUTOPILOT_SCALE_DOWN"
BURN_DESCEND_ENV = "LUMEN_AUTOPILOT_BURN_DESCEND"
BURN_ASCEND_ENV = "LUMEN_AUTOPILOT_BURN_ASCEND"
WASTE_ENV = "LUMEN_AUTOPILOT_WASTE_PCT"
PREDICT_ENV = "LUMEN_AUTOPILOT_PREDICT"
HORIZON_ENV = "LUMEN_AUTOPILOT_HORIZON_S"

#: per-loop manual-override knobs: ``0`` keeps that loop observing but
#: never actuating (the operator holds that actuator by hand).
LOOP_ENVS = {
    "scale": "LUMEN_AUTOPILOT_SCALE",
    "brownout": "LUMEN_AUTOPILOT_BROWNOUT",
    "window": "LUMEN_AUTOPILOT_WINDOW",
}

#: gRPC Health trailing-metadata key carrying the compact autopilot state.
AUTOPILOT_META_KEY = "lumen-autopilot-status"

#: replica-state strings shared with runtime/fleet.py — string literals so
#: this module (and its fakes) never import the jax-adjacent fleet module
#: at import time.
_SERVING = "serving"
_PARKED = "parked"

#: minimum batch slots observed in the sense window before the window loop
#: trusts a padding-waste reading — two padded singletons are noise, not a
#: trend.
MIN_WINDOW_SLOTS = 16


def autopilot_enabled() -> bool:
    """``LUMEN_AUTOPILOT`` (default OFF): the master switch. Tier-1 runs
    with it unset — zero actuations and zero per-request overhead (the
    controller is a background tick, never on the request path)."""
    return os.environ.get(AUTOPILOT_ENV) == "1"


def autopilot_tick_s() -> float:
    """``LUMEN_AUTOPILOT_TICK_S``: controller tick period (default 5s)."""
    return env_float(TICK_ENV, 5.0, minimum=0.05)


def autopilot_cooldown_s() -> float:
    """``LUMEN_AUTOPILOT_COOLDOWN_S``: minimum seconds between two
    actuations of the SAME actuator (default 30) — the anti-flap floor."""
    return env_float(COOLDOWN_ENV, 30.0, minimum=0.0)


def autopilot_sense_s() -> float:
    """``LUMEN_AUTOPILOT_SENSE_S``: sensor window the duty/waste readings
    aggregate over (default 30s; longer = calmer, shorter = twitchier)."""
    return env_float(SENSE_ENV, 30.0, minimum=1.0)


def autopilot_rate_per_min() -> int:
    """``LUMEN_AUTOPILOT_RATE_PER_MIN``: global cap on actuations per
    rolling minute across ALL loops (default 12) — a runaway controller
    can only misconfigure the fleet this fast."""
    return env_int(RATE_ENV, 12, minimum=1)


def autopilot_decisions() -> int:
    """``LUMEN_AUTOPILOT_DECISIONS``: decision-ring capacity on
    ``GET /autopilot`` (default 64)."""
    return env_int(DECISIONS_ENV, 64, minimum=1)


def autopilot_predict() -> bool:
    """``LUMEN_AUTOPILOT_PREDICT`` (default OFF): arms short-horizon
    arrival-rate forecasting in the scale loop. The trend is fit over the
    per-bucket ``batch_items`` rates already in the telemetry rings and
    extrapolated ``LUMEN_AUTOPILOT_HORIZON_S`` ahead; the park/unpark
    gates then act on the WORSE of current and projected duty, so a
    rising family unparks before the reactive threshold trips. Off keeps
    the reactive thresholds (and the sensor readings) byte-identical."""
    return os.environ.get(PREDICT_ENV) == "1"


def autopilot_horizon_s() -> float:
    """``LUMEN_AUTOPILOT_HORIZON_S``: how far ahead the arrival-rate
    trend is extrapolated (default 60s — about one replica unpark's
    build+warmup cost, so the forecast leads by what acting costs)."""
    return env_float(HORIZON_ENV, 60.0, minimum=1.0)


def loop_enabled(loop: str) -> bool:
    """Per-loop manual override (:data:`LOOP_ENVS`): setting the loop's
    knob to ``0`` disables its actuations while the other loops keep
    running (default on when the autopilot itself is)."""
    return os.environ.get(LOOP_ENVS[loop], "1") != "0"


class Autopilot:
    """The three-loop capacity controller.

    ``tick()`` is the whole control step and is side-effect-deterministic
    under an injected clock — tests drive it directly; production wraps it
    in a daemon thread (:meth:`start`). Sources are injectable callables
    returning the live fleets / batchers / admission queues; the defaults
    read the process registries lazily (so building an Autopilot never
    imports jax-adjacent modules)."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        tick_s: float | None = None,
        cooldown_s: float | None = None,
        sense_s: float | None = None,
        rate_per_min: int | None = None,
        chip_capacity: int | None = None,
        fleets: Callable[[], list] | None = None,
        batchers: Callable[[], list] | None = None,
        queues: Callable[[], list] | None = None,
        predict: bool | None = None,
        horizon_s: float | None = None,
    ):
        self._clock = clock
        self.predict = autopilot_predict() if predict is None else bool(predict)
        self.horizon_s = (
            autopilot_horizon_s() if horizon_s is None else max(1.0, horizon_s)
        )
        self.tick_s = autopilot_tick_s() if tick_s is None else max(0.05, tick_s)
        self.cooldown_s = autopilot_cooldown_s() if cooldown_s is None else max(0.0, cooldown_s)
        self.sense_s = autopilot_sense_s() if sense_s is None else max(1.0, sense_s)
        self.rate_per_min = (
            autopilot_rate_per_min() if rate_per_min is None else max(1, rate_per_min)
        )
        # Ledger capacity: explicit, or latched from the first observed
        # claim total (see _tick_scale) — conservation-only reallocation.
        self.chip_capacity = chip_capacity
        self._fleets = fleets if fleets is not None else _default_fleets
        self._batchers = batchers if batchers is not None else _default_batchers
        self._queues = queues if queues is not None else _default_queues
        # Loop enables are latched at build (env is deploy-time config;
        # reset_autopilot()/a restart re-reads).
        self.loops = {name: loop_enabled(name) for name in LOOP_ENVS}
        self.scale_up_duty = env_float(SCALE_UP_ENV, 0.75, minimum=0.0, maximum=1.0)
        self.scale_down_duty = env_float(SCALE_DOWN_ENV, 0.20, minimum=0.0, maximum=1.0)
        if self.scale_down_duty >= self.scale_up_duty:
            # A collapsed/inverted hysteresis band would flap by
            # construction; restore the default band loudly.
            logger.warning(
                "%s=%.2f >= %s=%.2f collapses the scale hysteresis band; "
                "using defaults 0.20/0.75",
                SCALE_DOWN_ENV, self.scale_down_duty, SCALE_UP_ENV, self.scale_up_duty,
            )
            self.scale_up_duty, self.scale_down_duty = 0.75, 0.20
        self.burn_descend = env_float(BURN_DESCEND_ENV, 1.0, minimum=0.0)
        self.burn_ascend = env_float(BURN_ASCEND_ENV, 0.5, minimum=0.0)
        if self.burn_ascend >= self.burn_descend:
            logger.warning(
                "%s=%.2f >= %s=%.2f collapses the brownout hysteresis band; "
                "using defaults 0.5/1.0",
                BURN_ASCEND_ENV, self.burn_ascend, BURN_DESCEND_ENV, self.burn_descend,
            )
            self.burn_descend, self.burn_ascend = 1.0, 0.5
        self.waste_grow_pct = env_float(WASTE_ENV, 30.0, minimum=0.1, maximum=99.0)

        self._lock = threading.Lock()
        self.decisions: deque[dict] = deque(maxlen=autopilot_decisions())
        self._last_act: dict[tuple[str, str], float] = {}
        self._act_times: deque[float] = deque()
        self._rung = 0  # the ladder floor this controller currently holds
        self._last_sensors: dict[str, Any] = {}
        self.ticks = 0
        self.actuations = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- stability gate ----------------------------------------------------

    def _may_act(self, loop: str, component: str, now: float) -> bool:
        """Cooldown (per actuator) + global rate limit, both against the
        injected clock. Pure check — :meth:`_record` commits."""
        last = self._last_act.get((loop, component))
        if last is not None and now - last < self.cooldown_s:
            return False
        while self._act_times and now - self._act_times[0] > 60.0:
            self._act_times.popleft()
        return len(self._act_times) < self.rate_per_min

    def _record(
        self, loop: str, component: str, action: str, reason: str,
        sensors: dict, now: float,
    ) -> dict:
        self._last_act[(loop, component)] = now
        self._act_times.append(now)
        decision = {
            "unix_ms": round(time.time() * 1e3, 1),
            "loop": loop,
            "component": component,
            "action": action,
            "reason": reason,
            "sensors": sensors,
        }
        with self._lock:
            self.actuations += 1
            self.decisions.append(decision)
        metrics.count("autopilot_actions")
        metrics.count(f"autopilot_actions:{loop}")
        telemetry.record_event(
            f"autopilot_{loop}", component, f"{action}: {reason}",
            sensors=sensors, action=action,
        )
        return decision

    # -- the control step --------------------------------------------------

    def tick(self) -> list[dict]:
        """One control evaluation across all enabled loops; returns the
        actuations made (possibly empty). Exceptions never escape — a
        controller that can crash its serving process is worse than no
        controller."""
        now = self._clock()
        made: list[dict] = []
        with self._lock:
            self.ticks += 1
        for name, fn in (
            ("scale", self._tick_scale),
            ("brownout", self._tick_brownout),
            ("window", self._tick_window),
        ):
            if not self.loops[name]:
                continue
            try:
                fn(now, made)
            except Exception:  # noqa: BLE001 - the loop must outlive a bad tick
                logger.exception("autopilot %s loop failed this tick", name)
        return made

    # -- loop 1: replica scaling + chip reallocation -----------------------

    def _fleet_readings(self, fleets: list, now: float) -> dict[str, dict]:
        readings: dict[str, dict] = {}
        for fs in fleets:
            duties: list[float] = []
            drain = 0.0
            queued = 0
            rate = forecast = 0.0
            saw_forecast = False
            for r in fs.replicas:
                b = r.batcher
                if r.state != _SERVING or b is None:
                    continue
                d = telemetry.duty_fraction(f"device:{b.name}", self.sense_s)
                if d is not None:
                    duties.append(d)
                # Engine fleets dispatch without a MicroBatcher queue, so
                # there is no drain estimator to read — treat as no
                # backlog rather than requiring the method.
                est_fn = getattr(b, "drain_estimate_s", None)
                est = est_fn() if est_fn is not None else None
                if est is not None:
                    drain = max(drain, est)
                queued += b.load()
                if self.predict:
                    cur = telemetry.window_total(
                        f"batch_items:{b.name}", self.sense_s
                    ) / self.sense_s
                    f = telemetry.forecast_rate(
                        f"batch_items:{b.name}", self.sense_s, self.horizon_s
                    )
                    rate += cur
                    if f is not None:
                        forecast += f
                        saw_forecast = True
            active = sum(1 for r in fs.replicas if r.state == _SERVING)
            parked = sum(1 for r in fs.replicas if r.state == _PARKED)
            readings[fs.name] = {
                "duty": round(sum(duties) / len(duties), 4) if duties else None,
                "drain_s": round(drain, 3),
                "queued": queued,
                "active": active,
                "parked": parked,
                # Chip-holding replicas: everything NOT parked. A DOWN or
                # REVIVING replica never released its mesh slice (only
                # park() frees chips), so it must keep its claim in the
                # ledger — or an unpark during an outage would
                # double-allocate the slice the revive is about to reuse.
                "holding": len(fs.replicas) - parked,
                "chips_per_replica": fs.devices_per_replica,
            }
            if self.predict:
                # Predictive keys exist ONLY with the knob on — the
                # unconfigured sensor dict (and every event built from it)
                # stays byte-identical. projected_duty scales the measured
                # duty by the forecast/current arrival ratio, clamped so a
                # noisy fit can neither zero the signal nor 100x it.
                duty = readings[fs.name]["duty"]
                proj = None
                if saw_forecast and rate > 0 and duty is not None:
                    ratio = max(0.25, min(4.0, forecast / rate))
                    proj = round(min(1.0, duty * ratio), 4)
                readings[fs.name]["rate_rps"] = round(rate, 3)
                readings[fs.name]["forecast_rps"] = (
                    round(forecast, 3) if saw_forecast else None
                )
                readings[fs.name]["projected_duty"] = proj
        return readings

    def _tick_scale(self, now: float, made: list[dict]) -> None:
        fleets = self._fleets()
        readings = self._fleet_readings(fleets, now)
        claimed = sum(
            r["holding"] * r["chips_per_replica"] for r in readings.values()
        )
        if self.chip_capacity is None and fleets:
            # Latch the ledger to the boot-time claim total: from here the
            # controller can only REALLOCATE slices between families —
            # never grow the fleet past what boot placed on the chips.
            self.chip_capacity = claimed
            logger.info(
                "autopilot chip ledger latched at %d slice-chip(s) across "
                "%d fleet(s)", claimed, len(fleets),
            )
        with self._lock:
            self._last_sensors["scale"] = readings
            if self.chip_capacity is not None:
                self._last_sensors["chips"] = {
                    "capacity": self.chip_capacity, "claimed": claimed,
                }
        if not fleets or self.chip_capacity is None:
            return
        free = self.chip_capacity - claimed
        # Scale DOWN first — an idle family releases the slice a hot
        # sibling claims in the SAME tick, so reallocation converges in
        # one controller window instead of two.
        for fs in fleets:
            r = readings[fs.name]
            duty = r["duty"]
            if duty is None:  # no sensor -> no actuation
                continue
            # Predictive gate: act on the WORSE of measured and projected
            # duty. A rising trend blocks the park (the chips are about to
            # be needed) and trips the unpark early; a falling trend never
            # parks ahead of the measurement — scale-down stays reactive,
            # so a forecast can cost capacity margin only upward.
            eff = duty
            proj = r.get("projected_duty")
            if proj is not None:
                eff = max(duty, proj)
            if eff >= self.scale_down_duty or r["drain_s"] > self.tick_s:
                continue
            if r["active"] <= 1 or not self._may_act("scale", fs.name, now):
                continue
            rid = fs.park()
            if rid is None:
                continue
            free += fs.devices_per_replica
            made.append(self._record(
                "scale", fs.name, f"park r{rid}",
                f"duty {duty:.2f} < {self.scale_down_duty:.2f} and no "
                f"backlog: released {fs.devices_per_replica} chip(s)",
                {**r, "free_chips": free}, now,
            ))
        # Scale UP, hottest first, gated by the ledger.
        hot = sorted(
            (fs for fs in fleets if readings[fs.name]["duty"] is not None),
            key=lambda fs: readings[fs.name]["duty"],
            reverse=True,
        )
        for fs in hot:
            r = readings[fs.name]
            eff = r["duty"]
            proj = r.get("projected_duty")
            if proj is not None:
                eff = max(eff, proj)
            pressured = (
                eff > self.scale_up_duty
                or r["drain_s"] > 2.0 * self.tick_s
            )
            if not pressured or r["parked"] <= 0:
                continue
            if free < fs.devices_per_replica:
                continue  # ledger empty: no sibling has released a slice
            if not self._may_act("scale", fs.name, now):
                continue
            rid = fs.unpark()
            if rid is None:
                continue
            free -= fs.devices_per_replica
            made.append(self._record(
                "scale", fs.name, f"unpark r{rid}",
                f"duty {r['duty']:.2f} / drain {r['drain_s']:.2f}s over "
                f"threshold: claimed {fs.devices_per_replica} free chip(s)",
                {**r, "free_chips": free}, now,
            ))

    # -- loop 2: SLO-burn-driven brownout ----------------------------------

    def _tick_brownout(self, now: float, made: list[dict]) -> None:
        slo = telemetry.slo_status()
        burn5 = burn1h = None
        worst = None
        for task, rec in slo.items():
            b5 = rec.get("burn_5m", 0.0)
            if burn5 is None or b5 > burn5:
                burn5, worst = b5, task
                burn1h = rec.get("burn_1h", 0.0)
        with self._lock:
            self._last_sensors["brownout"] = {
                "burn_5m": burn5, "burn_1h": burn1h, "task": worst,
                "rung": self._rung,
            }
        if burn5 is None:
            if self._rung > 0:
                # Objectives went away mid-hold (env reset): still keep
                # newly-built queues on the held floor until it releases.
                self._apply_rung()
            return  # no SLO objectives (or no traffic): nothing to steer by
        sensors = {
            "burn_5m": burn5, "burn_1h": burn1h, "task": worst,
            "rung": self._rung,
        }
        if (
            burn5 > self.burn_descend and self._rung < 2
            and self._may_act("brownout", "ladder", now)
        ):
            self._rung += 1
            made.append(self._record(
                "brownout", "ladder", f"descend to rung {self._rung}",
                f"{worst} burn_5m {burn5:.2f} > {self.burn_descend:.2f}: "
                "error budget burning faster than sustainable",
                sensors, now,
            ))
        elif (
            burn5 <= self.burn_ascend and self._rung > 0
            and self._may_act("brownout", "ladder", now)
        ):
            self._rung -= 1
            made.append(self._record(
                "brownout", "ladder", f"ascend to rung {self._rung}",
                f"burn_5m {burn5:.2f} <= {self.burn_ascend:.2f}: budget "
                "recovered",
                sensors, now,
            ))
        # Re-assert the (possibly just-changed) floor EVERY tick —
        # including ticks where cooldown/rate-limit blocked a transition —
        # so queues built since the last tick (a revive or unpark builds a
        # fresh batcher+queue) inherit the held rung within one tick.
        self._apply_rung()

    def _apply_rung(self) -> None:
        rung = self._rung
        for q in self._queues():
            try:
                q.force_rung(rung if rung > 0 else None)
            except Exception:  # noqa: BLE001 - one bad queue must not stop the rest
                logger.exception("autopilot: force_rung failed on %s", getattr(q, "name", q))

    # -- loop 3: batch-window auto-tune ------------------------------------

    def _tick_window(self, now: float, made: list[dict]) -> None:
        waste_view: dict[str, dict] = {}
        for b in self._batchers():
            base = getattr(b, "base_window_cap_s", 0.0)
            if base <= 0:
                continue  # nothing to tune: the window is pinned at 0
            if getattr(b, "adaptive", True) is False:
                # A fixed-window batcher (LUMEN_BATCH_ADAPTIVE=0) never
                # reads window_cap_s: actuating it would burn rate-limit
                # budget on recorded no-ops.
                continue
            items = telemetry.window_total(f"batch_items:{b.name}", self.sense_s)
            padded = telemetry.window_total(f"batch_padded:{b.name}", self.sense_s)
            slots = items + padded
            if slots < MIN_WINDOW_SLOTS:
                continue  # too little traffic for the reading to mean anything
            waste = 100.0 * padded / slots
            cap = b.window_cap_s
            waste_view[b.name] = {
                "waste_pct": round(waste, 1),
                "cap_ms": round(cap * 1e3, 2),
                "base_ms": round(base * 1e3, 2),
            }
            sensors = {
                **waste_view[b.name],
                "items": int(items), "padded": int(padded),
            }
            if waste > self.waste_grow_pct and cap < base * 4:
                if not self._may_act("window", b.name, now):
                    continue
                new = b.set_window_cap_s(min(base * 4, max(cap, base) * 1.5))
                made.append(self._record(
                    "window", b.name,
                    f"grow cap {cap * 1e3:.1f} -> {new * 1e3:.1f}ms",
                    f"padding waste {waste:.1f}% > {self.waste_grow_pct:.0f}%: "
                    "wait longer to fill fuller batches",
                    sensors, now,
                ))
            elif waste < self.waste_grow_pct / 4 and cap > base:
                if not self._may_act("window", b.name, now):
                    continue
                new = b.set_window_cap_s(max(base, cap / 1.5))
                made.append(self._record(
                    "window", b.name,
                    f"shrink cap {cap * 1e3:.1f} -> {new * 1e3:.1f}ms",
                    f"padding waste {waste:.1f}% cleared: give the latency "
                    "back",
                    sensors, now,
                ))
        with self._lock:
            self._last_sensors["window"] = waste_view

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Autopilot":
        """Run ``tick()`` on a daemon thread every ``tick_s`` seconds."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="autopilot", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(timeout=self.tick_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - belt over tick()'s own braces
                logger.exception("autopilot tick failed")

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        # Return the ladder to occupancy control: a stopped controller
        # must not leave a forced brownout floor behind.
        if self._rung != 0:
            self._rung = 0
            self._apply_rung()

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # -- export ------------------------------------------------------------

    def status(self) -> dict:
        """The ``GET /autopilot`` body: policy/knob state, per-loop enable
        flags + latest sensor readings, the chip ledger, and the decision
        ring (newest last)."""
        with self._lock:
            decisions = list(self.decisions)
            sensors = dict(self._last_sensors)
            ticks, acts = self.ticks, self.actuations
        scale_loop: dict[str, Any] = {
            "enabled": self.loops["scale"],
            "up_duty": self.scale_up_duty,
            "down_duty": self.scale_down_duty,
            "families": sensors.get("scale", {}),
        }
        if self.predict:
            # Predictive keys only when armed — the unconfigured body
            # stays byte-identical.
            scale_loop["predict"] = True
            scale_loop["horizon_s"] = self.horizon_s
        return {
            "enabled": True,
            "running": self.running,
            "tick_s": self.tick_s,
            "cooldown_s": self.cooldown_s,
            "sense_window_s": self.sense_s,
            "rate_limit_per_min": self.rate_per_min,
            "ticks": ticks,
            "actuations": acts,
            "chips": sensors.get("chips", {"capacity": self.chip_capacity}),
            "loops": {
                "scale": scale_loop,
                "brownout": {
                    "enabled": self.loops["brownout"],
                    "rung": self._rung,
                    "burn_descend": self.burn_descend,
                    "burn_ascend": self.burn_ascend,
                    "sensors": sensors.get("brownout", {}),
                },
                "window": {
                    "enabled": self.loops["window"],
                    "waste_grow_pct": self.waste_grow_pct,
                    "batchers": sensors.get("window", {}),
                },
            },
            "decisions": decisions,
        }

    def health_summary(self) -> dict:
        """Compact state for the ``lumen-autopilot-status`` Health key."""
        with self._lock:
            last = self.decisions[-1] if self.decisions else None
            acts = self.actuations
        out: dict[str, Any] = {
            "running": self.running,
            "loops": {k: ("on" if v else "off") for k, v in self.loops.items()},
            "rung": self._rung,
            "actuations": acts,
        }
        if last is not None:
            out["last"] = {
                "loop": last["loop"], "component": last["component"],
                "action": last["action"],
            }
        return out


# -- default registry sources (lazy: never imported at module import) ---------


def _default_fleets() -> list:
    from .fleet import live_fleets

    return live_fleets()


def _default_batchers() -> list:
    from .batcher import live_batchers

    return live_batchers()


def _default_queues() -> list:
    from ..utils.qos import live_queues

    return live_queues()


# -- process-wide instance ----------------------------------------------------

_autopilot: Autopilot | None = None
_autopilot_lock = threading.Lock()
_boot_logged = False


def get_autopilot() -> Autopilot | None:
    return _autopilot


def install_autopilot(ap: Autopilot | None) -> Autopilot | None:
    """Swap the process autopilot (tests); returns the previous one."""
    global _autopilot
    with _autopilot_lock:
        old, _autopilot = _autopilot, ap
    return old


def reset_autopilot() -> None:
    """Stop and drop the shared controller (tests / re-boot)."""
    global _boot_logged
    old = install_autopilot(None)
    _boot_logged = False
    if old is not None:
        old.stop()


def maybe_start_autopilot() -> Autopilot | None:
    """Server-boot hook: build+start the controller when
    ``LUMEN_AUTOPILOT=1``, else log the off state once and do nothing.
    Either way exactly one boot-log line says whether the fleet is
    self-driving — a deploy-time fact an operator should not probe for."""
    global _boot_logged
    if not autopilot_enabled():
        if not _boot_logged:
            _boot_logged = True
            logger.info(
                "autopilot off (set LUMEN_AUTOPILOT=1 for closed-loop "
                "scaling/brownout/window control)"
            )
        return None
    ap = Autopilot()
    install_autopilot(ap)
    ap.start()
    if not _boot_logged:
        _boot_logged = True
        logger.info(
            "autopilot ON (tick=%.1fs cooldown=%.0fs sense=%.0fs "
            "rate<=%d/min; loops: %s%s)",
            ap.tick_s, ap.cooldown_s, ap.sense_s, ap.rate_per_min,
            ",".join(k for k, v in ap.loops.items() if v) or "none",
            f"; predictive horizon={ap.horizon_s:.0f}s" if ap.predict else "",
        )
    return ap


def export_status() -> dict:
    """The ``GET /autopilot`` body regardless of state — an off autopilot
    still answers (enabled flag + empty ring), so probes need no 404
    handling."""
    ap = _autopilot
    if ap is None:
        return {
            "enabled": autopilot_enabled(),
            "running": False,
            "loops": {},
            "decisions": [],
        }
    return ap.status()


def health_status() -> dict:
    """Body of the ``lumen-autopilot-status`` Health trailing-metadata key
    (``{}`` when no controller is installed — the key is then omitted, the
    same contract as the qos/slo keys)."""
    ap = _autopilot
    if ap is None:
        return {}
    return ap.health_summary()
