"""Device-resident exact-ANN index over L2-normalized embeddings.

The product surface ROADMAP item 3 names: the CLIP embeddings the photo
pipeline already produces become queryable — "search my library" — by
brute-force cosine scoring on the chip. Brute force is the right call at
this scale: one fused ``scores = q @ buf.T`` + ``jax.lax.top_k`` over a
100k x 512 f32 shard is a fraction of a millisecond of MXU time, recall
is exactly 1.0 by construction (the bench asserts it against a numpy
oracle), and there is no graph/tree structure to rebuild on upsert.

Static-shape discipline (the same contract as every other device
structure in this repo):

- vectors live in a fixed-capacity ``(capacity, dim)`` f32 device buffer;
  growth DOUBLES the capacity (``LUMEN_ANN_MIN_CAPACITY`` floor), so XLA
  compiles one program per capacity bucket, never per upsert;
- upserts land via one jitted scatter per (capacity, write-bucket) pair —
  write batches pad to power-of-two buckets by repeating the last
  (row, index) pair, which is idempotent;
- queries run one jitted matmul + ``lax.top_k`` per (capacity, Q-bucket,
  k-bucket); shards past the VMEM-friendly tile (``LUMEN_ANN_TILE`` rows)
  score tile-by-tile under ``lax.map`` and merge the per-tile top-k, so
  the scratch footprint stays one tile no matter how big the shard grows.

Concurrency contract (the upsert-during-query guarantee): jax arrays are
immutable, so a write builds a NEW buffer and the shard commits
``(buffer, count, ids)`` as one atomic snapshot under its lock only
after the device write has been dispatched. A query snapshots the triple
once; it either sees the index entirely before or entirely after any
upsert — never a torn state — and row ids are append-only, so resolving
indices against a LATER ids list is always safe for committed rows.

jax is imported lazily (module level would break the jax-free serving
imports this package keeps deliberately light).
"""

from __future__ import annotations

import heapq
import logging
import threading
from typing import Any, Sequence

import numpy as np

from ..utils.env import env_int
from ..utils.metrics import metrics

logger = logging.getLogger(__name__)

#: rows per ``lax.map`` scoring tile. 8192 x 512 f32 is 16MB of operand —
#: it streams through VMEM comfortably; buffers at or under one tile
#: score in a single fused matmul with no map overhead.
TILE_ENV = "LUMEN_ANN_TILE"
#: smallest device buffer allocated per shard (doubling growth above it).
MIN_CAP_ENV = "LUMEN_ANN_MIN_CAPACITY"
#: hard per-shard row cap — an upsert past it is refused with a clear
#: error instead of growing until HBM dies under someone's feet.
MAX_VECTORS_ENV = "LUMEN_ANN_MAX_VECTORS"
#: logical shards per tenant: the federation front fans a query out to
#: the ring owners of ``ann/<tenant>/<shard>`` keys and merges the heaps.
SHARDS_ENV = "LUMEN_ANN_SHARDS"
#: ceiling on a single query's k (results per shard before the merge).
K_CAP_ENV = "LUMEN_ANN_K_CAP"


def ann_tile() -> int:
    return env_int(TILE_ENV, 8192, minimum=128)


def ann_min_capacity() -> int:
    return env_int(MIN_CAP_ENV, 1024, minimum=8)


def ann_max_vectors() -> int:
    return env_int(MAX_VECTORS_ENV, 1_000_000, minimum=1)


def ann_shards() -> int:
    return env_int(SHARDS_ENV, 3, minimum=1)


def ann_k_cap() -> int:
    return env_int(K_CAP_ENV, 128, minimum=1)


def _pow2_at_least(n: int, floor: int = 1) -> int:
    out = max(1, floor)
    while out < n:
        out *= 2
    return out


def normalize(vecs: np.ndarray) -> np.ndarray:
    """L2-normalize rows (host-side, float32). Zero vectors stay zero
    instead of dividing into NaNs — they simply never score above any
    real match."""
    vecs = np.asarray(vecs, dtype=np.float32)
    if vecs.ndim == 1:
        vecs = vecs[None, :]
    norms = np.linalg.norm(vecs, axis=-1, keepdims=True)
    return vecs / np.maximum(norms, 1e-12)


def shard_of(vec_id: str, shards: int) -> int:
    """Stable shard assignment for one vector id — the SAME function on
    the front tier (which partitions upsert batches) and on a single host
    (which partitions locally), so a library indexed standalone reshards
    identically when a fleet grows around it."""
    import hashlib

    if shards <= 1:
        return 0
    digest = hashlib.sha256(vec_id.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") % shards


def merge_topk(
    parts: Sequence[tuple[Sequence[str], Sequence[float]]], k: int
) -> tuple[list[str], list[float]]:
    """Merge per-shard ``(ids, scores)`` top-k lists into one global
    top-k. Deterministic tie-break — score descending, then id ascending
    — so a sharded merge is bit-reproducible and comparable against a
    sorted oracle. Tolerates empty shards and k larger than any shard's
    contribution (the hypothesis property test exercises both)."""
    heap: list[tuple[float, str]] = []
    for ids, scores in parts:
        for vid, score in zip(ids, scores):
            item = (float(score), str(vid))
            if len(heap) < k:
                heapq.heappush(heap, item)
            elif item > heap[0]:
                heapq.heapreplace(heap, item)
    ordered = sorted(heap, key=lambda t: (-t[0], t[1]))
    return [vid for _, vid in ordered], [score for score, _ in ordered]


class AnnShard:
    """One tenant-shard's device buffer + id table. Thread-safe."""

    def __init__(self, dim: int, name: str = "ann"):
        if dim < 1:
            raise ValueError(f"embedding dim must be >= 1, got {dim}")
        self.dim = int(dim)
        self.name = name
        self._lock = threading.Lock()
        # Committed snapshot: queries read (buffer, count) under the lock
        # and compute outside it. ids is APPEND-ONLY (updates rewrite the
        # row in place under the same id), so index -> id resolution after
        # the device call needs no snapshot of its own.
        self._buf = None  # lazy: allocated on first upsert
        self._n = 0
        self._ids: list[str] = []
        self._row: dict[str, int] = {}
        self._capacity = 0

    # -- internals --------------------------------------------------------

    def _grow_to(self, need: int) -> None:
        """Ensure capacity >= need (doubling; caller holds the lock)."""
        import jax.numpy as jnp

        cap = self._capacity or ann_min_capacity()
        cap = _pow2_at_least(need, floor=max(cap, ann_min_capacity()))
        if cap == self._capacity:
            return
        new = jnp.zeros((cap, self.dim), dtype=jnp.float32)
        if self._buf is not None and self._n:
            new = new.at[: self._capacity].set(self._buf)
        self._buf = new
        self._capacity = cap
        metrics.count("ann_grows")

    # -- public API -------------------------------------------------------

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._capacity

    def upsert(self, ids: Sequence[str], vecs: np.ndarray) -> tuple[int, int]:
        """Insert-or-replace ``vecs[i]`` under ``ids[i]``. Returns
        ``(added, updated)``. Vectors are L2-normalized here so scoring
        is cosine similarity regardless of what the caller sends."""
        import jax.numpy as jnp

        vecs = normalize(vecs)
        if len(ids) != vecs.shape[0]:
            raise ValueError(
                f"{len(ids)} ids but {vecs.shape[0]} vectors"
            )
        if vecs.shape[1] != self.dim:
            raise ValueError(
                f"vector dim {vecs.shape[1]} != index dim {self.dim}"
            )
        if not len(ids):
            return 0, 0
        # Last-write-wins WITHIN the batch too: a duplicated id writes its
        # final vector once instead of burning two scatter rows.
        dedup: dict[str, np.ndarray] = {}
        for vid, vec in zip(ids, vecs):
            dedup[str(vid)] = vec
        with self._lock:
            added = sum(1 for vid in dedup if vid not in self._row)
            updated = len(dedup) - added
            new_n = self._n + added
            if new_n > ann_max_vectors():
                raise ValueError(
                    f"shard {self.name!r} would hold {new_n} vectors, over "
                    f"the {MAX_VECTORS_ENV}={ann_max_vectors()} cap"
                )
            self._grow_to(new_n)
            idx_list: list[int] = []
            next_row = self._n
            for vid in dedup:
                row = self._row.get(vid)
                if row is None:
                    row = next_row
                    next_row += 1
                idx_list.append(row)
            rows = np.stack(list(dedup.values()))
            # Pad to a power-of-two bucket by REPEATING the last real
            # (index, row) pair — an idempotent rewrite, so each
            # (capacity, bucket) pair compiles exactly once.
            bucket = _pow2_at_least(len(idx_list))
            pad = bucket - len(idx_list)
            if pad:
                idx_arr = np.concatenate(
                    [idx_list, np.full(pad, idx_list[-1], np.int32)]
                ).astype(np.int32)
                rows = np.concatenate([rows, np.repeat(rows[-1:], pad, 0)])
            else:
                idx_arr = np.asarray(idx_list, np.int32)
            new_buf = _scatter_write(
                self._buf, jnp.asarray(idx_arr), jnp.asarray(rows)
            )
            # COMMIT: publish buffer, ids and count together. A query that
            # snapshotted before this line sees none of this batch; one
            # after sees all of it.
            self._buf = new_buf
            for vid in dedup:
                if vid not in self._row:
                    self._row[vid] = len(self._ids)
                    self._ids.append(vid)
            self._n = len(self._ids)
        metrics.count("ann_upserts", len(dedup))
        if updated:
            metrics.count("ann_updates", updated)
        return added, updated

    def snapshot(self):
        """Atomic ``(buffer, committed_count)`` view for a device query."""
        with self._lock:
            return self._buf, self._n

    def resolve(self, indices: Sequence[int]) -> list[str]:
        """Row indices -> vector ids. Safe without the query's snapshot:
        ids are append-only and the indices came from a masked top_k, so
        every index was committed when the query launched."""
        ids = self._ids  # list reference; rows < committed n never mutate
        return [ids[i] for i in indices]

    def query(self, q: np.ndarray, k: int) -> tuple[list[str], list[float]]:
        """Exact top-k over the committed rows for one or more query
        vectors. ``q`` is ``(dim,)`` or ``(Q, dim)``; returns the merged
        ids/scores for the FIRST query row when 1-D (the common case) —
        multi-row callers use :meth:`query_many`."""
        ids, scores = self.query_many(np.atleast_2d(np.asarray(q)), k)
        return ids[0], scores[0]

    def query_raw(self, q: np.ndarray, k: int):
        """Batched scoring core: ``(B, dim)`` raw query vectors -> device
        arrays ``(scores (B, k'), row_indices (B, k'))`` with ``k' =
        min(k, k_cap, committed_n)``. DISPATCHES without fetching — this
        is the MicroBatcher ``fn`` body (the batcher's fetch worker does
        the one blocking transfer per batch), so queries coalesced into
        one device call overlap the next batch's collection. Resolve the
        indices later via :meth:`resolve` (safe: append-only id table)."""
        q = normalize(q)
        if q.shape[1] != self.dim:
            raise ValueError(f"query dim {q.shape[1]} != index dim {self.dim}")
        buf, n = self.snapshot()
        metrics.count("ann_queries", q.shape[0])
        if buf is None or n == 0:
            return (
                np.zeros((q.shape[0], 0), np.float32),
                np.zeros((q.shape[0], 0), np.int32),
            )
        k_eff = min(max(1, int(k)), ann_k_cap(), n)
        # Static-shape k bucket (power of two, lazily sliced back): the
        # jit cache holds one program per (capacity, B, k-bucket) triple.
        k_bucket = min(_pow2_at_least(k_eff), self._cap_for_topk(buf.shape[0]))
        scores_d, idx_d = _topk_scores(buf, q, n, k_bucket, ann_tile())
        return scores_d[:, :k_eff], idx_d[:, :k_eff]

    def query_many(
        self, q: np.ndarray, k: int
    ) -> tuple[list[list[str]], list[list[float]]]:
        import jax

        q = np.atleast_2d(np.asarray(q, dtype=np.float32))
        n_queries = q.shape[0]
        # Pad B to a power-of-two bucket so direct (non-batcher) callers
        # hit the same compiled programs the batcher's buckets do.
        q_bucket = _pow2_at_least(n_queries)
        if q_bucket != n_queries and q.shape[1] == self.dim:
            q = np.concatenate(
                [q, np.zeros((q_bucket - n_queries, q.shape[1]), np.float32)]
            )
        scores_d, idx_d = self.query_raw(q, k)
        scores_np = np.asarray(jax.device_get(scores_d))
        idx_np = np.asarray(jax.device_get(idx_d))
        return self.resolve_rows(scores_np[:n_queries], idx_np[:n_queries])

    def resolve_rows(
        self, scores: np.ndarray, indices: np.ndarray
    ) -> tuple[list[list[str]], list[list[float]]]:
        """Fetched ``query_raw`` rows -> per-query ``(ids, scores)`` lists,
        dropping -inf padding (masked rows that leaked past a small n)."""
        out_ids: list[list[str]] = []
        out_scores: list[list[float]] = []
        for raw_sc, raw_idx in zip(np.atleast_2d(scores), np.atleast_2d(indices)):
            keep = raw_sc > -np.inf
            out_ids.append(self.resolve([int(i) for i in raw_idx[keep]]))
            out_scores.append([float(s) for s in raw_sc[keep]])
        return out_ids, out_scores

    @staticmethod
    def _cap_for_topk(capacity: int) -> int:
        """top_k's k cannot exceed the scored width (the tile width when
        mapping, the capacity otherwise)."""
        return max(1, min(capacity, ann_tile()))

    def gauges(self) -> dict:
        with self._lock:
            return {
                "vectors": self._n,
                "capacity": self._capacity,
                "dim": self.dim,
            }


def _topk_scores(buf, q, n: int, k: int, tile: int):
    """Dispatch the jitted scoring program: one fused matmul + top_k when
    the buffer fits a tile, else tile-by-tile under ``lax.map`` with a
    final merge. Returns device arrays ``(scores (Q,k), indices (Q,k))``
    — the caller fetches."""
    import jax
    import jax.numpy as jnp

    capacity = buf.shape[0]
    if capacity <= tile or capacity % tile:
        # Fits one tile — or a hand-set odd tile doesn't divide the
        # power-of-two capacity: fall back to the single fused program
        # (correct, bigger scratch) rather than a ragged map.
        return _topk_single_jit(buf, q, jnp.asarray(n, jnp.int32), k)
    return _topk_tiled(buf, q, jnp.asarray(n, jnp.int32), k, tile)


def _get_single_jit():
    import jax
    import jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnums=(3,))
    def run(buf, q, n, k):
        scores = q @ buf.T  # (Q, capacity) — one MXU call
        mask = jnp.arange(buf.shape[0]) < n
        scores = jnp.where(mask[None, :], scores, -jnp.inf)
        return jax.lax.top_k(scores, k)

    return run


def _get_tiled_jit():
    import jax
    import jax.numpy as jnp
    from functools import partial

    @partial(jax.jit, static_argnums=(3, 4))
    def run(buf, q, n, k, tile):
        tiles = buf.shape[0] // tile
        tiled = buf.reshape(tiles, tile, buf.shape[1])

        def score_tile(args):
            t_idx, t_buf = args
            scores = q @ t_buf.T  # (Q, tile)
            base = t_idx * tile
            mask = (base + jnp.arange(tile)) < n
            scores = jnp.where(mask[None, :], scores, -jnp.inf)
            s, i = jax.lax.top_k(scores, k)
            return s, i + base

        # lax.map: one tile of scratch live at a time — the VMEM story.
        s_all, i_all = jax.lax.map(
            score_tile, (jnp.arange(tiles), tiled)
        )  # (tiles, Q, k) each
        qn = q.shape[0]
        s_flat = jnp.transpose(s_all, (1, 0, 2)).reshape(qn, tiles * k)
        i_flat = jnp.transpose(i_all, (1, 0, 2)).reshape(qn, tiles * k)
        s_top, pos = jax.lax.top_k(s_flat, k)
        i_top = jnp.take_along_axis(i_flat, pos, axis=1)
        return s_top, i_top

    return run


_SINGLE_JIT = None
_TILED_JIT = None
_WRITE_JIT = None
_JIT_LOCK = threading.Lock()


def _scatter_write(buf, idx, rows):
    """One module-level jitted scatter — jax's jit cache keys on the
    (capacity, write-bucket) shapes, so each pair compiles exactly once
    process-wide."""
    global _WRITE_JIT
    if _WRITE_JIT is None:
        with _JIT_LOCK:
            if _WRITE_JIT is None:
                import jax

                _WRITE_JIT = jax.jit(lambda b, i, r: b.at[i].set(r))
    return _WRITE_JIT(buf, idx, rows)


def _topk_single_jit(buf, q, n, k):
    global _SINGLE_JIT
    if _SINGLE_JIT is None:
        with _JIT_LOCK:
            if _SINGLE_JIT is None:
                _SINGLE_JIT = _get_single_jit()
    return _SINGLE_JIT(buf, q, n, k)


def _topk_tiled(buf, q, n, k, tile):
    global _TILED_JIT
    if _TILED_JIT is None:
        with _JIT_LOCK:
            if _TILED_JIT is None:
                _TILED_JIT = _get_tiled_jit()
    return _TILED_JIT(buf, q, n, k, tile)


class AnnIndex:
    """Per-tenant, per-shard index map for one host. Shards materialize
    lazily on first upsert; gauges register per (tenant, shard) so
    ``/metrics`` shows which tenants hold rows where."""

    def __init__(self, dim: int):
        self.dim = int(dim)
        self._lock = threading.Lock()
        self._shards: dict[tuple[str, str], AnnShard] = {}

    def shard(self, tenant: str, shard: str, create: bool = True) -> AnnShard | None:
        key = (tenant or "default", str(shard))
        with self._lock:
            got = self._shards.get(key)
            if got is None and create:
                got = AnnShard(self.dim, name=f"{key[0]}/{key[1]}")
                self._shards[key] = got
                import weakref

                ref = weakref.ref(got)
                metrics.register_gauges(
                    f"ann:{key[0]}:{key[1]}",
                    lambda r=ref: (s.gauges() if (s := r()) is not None else {}),
                )
            return got

    def shards_for(self, tenant: str) -> dict[str, AnnShard]:
        tenant = tenant or "default"
        with self._lock:
            return {
                sh: shard
                for (t, sh), shard in self._shards.items()
                if t == tenant
            }

    def upsert(
        self, tenant: str, ids: Sequence[str], vecs: np.ndarray,
        shard: str | None = None,
    ) -> tuple[int, int]:
        """Upsert a batch. With an explicit ``shard`` label (the
        fleet-routed path) everything lands there; without one (direct
        single-host use) rows partition by :func:`shard_of` so a later
        fleet sees the same placement function."""
        vecs = np.atleast_2d(np.asarray(vecs))
        if shard is not None:
            return self.shard(tenant, shard).upsert(ids, vecs)
        n_shards = ann_shards()
        added = updated = 0
        groups: dict[int, list[int]] = {}
        for i, vid in enumerate(ids):
            groups.setdefault(shard_of(str(vid), n_shards), []).append(i)
        for sh, rows in sorted(groups.items()):
            a, u = self.shard(tenant, str(sh)).upsert(
                [str(ids[i]) for i in rows], vecs[rows]
            )
            added += a
            updated += u
        return added, updated

    def query(
        self, tenant: str, q: np.ndarray, k: int,
        shards: Sequence[str] | None = None,
    ) -> tuple[list[str], list[float], int]:
        """Top-k over the named shards (fleet hop) or every local shard of
        the tenant (direct use). Returns ``(ids, scores, shards_read)``."""
        if shards is None:
            local = self.shards_for(tenant)
        else:
            local = {
                sh: s
                for sh in shards
                if (s := self.shard(tenant, sh, create=False)) is not None
            }
        parts = [s.query(q, k) for s in local.values()]
        ids, scores = merge_topk(parts, k)
        return ids, scores, len(local)

    def stats(self) -> dict:
        with self._lock:
            return {
                f"{t}/{sh}": shard.gauges()
                for (t, sh), shard in sorted(self._shards.items())
            }


def exact_oracle(
    ids: Sequence[str], vecs: np.ndarray, q: np.ndarray, k: int
) -> tuple[list[str], list[float]]:
    """Numpy reference: full cosine scoring + the same deterministic
    tie-break as :func:`merge_topk`. The recall@k arbiter for tests and
    the bench phase."""
    vecs = normalize(vecs)
    q = normalize(q)[0]
    scores = vecs @ q
    order = sorted(range(len(ids)), key=lambda i: (-float(scores[i]), str(ids[i])))
    top = order[: min(k, len(order))]
    return [str(ids[i]) for i in top], [float(scores[i]) for i in top]
