"""TPU runtime core: device mesh, dtype policy, weight loading, batching.

The execution layer that replaces the reference's ONNX-Runtime/libtorch
backends (`SURVEY.md` §2 "native compute" note).
"""

from .compile_cache import enable_persistent_cache
from .batcher import MicroBatcher, bucket_for, default_buckets, live_batchers
from .decode_pool import DecodePool, get_decode_pool, shutdown_decode_pool
from .fleet import (
    FleetPlan,
    ReplicaSet,
    build_fleet,
    each_batcher,
    live_fleets,
    plan_replicas,
    register_policy,
    replicas_for,
)
from .quarantine import QuarantineRegistry, get_quarantine, reset_quarantine
from .result_cache import ResultCache, get_result_cache, reset_result_cache
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    build_mesh,
    data_sharding,
    local_batch_multiple,
    replicated,
    resolve_axes,
)
from .policy import Policy, get_policy
from .weights import (
    WeightLoadError,
    apply_rules,
    assert_tree_shapes,
    conv_kernel,
    flatten,
    linear_kernel,
    load_state_dict,
    unflatten,
)

__all__ = [
    "enable_persistent_cache",
    "MicroBatcher",
    "bucket_for",
    "default_buckets",
    "live_batchers",
    "live_fleets",
    "DecodePool",
    "get_decode_pool",
    "shutdown_decode_pool",
    "FleetPlan",
    "ReplicaSet",
    "build_fleet",
    "each_batcher",
    "plan_replicas",
    "register_policy",
    "replicas_for",
    "QuarantineRegistry",
    "get_quarantine",
    "reset_quarantine",
    "ResultCache",
    "get_result_cache",
    "reset_result_cache",
    "build_mesh",
    "resolve_axes",
    "data_sharding",
    "replicated",
    "local_batch_multiple",
    "DATA_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "Policy",
    "get_policy",
    "WeightLoadError",
    "load_state_dict",
    "apply_rules",
    "unflatten",
    "flatten",
    "linear_kernel",
    "conv_kernel",
    "assert_tree_shapes",
]
