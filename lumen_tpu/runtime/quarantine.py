"""TTL'd quarantine registry for poison-input fingerprints.

Batch bisection (:mod:`lumen_tpu.runtime.batcher`) isolates the input that
made a batch fail; this module remembers it. The first failure costs a
bisection pass (bounded sub-batch re-dispatches); every repeat of the same
payload is rejected *up front* — before admission control, the decode
pool, and the device — so a client (or a library re-index) hammering one
broken photo costs the hub a dict lookup, not a device batch.

Keying reuses the result cache's content addressing
(:func:`~lumen_tpu.runtime.result_cache.make_key`:
``{namespace}:{sha256(namespace, canonical options, payload)}``): the same
bytes under the same model/options that failed before are exactly the
bytes that will fail again, while the namespace half keeps one model's
poison from tainting another's. Entries expire after
``LUMEN_QUARANTINE_TTL_S`` (a hot-swapped or upgraded model deserves a
fresh verdict) and the registry is LRU-capped at ``LUMEN_QUARANTINE_MAX``
so an adversarial stream of unique poison cannot grow it without bound.

Rejections raise :class:`~lumen_tpu.utils.deadline.PoisonInput` and mark
the request-note scope (``quarantined``) so the gRPC layer surfaces the
verdict in trailing metadata.

Deliberately jax-free (like :mod:`~lumen_tpu.runtime.result_cache`): pure
host bookkeeping, usable from the serving layer without a backend.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import OrderedDict

from ..utils.deadline import PoisonInput
from ..utils.env import env_float, env_int
from ..utils.metrics import metrics
from ..utils.request_notes import mark as _mark

logger = logging.getLogger(__name__)

QUARANTINE_TTL_ENV = "LUMEN_QUARANTINE_TTL_S"
QUARANTINE_MAX_ENV = "LUMEN_QUARANTINE_MAX"

DEFAULT_TTL_S = 300.0
DEFAULT_MAX_ENTRIES = 4096


def quarantine_ttl_s() -> float:
    """``LUMEN_QUARANTINE_TTL_S``: seconds an isolated fingerprint stays
    rejected (0 disables quarantine entirely; unset/malformed -> 300)."""
    return env_float(QUARANTINE_TTL_ENV, DEFAULT_TTL_S, minimum=0.0)


def quarantine_max_entries() -> int:
    """``LUMEN_QUARANTINE_MAX``: LRU cap on tracked fingerprints
    (unset/malformed -> 4096; floor 1)."""
    return env_int(QUARANTINE_MAX_ENV, DEFAULT_MAX_ENTRIES, minimum=1)


class _Entry:
    __slots__ = ("expires_at", "reason", "rejections")

    def __init__(self, expires_at: float, reason: str):
        self.expires_at = expires_at
        self.reason = reason
        self.rejections = 0


class QuarantineRegistry:
    """Thread-safe fingerprint -> (expiry, reason) map with LRU eviction.

    ``add`` is called by whatever *proved* an input poison (batch
    bisection, per-item ingest salvage); ``check`` is the hot-path guard
    the managers and the batcher call before spending any work on a
    payload."""

    def __init__(
        self,
        ttl_s: float | None = None,
        max_entries: int | None = None,
        name: str = "quarantine",
    ):
        self.ttl_s = quarantine_ttl_s() if ttl_s is None else max(0.0, ttl_s)
        self.max_entries = (
            quarantine_max_entries() if max_entries is None else max(1, max_entries)
        )
        self.name = name
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, _Entry] = OrderedDict()
        self.stats = {"quarantined": 0, "rejections": 0, "expired": 0, "evicted": 0}
        ref = weakref.ref(self)

        def _gauges() -> dict:
            q = ref()
            if q is None:
                return {}
            with q._lock:
                return {**q.stats, "entries": len(q._entries)}

        self._gauge_fn = _gauges
        metrics.register_gauges(name, _gauges)

    @property
    def enabled(self) -> bool:
        return self.ttl_s > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- registration ------------------------------------------------------

    def add(self, key: str, reason: str) -> bool:
        """Quarantine ``key`` for ``ttl_s`` seconds. Returns False when
        quarantine is disabled. Re-adding refreshes the TTL (the input
        just proved itself poison again)."""
        if not self.enabled or not key:
            return False
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                entry = _Entry(0.0, reason)
                self.stats["quarantined"] += 1
            entry.expires_at = time.monotonic() + self.ttl_s
            entry.reason = reason
            self._entries[key] = entry
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.stats["evicted"] += 1
        metrics.count("quarantine_adds")
        from ..utils import telemetry

        telemetry.record_event(
            "quarantine_add", self.name, reason,
            fingerprint=key.split(":")[-1][:16],
        )
        logger.warning("quarantined input %s: %s", key.split(":")[-1][:16], reason)
        return True

    # -- lookup ------------------------------------------------------------

    def reason(self, key: str | None) -> str | None:
        """Why ``key`` is quarantined, or None. Expired entries are purged
        lazily here (no sweeper thread); a live hit refreshes LRU order
        but NOT the TTL — rejections must not keep an entry alive forever."""
        if not self.enabled or not key:
            return None
        now = time.monotonic()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            if now >= entry.expires_at:
                self._entries.pop(key, None)
                self.stats["expired"] += 1
                return None
            self._entries.move_to_end(key)
            entry.rejections += 1
            self.stats["rejections"] += 1
        metrics.count("quarantine_rejections")
        return entry.reason

    def check(self, key: str | None) -> None:
        """Raise :class:`PoisonInput` when ``key`` is quarantined — the
        up-front rejection every layer calls before spending work. Marks
        the request-note scope so the response carries ``quarantined``,
        and records a ``quarantine`` span on the active request trace
        (only when one is live — the bare lookup stays a dict probe)."""
        from .trace import current_trace

        tr = current_trace()
        span = tr.begin("quarantine") if tr is not None else None
        reason = self.reason(key)
        if span is not None:
            span.end(rejected="1" if reason is not None else "0")
        if reason is not None:
            _mark("quarantined")
            raise PoisonInput(
                f"input quarantined after being isolated as a poison batch "
                f"member (TTL {self.ttl_s:.0f}s): {reason}"
            )

    # -- lifecycle ---------------------------------------------------------

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def close(self) -> None:
        metrics.unregister_gauges(self.name, self._gauge_fn)


def guarded_key(namespace: str, options, payload: bytes) -> str | None:
    """The managers' pre-compute gate: one content address serving BOTH
    the quarantine rejection (raises :class:`PoisonInput` on a hit) and
    the result-cache lookup — or ``None`` when the cache and quarantine
    are both disabled, in which case NO hash is computed at all (the
    ``LUMEN_CACHE_BYTES=0`` kill-switch path must not pay a sha256 over
    megabytes of image bytes to feed two disabled gates)."""
    from .result_cache import get_result_cache, make_key

    quarantine = get_quarantine()
    if not quarantine.enabled and not get_result_cache().enabled:
        return None
    key = make_key(namespace, options, payload)
    quarantine.check(key)
    return key


# -- process-wide instance ---------------------------------------------------

_shared: QuarantineRegistry | None = None
_shared_lock = threading.Lock()


def get_quarantine() -> QuarantineRegistry:
    """The process-wide registry (lazily built from the env)."""
    global _shared
    if _shared is None:
        with _shared_lock:
            if _shared is None:
                _shared = QuarantineRegistry(name="quarantine")
    return _shared


def reset_quarantine() -> None:
    """Drop the shared registry (tests); the next :func:`get_quarantine`
    rebuilds from the current env."""
    global _shared
    with _shared_lock:
        registry, _shared = _shared, None
    if registry is not None:
        registry.close()
