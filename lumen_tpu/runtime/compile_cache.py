"""Persistent XLA compilation cache.

The reference pays "model load time" once per process (onnxruntime session
build, ``onnxrt_backend.py:228``); our equivalent startup cost is XLA
compilation — tens of seconds per shape bucket on TPU, worse through a
remote-compile tunnel. JAX can persist compiled executables to disk keyed
by (HLO, backend, flags); enabling it turns every warm restart, bench
subprocess, and supervised-server respawn into a cache hit instead of a
recompile.

Opt-out via ``LUMEN_COMPILE_CACHE=0``; cache location override via
``LUMEN_COMPILE_CACHE_DIR`` (default ``~/.cache/lumen_tpu/xla``).
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

_DEFAULT_DIR = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")), "lumen_tpu", "xla"
)

_listener_lock = threading.Lock()
_listener_installed = False


def _on_jax_event(name: str, secs: float, **kwargs) -> None:  # noqa: ARG001
    """jax.monitoring duration listener: every backend compile lands on
    the capacity-telemetry rings as a count + a duration observation —
    the recompile-storm signal continuous batching needs (a healthy warm
    server shows ~0 compiles/window; a shape-churning caller shows a
    rising windowed rate at seconds per compile)."""
    if not name.endswith("backend_compile_duration"):
        return
    from . import telemetry
    from ..utils.metrics import metrics

    # metrics.count tees into the rolling window itself, so the windowed
    # `xla_compiles` rate comes for free with the cumulative counter;
    # only the duration histogram is telemetry-direct (a metrics.observe
    # would fabricate an "xla_compile_ms" row in the per-task table).
    metrics.count("xla_compiles")
    telemetry.observe("xla_compile_ms", secs * 1e3)


def install_compile_listener() -> bool:
    """Register the XLA compile-event hook (idempotent; returns whether
    the hook is live). Called from :func:`enable_persistent_cache` — the
    one place this repo configures JAX's compilation machinery — and
    safe on jax versions without ``jax.monitoring`` (degrades to off)."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return True
        try:
            from jax import monitoring

            monitoring.register_event_duration_secs_listener(_on_jax_event)
        except Exception as e:  # noqa: BLE001 - telemetry hook is never fatal
            logger.warning("XLA compile-event listener unavailable: %s", e)
            return False
        _listener_installed = True
    logger.info("XLA compile events feeding capacity telemetry")
    return True


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point JAX's compilation cache at a persistent directory.

    Idempotent; safe to call before or after backend init (the cache is
    consulted per compile). Returns the cache dir, or None when disabled.
    """
    # Compile events feed telemetry whether or not the disk cache is on:
    # the recompile-storm detector must not vanish with LUMEN_COMPILE_CACHE=0.
    install_compile_listener()
    if os.environ.get("LUMEN_COMPILE_CACHE") == "0":
        return None
    import jax

    cache_dir = path or os.environ.get("LUMEN_COMPILE_CACHE_DIR") or _DEFAULT_DIR
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # JAX's own gating (min compile time 1s by default) keeps ms-scale
        # programs out of the cache; every real model bucket qualifies.
    except Exception as e:  # noqa: BLE001 - cache is an optimization, never fatal
        logger.warning("persistent compile cache unavailable: %s", e)
        return None
    logger.info("persistent XLA compile cache at %s", cache_dir)
    return cache_dir
