"""Persistent XLA compilation cache.

The reference pays "model load time" once per process (onnxruntime session
build, ``onnxrt_backend.py:228``); our equivalent startup cost is XLA
compilation — tens of seconds per shape bucket on TPU, worse through a
remote-compile tunnel. JAX can persist compiled executables to disk keyed
by (HLO, backend, flags); enabling it turns every warm restart, bench
subprocess, and supervised-server respawn into a cache hit instead of a
recompile.

Opt-out via ``LUMEN_COMPILE_CACHE=0``; cache location override via
``LUMEN_COMPILE_CACHE_DIR`` (default ``~/.cache/lumen_tpu/xla``).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_DEFAULT_DIR = os.path.join(
    os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")), "lumen_tpu", "xla"
)


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point JAX's compilation cache at a persistent directory.

    Idempotent; safe to call before or after backend init (the cache is
    consulted per compile). Returns the cache dir, or None when disabled.
    """
    if os.environ.get("LUMEN_COMPILE_CACHE") == "0":
        return None
    import jax

    cache_dir = path or os.environ.get("LUMEN_COMPILE_CACHE_DIR") or _DEFAULT_DIR
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # JAX's own gating (min compile time 1s by default) keeps ms-scale
        # programs out of the cache; every real model bucket qualifies.
    except Exception as e:  # noqa: BLE001 - cache is an optimization, never fatal
        logger.warning("persistent compile cache unavailable: %s", e)
        return None
    logger.info("persistent XLA compile cache at %s", cache_dir)
    return cache_dir
