"""HTTP observability sidecar for the gRPC serving process.

A stdlib ``http.server`` thread exposing:

- ``GET  /metrics``        — Prometheus text exposition (cumulative
  ``le``-labeled ``_bucket`` histograms + ``_sum``/``_count``),
- ``GET  /metrics.json``   — JSON snapshot (per-task p50/p90/p99, errors),
- ``GET  /stats?window=N`` — rolling-window capacity view: last-N-seconds
  task latencies/rates, device/decode duty cycles, batch padding waste,
  transfer bytes, XLA compile activity, HBM occupancy/headroom and the
  SLO summary (see ``utils/telemetry.py``),
- ``GET  /slo``            — SLO objectives + multi-window burn state,
- ``GET  /autopilot``      — the capacity controller's state: per-loop
  enable flags + latest sensor readings, the chip ledger, and the last N
  actuation decisions with the readings that justified them,
- ``GET  /events?n=K``     — the incident flight recorder's event ring,
- ``GET  /incidents``      — captured incident bundles (breaker-open /
  replica-down / SLO-breach context dumps),
- ``GET  /traces``         — retained request traces (tail-sampled ring:
  errors + slowest-N + a sampled fraction; see ``utils/trace.py``),
- ``GET  /traces/perfetto``— the same traces as Chrome trace-event JSON,
  loadable in Perfetto/chrome://tracing next to a ``jax.profiler`` dump,
- ``POST /profiler/start`` — begin a ``jax.profiler`` trace (query
  parameter ``dir=...``, default ``/tmp/lumen-tpu-trace``),
- ``POST /profiler/stop``  — end the trace; response carries the trace dir.

Fills SURVEY.md §5's gap ("Tracing/profiling: none" in the reference): the
profiler endpoints give on-demand XLA/TPU traces viewable in TensorBoard or
Perfetto, the request traces attribute per-stage host latency (the gap the
device profiler cannot see), and the histograms come from the per-dispatch
hook in ``base_service.py``. Enabled with ``lumen-tpu --metrics-port N``.

Every HTTP route handled here must have a row in docs/OBSERVABILITY.md's
endpoint table — ``scripts/check_endpoints.py`` (collected by tier-1)
fails on the gap.
"""

from __future__ import annotations

import json
import logging
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import telemetry
from ..utils.metrics import metrics
from ..utils.trace import get_recorder

logger = logging.getLogger(__name__)

DEFAULT_TRACE_DIR = "/tmp/lumen-tpu-trace"


class _ProfilerState:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.active_dir: str | None = None

    def start(self, trace_dir: str) -> tuple[bool, str]:
        import jax

        with self.lock:
            if self.active_dir:
                return False, f"trace already running into {self.active_dir}"
            jax.profiler.start_trace(trace_dir)
            self.active_dir = trace_dir
            return True, trace_dir

    def stop(self) -> tuple[bool, str]:
        import jax

        with self.lock:
            if not self.active_dir:
                return False, "no trace running"
            # Clear state only AFTER stop succeeds: a stop_trace failure
            # must stay stoppable/observable, not wedge the profiler.
            jax.profiler.stop_trace()
            trace_dir, self.active_dir = self.active_dir, None
            return True, trace_dir


class MetricsServer:
    """Threaded HTTP sidecar; ``start()`` returns the bound port."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        # Loopback default on purpose: /profiler/* is unauthenticated
        # control; exposing it beyond the host must be an explicit choice.
        self.host = host
        self.port = port
        self.profiler = _ProfilerState()
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> int:
        profiler = self.profiler

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A002 - silence stdlib access log
                logger.debug("metrics: " + fmt, *args)

            def _send(self, code: int, body: str, content_type: str = "application/json"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 - stdlib API
                parsed = urlparse(self.path)
                path = parsed.path
                if path == "/metrics":
                    self._send(200, "\n".join(metrics.prometheus_lines()) + "\n", "text/plain; version=0.0.4")
                elif path == "/metrics.json":
                    snap = metrics.snapshot()
                    snap["device_memory"] = metrics.device_memory()
                    self._send(200, json.dumps(snap))
                elif path == "/stats":
                    q = parse_qs(parsed.query)
                    try:
                        window = float(q.get("window", ["60"])[0])
                    except ValueError:
                        window = 60.0
                    self._send(200, json.dumps(telemetry.capacity_stats(window)))
                elif path == "/slo":
                    self._send(200, json.dumps(telemetry.slo_report()))
                elif path == "/autopilot":
                    # Same no-jax rule as the router's Health probe: read
                    # the controller only when its module is already
                    # loaded; a jax-free sidecar answers "off" honestly.
                    mod = sys.modules.get("lumen_tpu.runtime.autopilot")
                    if mod is None:
                        body = {
                            "enabled": False, "running": False,
                            "loops": {}, "decisions": [],
                            "detail": "autopilot module not loaded in this process",
                        }
                    else:
                        try:
                            body = mod.export_status()
                        except Exception as e:  # noqa: BLE001 - report, don't 500
                            body = {"enabled": False, "running": False,
                                    "loops": {}, "decisions": [],
                                    "error": str(e)}
                    self._send(200, json.dumps(body))
                elif path == "/peers":
                    # Same no-jax rule as /autopilot: read the federation
                    # module only when it is already loaded in-process; a
                    # non-federated (or jax-free) sidecar answers
                    # "not configured" honestly.
                    mod = sys.modules.get("lumen_tpu.runtime.federation")
                    if mod is None:
                        body = {
                            "enabled": False, "peers": {},
                            "detail": "federation module not loaded in this process",
                        }
                    else:
                        try:
                            body = mod.export_status()
                        except Exception as e:  # noqa: BLE001 - report, don't 500
                            body = {"enabled": False, "peers": {}, "error": str(e)}
                    self._send(200, json.dumps(body))
                elif path == "/events":
                    q = parse_qs(parsed.query)
                    try:
                        n = int(q.get("n", ["0"])[0])
                    except ValueError:
                        n = 0
                    # n caps the tail; zero/negative means "everything"
                    # (a negative slice bound would silently invert the
                    # semantics to drop-oldest-K).
                    self._send(
                        200,
                        json.dumps(telemetry.export_events(n if n > 0 else None)),
                    )
                elif path == "/incidents":
                    self._send(200, json.dumps(telemetry.export_incidents()))
                elif path == "/traces":
                    self._send(200, json.dumps(get_recorder().export()))
                elif path == "/traces/perfetto":
                    self._send(200, json.dumps(get_recorder().perfetto()))
                elif path == "/health":
                    self._send(200, json.dumps({"status": "ok"}))
                else:
                    self._send(404, json.dumps({"error": "not found"}))

            def do_POST(self):  # noqa: N802 - stdlib API
                parsed = urlparse(self.path)
                if parsed.path == "/profiler/start":
                    q = parse_qs(parsed.query)
                    trace_dir = q.get("dir", [DEFAULT_TRACE_DIR])[0]
                    try:
                        ok, detail = profiler.start(trace_dir)
                    except Exception as e:  # noqa: BLE001 - report to client
                        self._send(500, json.dumps({"error": str(e)}))
                        return
                    self._send(200 if ok else 409, json.dumps({"tracing": ok, "dir": detail}))
                elif parsed.path == "/profiler/stop":
                    try:
                        ok, detail = profiler.stop()
                    except Exception as e:  # noqa: BLE001
                        self._send(500, json.dumps({"error": str(e)}))
                        return
                    self._send(200 if ok else 409, json.dumps({"stopped": ok, "dir": detail}))
                else:
                    self._send(404, json.dumps({"error": "not found"}))

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http", daemon=True
        )
        self._thread.start()
        logger.info("metrics endpoint on http://%s:%d/metrics", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
