"""Per-service circuit breaker: fast-fail a repeatedly-failing backend.

Without this, a broken model path (wedged device stream, corrupt weights
after a partial hot-swap, a kernel that started faulting) keeps every
request paying the FULL failure cost — admission queue, decode pool,
batch slot, device dispatch, error — forever. Production posture is the
standard three-state breaker:

- **closed** (normal): requests flow; consecutive non-poison failures
  within ``LUMEN_BREAKER_WINDOW_S`` are counted. Poison-input isolations
  (:class:`~lumen_tpu.utils.deadline.PoisonInput`) do NOT count — one bad
  payload retried in a loop must not take a healthy service down. Neither
  do overload verdicts (shed / deadline): those describe the caller's
  budget, not the backend's health.
- **open** (tripped, after ``LUMEN_BREAKER_FAILURES`` consecutive
  failures): every request sheds instantly — same UNAVAILABLE-with-hint
  shape as a :class:`~lumen_tpu.serving.resilience.DegradedService`
  answer, plus a retry-after hint and a ``breaker_open`` trailing-metadata
  note so clients can tell shed-by-breaker from shed-by-queue. The
  ``on_open`` hook can hand the service to the
  :class:`~lumen_tpu.serving.resilience.RecoveryManager` for a full
  reload (``LUMEN_BREAKER_RELOAD=1`` wires this in the server).
- **half-open** (after ``LUMEN_BREAKER_RESET_S``): exactly one probe
  request is admitted; success closes the breaker, failure re-opens it
  for another full reset window.

The breaker observes at the gRPC dispatch layer
(:meth:`~lumen_tpu.serving.base_service.BaseService._dispatch`), so
"batch failure" is seen once per affected request — with bisection
upstream, innocent co-batched requests succeed and correctly count as
successes. State changes land on :mod:`~lumen_tpu.utils.metrics`
(``breaker_opens`` / ``breaker_closes`` / ``breaker_sheds`` counters and a
``breaker:{service}`` gauge set) and in ``Health`` /
``StreamCapabilities`` via the router.

``LUMEN_BREAKER_FAILURES=0`` disables the breaker (no gate, no counting).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from typing import Callable

from ..utils import telemetry
from ..utils.env import env_float, env_int
from ..utils.metrics import metrics

logger = logging.getLogger(__name__)

BREAKER_FAILURES_ENV = "LUMEN_BREAKER_FAILURES"
BREAKER_WINDOW_ENV = "LUMEN_BREAKER_WINDOW_S"
BREAKER_RESET_ENV = "LUMEN_BREAKER_RESET_S"

DEFAULT_FAILURES = 6
DEFAULT_WINDOW_S = 30.0
DEFAULT_RESET_S = 10.0

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def breaker_failures() -> int:
    """``LUMEN_BREAKER_FAILURES``: consecutive non-poison failures that
    trip the breaker (0 disables; unset/malformed -> 6)."""
    return env_int(BREAKER_FAILURES_ENV, DEFAULT_FAILURES, minimum=0)


def breaker_window_s() -> float:
    """``LUMEN_BREAKER_WINDOW_S``: the failure streak must fit in this
    window to trip (a streak older than the window restarts the count)."""
    return env_float(BREAKER_WINDOW_ENV, DEFAULT_WINDOW_S, minimum=0.1)


def breaker_reset_s() -> float:
    """``LUMEN_BREAKER_RESET_S``: how long an open breaker sheds before
    admitting one half-open probe."""
    return env_float(BREAKER_RESET_ENV, DEFAULT_RESET_S, minimum=0.05)


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker for one service."""

    def __init__(
        self,
        name: str,
        failures: int | None = None,
        window_s: float | None = None,
        reset_s: float | None = None,
        on_open: Callable[[], None] | None = None,
    ):
        self.name = name
        self.failures = breaker_failures() if failures is None else max(0, failures)
        self.window_s = breaker_window_s() if window_s is None else max(0.1, window_s)
        self.reset_s = breaker_reset_s() if reset_s is None else max(0.05, reset_s)
        self.on_open = on_open
        self._lock = threading.Lock()
        self._state = CLOSED
        self._streak = 0  # consecutive non-poison failures
        self._streak_started = 0.0
        self._opened_at = 0.0
        self._probe_out = False  # half-open: one probe in flight
        self._probe_started = 0.0
        self.stats = {"opens": 0, "closes": 0, "sheds": 0, "poison": 0, "failures": 0}
        ref = weakref.ref(self)

        def _gauges() -> dict:
            b = ref()
            if b is None:
                return {}
            with b._lock:
                return {
                    **b.stats,
                    "state": _STATE_CODES[b._state],
                    "streak": b._streak,
                }

        self._gauge_fn = _gauges
        metrics.register_gauges(f"breaker:{name}", _gauges)

    @property
    def enabled(self) -> bool:
        return self.failures > 0

    def state(self) -> str:
        with self._lock:
            return self._state

    # -- admission ---------------------------------------------------------

    def allow(self) -> tuple[bool, float]:
        """Gate one request. Returns ``(admitted, retry_after_s)``;
        ``retry_after_s`` is only meaningful when shed. Transitions
        open -> half-open when the reset window has elapsed (the caller
        that triggers the transition becomes the probe)."""
        if not self.enabled:
            return True, 0.0
        with self._lock:
            if self._state == CLOSED:
                return True, 0.0
            now = time.monotonic()
            if self._state == OPEN:
                elapsed = now - self._opened_at
                if elapsed >= self.reset_s:
                    self._state = HALF_OPEN
                    self._probe_out = True
                    self._probe_started = now
                    logger.info("breaker %r half-open: admitting one probe", self.name)
                    return True, 0.0
                self.stats["sheds"] += 1
                return False, max(0.0, self.reset_s - elapsed)
            # half-open: only one probe at a time; everyone else waits a
            # reset window (the probe's verdict arrives well before that).
            # A probe that never reported back (abandoned stream, handler
            # path that records no outcome) must not shed traffic forever:
            # after a reset window it is presumed lost and replaced.
            if not self._probe_out or now - self._probe_started > self.reset_s:
                self._probe_out = True
                self._probe_started = now
                return True, 0.0
            self.stats["sheds"] += 1
            return False, self.reset_s

    # -- outcomes ----------------------------------------------------------

    def record_success(self) -> None:
        closed = False
        with self._lock:
            if self._state == HALF_OPEN:
                self._state = CLOSED
                self.stats["closes"] += 1
                closed = True
            self._streak = 0
            self._probe_out = False
        if closed:
            metrics.count("breaker_closes")
            telemetry.record_event(
                "breaker_close", self.name, "half-open probe succeeded"
            )
            logger.info("breaker %r closed: probe succeeded", self.name)

    def record_failure(self) -> None:
        """One non-poison backend failure (INTERNAL-class handler error,
        watchdog timeout, injected batch fault). Overload and client
        errors must NOT be recorded here."""
        if not self.enabled:
            return
        tripped = False
        with self._lock:
            self.stats["failures"] += 1
            now = time.monotonic()
            if self._state == HALF_OPEN:
                tripped = self._trip_locked(now, probe_failed=True)
            elif self._state == CLOSED:
                if self._streak == 0 or now - self._streak_started > self.window_s:
                    self._streak = 0
                    self._streak_started = now
                self._streak += 1
                if self._streak >= self.failures:
                    tripped = self._trip_locked(now)
            # open: in-flight stragglers admitted pre-trip; nothing to do.
        if tripped:
            # Flight recorder + incident bundle OUTSIDE the state lock:
            # the capture walks the metrics/trace surfaces, which must
            # not serialize behind (or deadlock with) breaker admission.
            telemetry.record_event(
                "breaker_open", self.name,
                f"circuit opened after repeated backend failures; "
                f"shedding for {self.reset_s:.1f}s",
            )
        if tripped and self.on_open is not None:
            try:
                self.on_open()
            except Exception:  # noqa: BLE001 - a broken hook must not break shedding
                logger.exception("breaker %r on_open hook failed", self.name)

    def record_poison(self) -> None:
        """A poison-input isolation: the payload, not the service, is
        broken — counted for telemetry, never toward tripping. Releases a
        half-open probe slot (a poison verdict says nothing about backend
        health, so the next request should get to probe)."""
        with self._lock:
            self.stats["poison"] += 1
            self._probe_out = False

    def record_neutral(self) -> None:
        """The request ended with no verdict on backend health — shed
        (:class:`~lumen_tpu.utils.deadline.QueueFull`), deadline expiry, a
        client-error ServiceError. Not counted anywhere, but it must
        release the half-open probe slot: a probe that was itself shed by
        admission control would otherwise pin the breaker half-open and
        shedding until the probe-expiry backstop."""
        with self._lock:
            self._probe_out = False

    def _trip_locked(self, now: float, probe_failed: bool = False) -> bool:
        self._state = OPEN
        self._opened_at = now
        self._streak = 0
        self._probe_out = False
        self.stats["opens"] += 1
        metrics.count("breaker_opens")
        logger.error(
            "breaker %r OPEN (%s); shedding for %.1fs",
            self.name,
            "half-open probe failed" if probe_failed else f"{self.failures} consecutive failures",
            self.reset_s,
        )
        return True

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        metrics.unregister_gauges(f"breaker:{self.name}", self._gauge_fn)
