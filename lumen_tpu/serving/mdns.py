"""Minimal multicast-DNS service advertiser (dependency-free).

LAN discovery parity with the reference, which registers a zeroconf
``_lumen._tcp.local.`` service (``src/lumen/server.py:75-149``). The
``zeroconf`` package is not in the TPU image, so this module speaks just
enough raw mDNS itself: it answers PTR/SRV/TXT/A queries for the advertised
instance and sends periodic unsolicited announcements.

Environment overrides mirror the reference: ``ADVERTISE_IP`` (skip
autodetection), ``SERVICE_UUID`` (stable instance identity), plus
``SERVICE_STATUS`` / ``SERVICE_VERSION`` merged into TXT properties.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
import uuid

logger = logging.getLogger(__name__)

MDNS_GROUP = "224.0.0.251"
MDNS_PORT = 5353
SERVICE_TYPE = "_lumen._tcp.local."

_TYPE_A, _TYPE_PTR, _TYPE_TXT, _TYPE_SRV, _TYPE_ANY = 1, 12, 16, 33, 255
_CLASS_IN = 1
_CACHE_FLUSH = 0x8001  # class IN with cache-flush bit


def detect_lan_ip() -> str:
    """Best-effort LAN IP via the UDP connect trick (no packets sent)."""
    override = os.environ.get("ADVERTISE_IP")
    if override:
        return override
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def _encode_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        raw = label.encode("utf-8")
        out += struct.pack("!B", len(raw)) + raw
    return out + b"\x00"


def _decode_name(data: bytes, off: int) -> tuple[str, int]:
    """Decode a DNS name honouring compression pointers; returns (name, next_offset)."""
    labels: list[str] = []
    jumped = False
    next_off = off
    hops = 0
    while True:
        if off >= len(data):
            break
        length = data[off]
        if length == 0:
            if not jumped:
                next_off = off + 1
            break
        if length & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(data) or hops > 32:
                break
            ptr = ((length & 0x3F) << 8) | data[off + 1]
            if not jumped:
                next_off = off + 2
                jumped = True
            off = ptr
            hops += 1
            continue
        labels.append(data[off + 1 : off + 1 + length].decode("utf-8", "replace"))
        off += 1 + length
    return ".".join(labels) + ".", next_off


def _record(name: str, rtype: int, rdata: bytes, ttl: int = 120) -> bytes:
    return _encode_name(name) + struct.pack("!HHIH", rtype, _CACHE_FLUSH if rtype != _TYPE_PTR else _CLASS_IN, ttl, len(rdata)) + rdata


class MdnsAdvertiser:
    """Advertise one service instance; run as a daemon thread."""

    def __init__(
        self,
        service_name: str,
        port: int,
        properties: dict[str, str] | None = None,
        ip: str | None = None,
    ):
        self.instance = f"{service_name}-{os.environ.get('SERVICE_UUID', uuid.uuid4().hex[:8])}"
        self.port = port
        self.ip = ip or detect_lan_ip()
        props = dict(properties or {})
        props.setdefault("status", os.environ.get("SERVICE_STATUS", "ready"))
        props.setdefault("version", os.environ.get("SERVICE_VERSION", "0.1.0"))
        self.properties = props
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- names ------------------------------------------------------------

    @property
    def instance_name(self) -> str:
        return f"{self.instance}.{SERVICE_TYPE}"

    @property
    def host_name(self) -> str:
        return f"{self.instance}.local."

    # -- packet building ---------------------------------------------------

    def _txt_rdata(self) -> bytes:
        out = b""
        for k, v in self.properties.items():
            kv = f"{k}={v}".encode("utf-8")[:255]
            out += struct.pack("!B", len(kv)) + kv
        return out or b"\x00"

    def _answers(self) -> list[bytes]:
        srv_rdata = struct.pack("!HHH", 0, 0, self.port) + _encode_name(self.host_name)
        a_rdata = socket.inet_aton(self.ip)
        return [
            _record(SERVICE_TYPE, _TYPE_PTR, _encode_name(self.instance_name)),
            _record(self.instance_name, _TYPE_SRV, srv_rdata),
            _record(self.instance_name, _TYPE_TXT, self._txt_rdata()),
            _record(self.host_name, _TYPE_A, a_rdata),
        ]

    def _response_packet(self) -> bytes:
        answers = self._answers()
        header = struct.pack("!HHHHHH", 0, 0x8400, 0, len(answers), 0, 0)
        return header + b"".join(answers)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM, socket.IPPROTO_UDP)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            sock.bind(("", MDNS_PORT))
            mreq = socket.inet_aton(MDNS_GROUP) + socket.inet_aton("0.0.0.0")
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
        except OSError as e:
            logger.warning("mDNS unavailable (%s); discovery disabled", e)
            sock.close()
            return
        sock.settimeout(1.0)
        self._sock = sock
        self._thread = threading.Thread(target=self._run, name="mdns", daemon=True)
        self._thread.start()
        logger.info("mDNS advertising %s at %s:%d", self.instance_name, self.ip, self.port)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)
        if self._sock:
            try:
                # Goodbye packet: TTL 0 announcement.
                pkt = struct.pack("!HHHHHH", 0, 0x8400, 0, 1, 0, 0) + _record(
                    SERVICE_TYPE, _TYPE_PTR, _encode_name(self.instance_name), ttl=0
                )
                self._sock.sendto(pkt, (MDNS_GROUP, MDNS_PORT))
            except OSError:
                pass
            self._sock.close()
            self._sock = None

    def _run(self) -> None:
        next_announce = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_announce:
                try:
                    self._sock.sendto(self._response_packet(), (MDNS_GROUP, MDNS_PORT))
                except OSError:
                    pass
                next_announce = now + 60.0
            try:
                data, addr = self._sock.recvfrom(4096)
                if self._matches_query(data):
                    self._sock.sendto(self._response_packet(), (MDNS_GROUP, MDNS_PORT))
            except socket.timeout:
                pass
            except OSError:
                break

    def _matches_query(self, data: bytes) -> bool:
        if len(data) < 12:
            return False
        (tid, flags, qdcount, *_rest) = struct.unpack("!HHHHHH", data[:12])
        if flags & 0x8000:  # a response, not a query
            return False
        off = 12
        ours = {SERVICE_TYPE.lower(), self.instance_name.lower(), self.host_name.lower()}
        for _ in range(qdcount):
            try:
                qname, off = _decode_name(data, off)
                off += 4  # qtype + qclass
            except Exception:  # noqa: BLE001 - malformed packet
                return False
            if qname.lower() in ours:
                return True
        return False
