"""Minimal multicast-DNS service advertiser (dependency-free).

LAN discovery parity with the reference, which registers a zeroconf
``_lumen._tcp.local.`` service (``src/lumen/server.py:75-149``). The
``zeroconf`` package is not in the TPU image, so this module speaks just
enough raw mDNS itself: it answers PTR/SRV/TXT/A queries for the advertised
instance and sends periodic unsolicited announcements.

Environment overrides mirror the reference: ``ADVERTISE_IP`` (skip
autodetection), ``SERVICE_UUID`` (stable instance identity), plus
``SERVICE_STATUS`` / ``SERVICE_VERSION`` merged into TXT properties.
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
import uuid

logger = logging.getLogger(__name__)

MDNS_GROUP = "224.0.0.251"
MDNS_PORT = 5353
SERVICE_TYPE = "_lumen._tcp.local."

_TYPE_A, _TYPE_PTR, _TYPE_TXT, _TYPE_SRV, _TYPE_ANY = 1, 12, 16, 33, 255
_CLASS_IN = 1
_CACHE_FLUSH = 0x8001  # class IN with cache-flush bit


def detect_lan_ip() -> str:
    """Best-effort LAN IP via the UDP connect trick (no packets sent)."""
    override = os.environ.get("ADVERTISE_IP")
    if override:
        return override
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("10.255.255.255", 1))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def _encode_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        raw = label.encode("utf-8")
        out += struct.pack("!B", len(raw)) + raw
    return out + b"\x00"


def _decode_name(data: bytes, off: int) -> tuple[str, int]:
    """Decode a DNS name honouring compression pointers; returns (name, next_offset)."""
    labels: list[str] = []
    jumped = False
    next_off = off
    hops = 0
    while True:
        if off >= len(data):
            break
        length = data[off]
        if length == 0:
            if not jumped:
                next_off = off + 1
            break
        if length & 0xC0 == 0xC0:  # compression pointer
            if off + 1 >= len(data) or hops > 32:
                break
            ptr = ((length & 0x3F) << 8) | data[off + 1]
            if not jumped:
                next_off = off + 2
                jumped = True
            off = ptr
            hops += 1
            continue
        labels.append(data[off + 1 : off + 1 + length].decode("utf-8", "replace"))
        off += 1 + length
    return ".".join(labels) + ".", next_off


def _record(name: str, rtype: int, rdata: bytes, ttl: int = 120) -> bytes:
    return _encode_name(name) + struct.pack("!HHIH", rtype, _CACHE_FLUSH if rtype != _TYPE_PTR else _CLASS_IN, ttl, len(rdata)) + rdata


class MdnsAdvertiser:
    """Advertise one service instance; run as a daemon thread."""

    def __init__(
        self,
        service_name: str,
        port: int,
        properties: dict[str, str] | None = None,
        ip: str | None = None,
    ):
        self.instance = f"{service_name}-{os.environ.get('SERVICE_UUID', uuid.uuid4().hex[:8])}"
        self.port = port
        self.ip = ip or detect_lan_ip()
        props = dict(properties or {})
        props.setdefault("status", os.environ.get("SERVICE_STATUS", "ready"))
        props.setdefault("version", os.environ.get("SERVICE_VERSION", "0.1.0"))
        self.properties = props
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- names ------------------------------------------------------------

    @property
    def instance_name(self) -> str:
        return f"{self.instance}.{SERVICE_TYPE}"

    @property
    def host_name(self) -> str:
        return f"{self.instance}.local."

    # -- packet building ---------------------------------------------------

    def _txt_rdata(self) -> bytes:
        out = b""
        for k, v in self.properties.items():
            kv = f"{k}={v}".encode("utf-8")[:255]
            out += struct.pack("!B", len(kv)) + kv
        return out or b"\x00"

    def _answers(self) -> list[bytes]:
        srv_rdata = struct.pack("!HHH", 0, 0, self.port) + _encode_name(self.host_name)
        a_rdata = socket.inet_aton(self.ip)
        return [
            _record(SERVICE_TYPE, _TYPE_PTR, _encode_name(self.instance_name)),
            _record(self.instance_name, _TYPE_SRV, srv_rdata),
            _record(self.instance_name, _TYPE_TXT, self._txt_rdata()),
            _record(self.host_name, _TYPE_A, a_rdata),
        ]

    def _response_packet(self) -> bytes:
        answers = self._answers()
        header = struct.pack("!HHHHHH", 0, 0x8400, 0, len(answers), 0, 0)
        return header + b"".join(answers)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM, socket.IPPROTO_UDP)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            sock.bind(("", MDNS_PORT))
            mreq = socket.inet_aton(MDNS_GROUP) + socket.inet_aton("0.0.0.0")
            sock.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
        except OSError as e:
            logger.warning("mDNS unavailable (%s); discovery disabled", e)
            sock.close()
            return
        sock.settimeout(1.0)
        self._sock = sock
        self._thread = threading.Thread(target=self._run, name="mdns", daemon=True)
        self._thread.start()
        logger.info("mDNS advertising %s at %s:%d", self.instance_name, self.ip, self.port)

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)
        if self._sock:
            try:
                # Goodbye packet: TTL 0 announcement.
                pkt = struct.pack("!HHHHHH", 0, 0x8400, 0, 1, 0, 0) + _record(
                    SERVICE_TYPE, _TYPE_PTR, _encode_name(self.instance_name), ttl=0
                )
                self._sock.sendto(pkt, (MDNS_GROUP, MDNS_PORT))
            except OSError:
                pass
            self._sock.close()
            self._sock = None

    def _run(self) -> None:
        next_announce = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_announce:
                try:
                    self._sock.sendto(self._response_packet(), (MDNS_GROUP, MDNS_PORT))
                except OSError:
                    pass
                next_announce = now + 60.0
            try:
                data, addr = self._sock.recvfrom(4096)
                if self._matches_query(data):
                    self._sock.sendto(self._response_packet(), (MDNS_GROUP, MDNS_PORT))
            except socket.timeout:
                pass
            except OSError:
                break

    def _matches_query(self, data: bytes) -> bool:
        if len(data) < 12:
            return False
        (tid, flags, qdcount, *_rest) = struct.unpack("!HHHHHH", data[:12])
        if flags & 0x8000:  # a response, not a query
            return False
        off = 12
        ours = {SERVICE_TYPE.lower(), self.instance_name.lower(), self.host_name.lower()}
        for _ in range(qdcount):
            try:
                qname, off = _decode_name(data, off)
                off += 4  # qtype + qclass
            except Exception:  # noqa: BLE001 - malformed packet
                return False
            if qname.lower() in ours:
                return True
        return False


def parse_mdns_response(data: bytes) -> list[dict]:
    """Parse one mDNS RESPONSE packet into advertised ``_lumen._tcp``
    instances: ``[{instance, host, ip, port, properties}]``.

    The inverse of :meth:`MdnsAdvertiser._response_packet` (and of any
    zeroconf-compliant advertiser): walk the answer records, join SRV
    (port + target host) with A (host -> IP) and TXT (properties) per
    instance. Records for other service types are ignored. Malformed
    packets return ``[]`` — discovery is best-effort by construction."""
    if len(data) < 12:
        return []
    try:
        _tid, flags, qdcount, ancount, nscount, arcount = struct.unpack(
            "!HHHHHH", data[:12]
        )
    except struct.error:
        return []
    if not flags & 0x8000:  # a query, not a response
        return []
    off = 12
    try:
        for _ in range(qdcount):  # skip the (usually absent) question section
            _q, off = _decode_name(data, off)
            off += 4
        srv: dict[str, tuple[str, int]] = {}  # instance -> (target host, port)
        txt: dict[str, dict[str, str]] = {}
        a_records: dict[str, str] = {}  # host name -> dotted quad
        for _ in range(ancount + nscount + arcount):
            name, off = _decode_name(data, off)
            if off + 10 > len(data):
                break
            rtype, _rclass, _ttl, rdlen = struct.unpack(
                "!HHIH", data[off : off + 10]
            )
            off += 10
            rdata_off, off = off, off + rdlen
            if off > len(data):
                break
            if rtype == _TYPE_SRV and rdlen >= 6:
                _prio, _weight, port = struct.unpack(
                    "!HHH", data[rdata_off : rdata_off + 6]
                )
                target, _ = _decode_name(data, rdata_off + 6)
                srv[name.lower()] = (target.lower(), port)
            elif rtype == _TYPE_A and rdlen == 4:
                a_records[name.lower()] = socket.inet_ntoa(
                    data[rdata_off : rdata_off + 4]
                )
            elif rtype == _TYPE_TXT:
                props: dict[str, str] = {}
                p = rdata_off
                while p < rdata_off + rdlen:
                    ln = data[p]
                    kv = data[p + 1 : p + 1 + ln].decode("utf-8", "replace")
                    p += 1 + ln
                    if "=" in kv:
                        k, _, v = kv.partition("=")
                        props[k] = v
                txt[name.lower()] = props
    except Exception:  # noqa: BLE001 - malformed packet: nothing discovered
        return []
    out = []
    for instance, (target, port) in srv.items():
        if not instance.endswith(SERVICE_TYPE.lower()):
            continue
        ip = a_records.get(target)
        if ip is None and a_records:
            # Single-advertiser packets (ours) carry exactly one A record.
            ip = next(iter(a_records.values()))
        if ip is None:
            continue
        out.append({
            "instance": instance[: -len(SERVICE_TYPE) - 1] or instance,
            "host": target,
            "ip": ip,
            "port": port,
            "properties": txt.get(instance, {}),
        })
    return out


class MdnsBrowser:
    """One-shot LAN browse for ``_lumen._tcp`` advertisers — the matching
    half of :class:`MdnsAdvertiser` (which only answers queries). Used by
    federation peer discovery (``LUMEN_FED_DISCOVER=1``): send one PTR
    query for the service type, collect responses for ``timeout_s``,
    return the parsed instances."""

    def __init__(self, timeout_s: float = 1.5):
        self.timeout_s = timeout_s

    def _query_packet(self) -> bytes:
        header = struct.pack("!HHHHHH", 0, 0, 1, 0, 0, 0)
        question = _encode_name(SERVICE_TYPE) + struct.pack("!HH", _TYPE_PTR, _CLASS_IN)
        return header + question

    def browse(self) -> list[dict]:
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM, socket.IPPROTO_UDP)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        found: dict[tuple[str, int], dict] = {}
        try:
            try:
                sock.bind(("", MDNS_PORT))
                mreq = socket.inet_aton(MDNS_GROUP) + socket.inet_aton("0.0.0.0")
                sock.setsockopt(socket.IPPROTO_IP, socket.IP_ADD_MEMBERSHIP, mreq)
            except OSError as e:
                logger.warning("mDNS browse unavailable (%s)", e)
                return []
            sock.settimeout(0.25)
            sock.sendto(self._query_packet(), (MDNS_GROUP, MDNS_PORT))
            deadline = time.monotonic() + self.timeout_s
            while time.monotonic() < deadline:
                try:
                    data, _addr = sock.recvfrom(4096)
                except socket.timeout:
                    continue
                except OSError:
                    break
                for rec in parse_mdns_response(data):
                    found[(rec["ip"], rec["port"])] = rec
        finally:
            sock.close()
        return list(found.values())


def discover_peers(timeout_s: float = 1.5) -> list[str]:
    """One-shot federation peer discovery: browse the LAN and return
    ``host:port`` gRPC addresses of advertised lumen servers, sorted for
    deterministic ring membership across hosts that ran the same browse."""
    peers = sorted(f"{r['ip']}:{r['port']}" for r in MdnsBrowser(timeout_s).browse())
    if peers:
        logger.info("mDNS discovery resolved %d peer(s): %s", len(peers), peers)
    else:
        logger.info("mDNS discovery found no lumen advertisers on the LAN")
    return peers
