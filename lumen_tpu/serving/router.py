"""Hub router: one gRPC endpoint multiplexing several model services.

Same role as the reference ``src/lumen/router.py:10-87``: a routing table
from task key -> child service is built from each child's registry; ``Infer``
peeks at the first message of the stream to pick the child and then forwards
the whole stream zero-copy; capabilities aggregate; health is the AND of all
children.
"""

from __future__ import annotations

import itertools
import logging
from typing import Iterable, Iterator

import grpc
from google.protobuf import empty_pb2

from .base_service import BaseService
from .proto import ml_service_pb2 as pb
from .proto.ml_service_pb2_grpc import InferenceServicer

logger = logging.getLogger(__name__)


class HubRouter(InferenceServicer):
    def __init__(self, services: dict[str, BaseService]):
        self.services = services
        self._route_table: dict[str, BaseService] = {}
        for name, svc in services.items():
            for task in svc.registry.task_names():
                if task in self._route_table:
                    raise ValueError(
                        f"task {task!r} registered by multiple services "
                        f"(second: {name!r})"
                    )
                self._route_table[task] = svc
        logger.info(
            "hub routing table: %s", {t: s.registry.service_name for t, s in self._route_table.items()}
        )

    def attach_to_server(self, server: grpc.Server) -> None:
        from .proto.ml_service_pb2_grpc import add_InferenceServicer_to_server

        add_InferenceServicer_to_server(self, server)

    # -- rpcs -------------------------------------------------------------

    def Infer(self, request_iterator: Iterable[pb.InferRequest], context) -> Iterator[pb.InferResponse]:
        try:
            first = next(iter(request_iterator))
        except StopIteration:
            return
        target = self._route_table.get(first.task)
        if target is None:
            yield pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                error=pb.Error(
                    code=pb.ERROR_CODE_INVALID_ARGUMENT,
                    message=f"no service handles task {first.task!r}",
                    detail=f"known tasks: {sorted(self._route_table)}",
                ),
            )
            return
        # Re-prepend the consumed first message; forward the stream as-is.
        yield from target.Infer(itertools.chain([first], request_iterator), context)

    def GetCapabilities(self, request, context) -> pb.Capability:
        # Aggregate: merge every child capability into one record (the
        # detailed per-service view is StreamCapabilities).
        agg = pb.Capability(
            service_name="hub",
            runtime="jax-tpu",
            protocol_version="1.0.0",
        )
        for svc in self.services.values():
            cap = svc.capability()
            agg.model_ids.extend(cap.model_ids)
            agg.tasks.extend(cap.tasks)
            for p in cap.precisions:
                if p not in agg.precisions:
                    agg.precisions.append(p)
            agg.max_concurrency = max(agg.max_concurrency, cap.max_concurrency)
        return agg

    def StreamCapabilities(self, request, context) -> Iterator[pb.Capability]:
        for svc in self.services.values():
            yield svc.capability()

    def Health(self, request, context):
        for name, svc in self.services.items():
            if not svc.healthy():
                context.abort(grpc.StatusCode.UNAVAILABLE, f"service {name!r} unhealthy")
        return empty_pb2.Empty()
