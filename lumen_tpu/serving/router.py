"""Hub router: one gRPC endpoint multiplexing several model services.

Same role as the reference ``src/lumen/router.py:10-87``: a routing table
from task key -> child service is built from each child's registry; ``Infer``
peeks at the first message of the stream to pick the child and then forwards
the whole stream zero-copy; capabilities aggregate.

Resilience semantics on top of the reference:

- services can be hot-swapped (:meth:`replace_service`) — the background
  recovery loop promotes a ``DegradedService`` placeholder to the real
  service without restarting the server; the route table rebuilds
  atomically under a lock;
- ``Health`` reports per-service status in trailing metadata
  (``lumen-service-status``: JSON ``{name: state}``). A *degraded* service
  (known-broken, recovering) does NOT fail hub health — healthy siblings
  keep serving; an *unhealthy* one (unexpected) still aborts UNAVAILABLE,
  as does a hub with no working service at all;
- an unknown task while some service is degraded answers UNAVAILABLE with
  the degraded-service hint, not INVALID_ARGUMENT — the task may well
  belong to the broken service, and "client bug" is the wrong message;
- containment state is first-class: per-service circuit-breaker states
  ride ``Health`` trailing metadata (``lumen-breaker-status``) and each
  ``StreamCapabilities`` record (``extra["breaker"]``), and the current
  poison-quarantine size rides ``lumen-quarantine-size`` — a client can
  tell "backend fast-failing" from "overloaded" without a failed Infer;
- multi-tenant QoS state rides ``lumen-qos-status`` (per-admission-queue
  occupancy + brownout level, per-tenant quota admit/shed totals) so an
  operator sees "tenant X is being browned out" from a Health probe, and
  each ``StreamCapabilities`` record carries ``extra["qos"]``;
- SLO burn state rides ``lumen-slo-status`` (per-task breach/ok + 5m/1h
  error-budget burn rates from ``utils/telemetry.py``) — a Health probe
  is also the lazy SLO evaluation tick, so breach counters and incident
  bundles fire within one probe of the window turning bad.
"""

from __future__ import annotations

import itertools
import json
import logging
import sys
import threading
from typing import Iterable, Iterator

import grpc
from google.protobuf import empty_pb2

from .base_service import BaseService
from .proto import ml_service_pb2 as pb
from .proto.ml_service_pb2_grpc import InferenceServicer

logger = logging.getLogger(__name__)


class HubRouter(InferenceServicer):
    def __init__(self, services: dict[str, BaseService]):
        self.services = dict(services)
        self._lock = threading.Lock()
        self._route_table: dict[str, BaseService] = {}
        # Graceful-drain gate: once set, new Infer streams answer
        # UNAVAILABLE with a retry-after hint while queued/in-flight work
        # completes (see ServerHandle.drain_and_stop). _active_streams
        # counts forwarded Infer streams so the drain knows when the last
        # one finished — gRPC itself does not expose this.
        self._draining = False
        self._drain_retry_ms = "1000"
        self._active_streams = 0
        self._rebuild_routes()

    def begin_drain(self, retry_after_s: float = 1.0) -> None:
        """Stop admitting new RPCs: every subsequent Infer stream answers
        UNAVAILABLE carrying ``lumen-retry-after-ms`` (sized to the drain
        budget — by then this process is gone and the client's next
        attempt lands on a live sibling). In-flight streams are untouched;
        the gRPC server's grace period drains them."""
        from ..utils.qos import retry_after_ms

        self._drain_retry_ms = retry_after_ms(max(retry_after_s, 0.001))
        self._draining = True
        logger.info(
            "drain: refusing new RPCs (retry-after %sms)", self._drain_retry_ms
        )

    @property
    def draining(self) -> bool:
        return self._draining

    def active_streams(self) -> int:
        """Forwarded Infer streams currently executing — the drain's
        "is the house empty yet" probe."""
        with self._lock:
            return self._active_streams

    def _rebuild_routes(self) -> None:
        table: dict[str, BaseService] = {}
        owner: dict[str, str] = {}
        for name, svc in self.services.items():
            for task in svc.registry.task_names():
                if task in table:
                    raise ValueError(
                        f"task {task!r} registered by multiple services "
                        f"(first: {owner[task]!r}, second: {name!r})"
                    )
                table[task] = svc
                owner[task] = name
        self._route_table = table
        logger.info(
            "hub routing table: %s",
            {t: s.registry.service_name for t, s in table.items()},
        )

    def replace_service(self, name: str, svc: BaseService) -> None:
        """Atomically swap a child service (degraded -> recovered) and
        rebuild the route table. The old service's in-flight streams keep
        their reference; new streams route to the replacement. A duplicate
        task in the replacement rolls the swap back."""
        with self._lock:
            old = self.services.get(name)
            self.services[name] = svc
            try:
                self._rebuild_routes()
            except ValueError:
                if old is None:
                    self.services.pop(name, None)
                else:
                    self.services[name] = old
                self._rebuild_routes()
                raise
        # Hot-swap cache invalidation: result-cache namespaces lead with
        # the service family name, so dropping the prefix guarantees the
        # swapped-in model never serves a predecessor's cached results —
        # even if id+revision happen to match (e.g. same model re-loaded
        # after a recovery). Lazy import: the router must stay importable
        # without the jax-importing runtime package.
        from ..runtime.result_cache import invalidate_namespace

        # Prefix = the service FAMILY (registry name: "clip"/"face"/...),
        # which is what the managers key their namespaces with; the router
        # key is a config alias that may differ. Ingest records embed
        # model ids mid-namespace where a prefix can't reach them, so any
        # hot-swap drops the whole (rebuildable) ingest cache too — swaps
        # are rare, stale whole-photo records are not worth the risk.
        prefixes = {getattr(svc.registry, "service_name", name), name, "ingest"}

        def sweep() -> int:
            return sum(invalidate_namespace(f"{p}/") for p in prefixes)

        dropped = sweep()
        close = getattr(old, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - best-effort teardown of the placeholder
                logger.exception("closing replaced service %r failed", name)
        # Sweep AGAIN after the old service is closed: a request that
        # entered the old instance after the first sweep captured a
        # post-invalidation fence, so the store-side fence cannot reject
        # it — but it completed before close() finished, so this second
        # sweep removes it. Anything starting later hits the old
        # instance's closed batchers and produces nothing to cache.
        dropped += sweep()
        if dropped:
            logger.info(
                "hot-swap of %r invalidated %d cached result(s)", name, dropped
            )

    def _route(self, task: str) -> BaseService | None:
        with self._lock:
            return self._route_table.get(task)

    def _statuses(self) -> dict[str, str]:
        with self._lock:
            return {name: svc.status() for name, svc in sorted(self.services.items())}

    def attach_to_server(self, server: grpc.Server) -> None:
        from .proto.ml_service_pb2_grpc import add_InferenceServicer_to_server

        add_InferenceServicer_to_server(self, server)

    # -- rpcs -------------------------------------------------------------

    def Infer(self, request_iterator: Iterable[pb.InferRequest], context) -> Iterator[pb.InferResponse]:
        try:
            first = next(iter(request_iterator))
        except StopIteration:
            return
        if self._draining:
            from ..utils.qos import RETRY_AFTER_META

            yield pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                meta={RETRY_AFTER_META: self._drain_retry_ms},
                error=pb.Error(
                    code=pb.ERROR_CODE_UNAVAILABLE,
                    message="server is draining for shutdown",
                    detail=(
                        "graceful drain in progress; retry with backoff "
                        "(lumen-retry-after-ms) against another replica"
                    ),
                ),
            )
            return
        target = self._route(first.task)
        if target is None:
            degraded = {n: s for n, s in self._statuses().items() if s in ("degraded", "failed")}
            if degraded:
                # The task may belong to a service that failed to load and
                # could not even declare its tasks — answer "broken
                # backend", not "client bug".
                yield pb.InferResponse(
                    correlation_id=first.correlation_id,
                    is_final=True,
                    error=pb.Error(
                        code=pb.ERROR_CODE_UNAVAILABLE,
                        message=(
                            f"no healthy service handles task {first.task!r}; "
                            f"degraded services: {sorted(degraded)}"
                        ),
                        detail="recovery is retrying in the background; retry later",
                    ),
                )
                return
            yield pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                error=pb.Error(
                    code=pb.ERROR_CODE_INVALID_ARGUMENT,
                    message=f"no service handles task {first.task!r}",
                    detail=f"known tasks: {sorted(self._route_table)}",
                ),
            )
            return
        # Re-prepend the consumed first message; forward the stream as-is.
        # The active-stream count brackets the forward so a drain can tell
        # "in-flight work still running" from "house empty".
        with self._lock:
            self._active_streams += 1
        try:
            yield from target.Infer(itertools.chain([first], request_iterator), context)
        finally:
            with self._lock:
                self._active_streams -= 1

    def GetCapabilities(self, request, context) -> pb.Capability:
        # Aggregate: merge every child capability into one record (the
        # detailed per-service view is StreamCapabilities).
        agg = pb.Capability(
            service_name="hub",
            runtime="jax-tpu",
            protocol_version="1.0.0",
        )
        with self._lock:
            services = list(self.services.values())
        for svc in services:
            cap = svc.capability()
            agg.model_ids.extend(cap.model_ids)
            agg.tasks.extend(cap.tasks)
            for p in cap.precisions:
                if p not in agg.precisions:
                    agg.precisions.append(p)
            agg.max_concurrency = max(agg.max_concurrency, cap.max_concurrency)
        return agg

    def StreamCapabilities(self, request, context) -> Iterator[pb.Capability]:
        with self._lock:
            services = list(self.services.values())
        for svc in services:
            cap = svc.capability()
            breaker = getattr(svc, "breaker", None)
            if breaker is not None:
                # Live containment state rides the capability record so a
                # client refreshing capabilities sees "backend fast-failing"
                # without a failed Infer round-trip.
                cap.extra["breaker"] = breaker.state()
            yield cap

    def _breaker_states(self) -> dict[str, str]:
        with self._lock:
            services = list(self.services.items())
        return {
            name: breaker.state()
            for name, svc in services
            if (breaker := getattr(svc, "breaker", None)) is not None
        }

    def _replica_states(self) -> dict[str, dict]:
        """Per-service replica-fleet states ({service: {dispatcher:
        {replica: state}}}); services without a fleet report nothing.
        jax-free: the states come from the service objects, the router
        never touches the runtime package."""
        with self._lock:
            services = list(self.services.items())
        out: dict[str, dict] = {}
        for name, svc in services:
            try:
                states = svc.replica_states()
            except Exception:  # noqa: BLE001 - health must never fail on telemetry
                continue
            if states:
                out[name] = states
        return out

    @staticmethod
    def _qos_status() -> dict:
        """Live multi-tenant QoS state (jax-free — the implementation
        lives in ``utils.qos`` precisely so this router can read it on
        jax-free deployments). ``{}`` omits the key entirely."""
        from ..utils import qos

        try:
            return qos.status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    @staticmethod
    def _slo_state() -> dict:
        """Evaluated SLO burn state per task (jax-free — the engine lives
        in ``utils.telemetry``). ``{}`` (no objectives configured, or no
        traffic) omits the key entirely. Evaluating here is what makes a
        Health probe flip ``lumen-slo-status`` within one window: the
        engine is lazy, and Health is the operator's poll."""
        from ..utils import telemetry

        try:
            return telemetry.slo_status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    @staticmethod
    def _autopilot_state() -> dict:
        """Compact autopilot state WITHOUT importing the runtime package
        (jax — same rule as the quarantine probe): only report when the
        controller module is already loaded in-process. ``{}`` omits the
        key."""
        mod = sys.modules.get("lumen_tpu.runtime.autopilot")
        if mod is None:
            return {}
        try:
            return mod.health_status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    @staticmethod
    def _quarantine_size() -> int | None:
        """Entries currently quarantined, WITHOUT importing the runtime
        package (which drags in jax — this router must stay importable and
        health-checkable on jax-free deployments like the echo service):
        only report when the runtime is already loaded in-process."""
        mod = sys.modules.get("lumen_tpu.runtime.quarantine")
        if mod is None:
            return None
        try:
            return len(mod.get_quarantine())
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return None

    def Health(self, request, context):
        statuses = self._statuses()
        if context is not None:
            try:
                trailing = [("lumen-service-status", json.dumps(statuses))]
                breakers = self._breaker_states()
                if breakers:
                    trailing.append(("lumen-breaker-status", json.dumps(breakers)))
                quarantined = self._quarantine_size()
                if quarantined is not None:
                    trailing.append(("lumen-quarantine-size", str(quarantined)))
                replicas = self._replica_states()
                if replicas:
                    # Per-replica fleet health next to the breaker/
                    # quarantine keys: a DOWN replica is a reported
                    # condition (siblings keep the hub SERVING), exactly
                    # like a degraded sibling service.
                    trailing.append(("lumen-replica-status", json.dumps(replicas)))
                slo_state = self._slo_state()
                if slo_state:
                    # SLO burn next to the containment keys: a breaching
                    # task is a reported condition (clients may back off
                    # bulk traffic), not an outage — the hub still serves.
                    trailing.append(("lumen-slo-status", json.dumps(slo_state)))
                qos_state = self._qos_status()
                if qos_state:
                    # Multi-tenant QoS next to the containment keys:
                    # per-admission-queue occupancy/brownout and the
                    # quota gate's per-tenant admit/shed totals — a
                    # browned-out bulk lane is a reported condition, not
                    # an outage.
                    trailing.append(("lumen-qos-status", json.dumps(qos_state)))
                ap_state = self._autopilot_state()
                if ap_state:
                    # Whether the capacity controller is live, which loops
                    # it holds, and its last actuation — so "who parked
                    # that replica / forced that rung" is answerable from
                    # a Health probe.
                    trailing.append(("lumen-autopilot-status", json.dumps(ap_state)))
                context.set_trailing_metadata(tuple(trailing))
            except Exception:  # noqa: BLE001 - test stubs may lack metadata support
                pass
        unhealthy = [n for n, s in statuses.items() if s == "unhealthy"]
        broken = [n for n, s in statuses.items() if s != "healthy"]
        if unhealthy:
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"service(s) unhealthy: {sorted(unhealthy)}",
            )
        if statuses and len(broken) == len(statuses):
            # Nothing left serving: a hub of only degraded placeholders is
            # not healthy, however gracefully it boots.
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"all services degraded: {sorted(broken)}",
            )
        return empty_pb2.Empty()
