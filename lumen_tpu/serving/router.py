"""Hub router: one gRPC endpoint multiplexing several model services.

Same role as the reference ``src/lumen/router.py:10-87``: a routing table
from task key -> child service is built from each child's registry; ``Infer``
peeks at the first message of the stream to pick the child and then forwards
the whole stream zero-copy; capabilities aggregate.

Resilience semantics on top of the reference:

- services can be hot-swapped (:meth:`replace_service`) — the background
  recovery loop promotes a ``DegradedService`` placeholder to the real
  service without restarting the server; the route table rebuilds
  atomically under a lock;
- ``Health`` reports per-service status in trailing metadata
  (``lumen-service-status``: JSON ``{name: state}``). A *degraded* service
  (known-broken, recovering) does NOT fail hub health — healthy siblings
  keep serving; an *unhealthy* one (unexpected) still aborts UNAVAILABLE,
  as does a hub with no working service at all;
- an unknown task while some service is degraded answers UNAVAILABLE with
  the degraded-service hint, not INVALID_ARGUMENT — the task may well
  belong to the broken service, and "client bug" is the wrong message;
- containment state is first-class: per-service circuit-breaker states
  ride ``Health`` trailing metadata (``lumen-breaker-status``) and each
  ``StreamCapabilities`` record (``extra["breaker"]``), and the current
  poison-quarantine size rides ``lumen-quarantine-size`` — a client can
  tell "backend fast-failing" from "overloaded" without a failed Infer;
- multi-tenant QoS state rides ``lumen-qos-status`` (per-admission-queue
  occupancy + brownout level, per-tenant quota admit/shed totals) so an
  operator sees "tenant X is being browned out" from a Health probe, and
  each ``StreamCapabilities`` record carries ``extra["qos"]``;
- SLO burn state rides ``lumen-slo-status`` (per-task breach/ok + 5m/1h
  error-budget burn rates from ``utils/telemetry.py``) — a Health probe
  is also the lazy SLO evaluation tick, so breach counters and incident
  bundles fire within one probe of the window turning bad.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import sys
import threading
import time
from typing import Iterable, Iterator

import grpc
from google.protobuf import empty_pb2

from ..utils import trace as request_trace
from ..utils.metrics import metrics
from .base_service import BaseService, _Assembly
from .proto import ml_service_pb2 as pb
from .proto.ml_service_pb2_grpc import InferenceServicer

logger = logging.getLogger(__name__)

#: Reserved task name of the federation cache-lookup RPC. Answered HERE —
#: before routing, before the drain gate, before any admission accounting —
#: because a cache read is a cheap read-only probe that must keep working
#: on a draining peer and costs O(1) on the owner. Payload = the exact
#: result-cache key (UTF-8); response meta ``fed_cache`` = ``hit``/``miss``
#: with the pickle blob as the result on a hit. The client half lives in
#: :mod:`lumen_tpu.runtime.federation`.
FED_CACHE_TASK = "fed_cache_lookup"

#: cap one cache-lookup answer under the gRPC message limit (with
#: protobuf headroom); larger entries answer miss and the requester
#: computes — correctness first, the dedupe win is for typical results.
_FED_CACHE_MAX_BLOB = 48 * 1024 * 1024

#: hard cap on how long the OWNER parks a handler thread riding its own
#: in-flight computation for a cache lookup (the requester asks via
#: ``wait_ms``; the effective wait is further clamped to the lookup
#: RPC's own remaining deadline — a waiter whose caller is gone must not
#: keep a thread). Re-exported by :mod:`lumen_tpu.runtime.federation`.
FED_CACHE_MAX_WAIT_S = 30.0


#: Reserved task name of the KV page-migration RPC (disaggregated
#: prefill/decode): a prefill-lane host ships a freshly prefilled row's
#: KV pages + exact decode state to its decode-lane owner, which decodes
#: with ZERO re-prefill and streams the tokens back on the same RPC.
#: Answered like :data:`FED_CACHE_TASK` (before the route table — the
#: task is reserved, never registered) but BEHIND the drain gate:
#: accepting a row to decode is real admission. Server half:
#: :meth:`HubRouter._answer_kv_put` (sink = the VLM service's
#: ``handle_kv_put``); client half:
#: ``lumen_tpu.runtime.federation.FederationManager.kv_migrate``.
FED_KV_PUT_TASK = "fed_kv_put"

#: env knob selecting this host's lane in a disaggregated fleet.
ROLE_ENV = "LUMEN_FED_ROLE"

#: gRPC metadata key a host's lane rides on Health TRAILING metadata —
#: peers learn each other's roles passively from the probe they already
#: run, no new RPC. Absent = unconfigured = serves both lanes.
FED_ROLE_META = "lumen-fed-role"

FED_ROLES = ("prefill", "decode", "both")

#: env knob opting a fleet into capacity gossip: when "1", each host's
#: Health trailing metadata carries a compact capacity report (duty
#: fraction, worst SLO burn, drain flag) and the federation front scales
#: ring weights from it. Unset keeps the Health payload — and the ring —
#: byte-identical to pre-capacity builds.
FED_CAPACITY_ENV = "LUMEN_FED_CAPACITY"

#: gRPC metadata key the capacity report rides on Health TRAILING
#: metadata — same passive channel as :data:`FED_ROLE_META`: peers learn
#: each other's headroom from the probe they already run, no new RPC.
FED_CAPACITY_META = "lumen-fed-capacity"

#: Search tasks the federation FRONT fans out SHARD-WISE instead of
#: routing to a single content-address owner: ANN shard placement keys
#: the hash ring per ``ann/{tenant}/{shard}`` (data placement — a query
#: must visit EVERY shard owner, an upsert batch is partitioned by the
#: same placement function), so the ordinary payload-digest routing
#: would send a query to one random peer holding one fraction of the
#: index. String literals on purpose: the canonical definitions live in
#: :mod:`.services.search_service`, whose import drags numpy and the
#: batcher machinery this router deliberately stays free of — and the
#: task names are wire protocol either way.
FED_SEARCH_QUERY_TASK = "search_query"
FED_SEARCH_UPSERT_TASK = "search_upsert"
FED_SEARCH_TASKS = (FED_SEARCH_QUERY_TASK, FED_SEARCH_UPSERT_TASK)

#: chunk size for front-built shard sub-requests (same 1 MiB the client
#: uses — comfortably under any gRPC frame limit).
_FED_SEARCH_CHUNK = 1 << 20

_ROLE_WARNED = False


def capacity_gossip_enabled() -> bool:
    """Whether this process participates in capacity gossip (report on
    the server side, weighted ring + drain handoff on the front). Read
    fresh on each call — it gates per-probe work, not a latched
    structure."""
    return os.environ.get(FED_CAPACITY_ENV, "") == "1"


def advertised_fed_role() -> str | None:
    """This host's ``LUMEN_FED_ROLE`` lane, or None when unset. None
    advertises nothing — an unconfigured host's Health payload (and
    every request path) stays byte-identical to pre-role builds. A
    malformed value warns once and behaves as unset: serve both lanes,
    degrade rather than crash."""
    raw = (os.environ.get(ROLE_ENV) or "").strip().lower()
    if not raw:
        return None
    if raw not in FED_ROLES:
        global _ROLE_WARNED
        if not _ROLE_WARNED:
            _ROLE_WARNED = True
            logger.warning(
                "%s=%r is not one of %s; serving both lanes",
                ROLE_ENV, raw, FED_ROLES,
            )
        return None
    return raw


def _fed_wait_slots() -> threading.Semaphore:
    """Process-wide cap on CONCURRENTLY-PARKED cache-lookup waits — the
    per-RPC deadline clamp bounds each wait, this bounds the aggregate:
    with the default 10-thread gRPC pool, a handful of slow flights each
    attracting one waiting lookup per non-owner peer could otherwise park
    every handler thread and starve this host's own Health probes into a
    fleet-wide ejection. Over the cap, lookups degrade to an immediate
    peek (miss if not cached) — the requester computes, which is always
    correct. Sized to half the handler pool, floor 1."""
    global _FED_WAIT_SLOTS
    if _FED_WAIT_SLOTS is None:
        from ..utils.env import env_int

        workers = env_int("LUMEN_GRPC_WORKERS", 10, minimum=1)
        _FED_WAIT_SLOTS = threading.Semaphore(max(1, workers // 2))
    return _FED_WAIT_SLOTS


_FED_WAIT_SLOTS: threading.Semaphore | None = None


class HubRouter(InferenceServicer):
    #: Fleet view (:class:`~lumen_tpu.runtime.federation.FederationManager`)
    #: attached by the server on peer-aware boots; None (the default and
    #: the only state when ``LUMEN_FED_PEERS`` is unset) keeps every
    #: request path byte-identical to single-host.
    federation = None

    #: KV-migration sink (the VLM service's ``handle_kv_put``), attached
    #: by the server on decode-capable boots; None answers the reserved
    #: ``fed_kv_put`` task with a typed in-band refusal, and the prefill
    #: host decodes the row locally — a refusal never loses work.
    kv_migration = None

    def __init__(self, services: dict[str, BaseService]):
        self.services = dict(services)
        self._lock = threading.Lock()
        self._route_table: dict[str, BaseService] = {}
        # Graceful-drain gate: once set, new Infer streams answer
        # UNAVAILABLE with a retry-after hint while queued/in-flight work
        # completes (see ServerHandle.drain_and_stop). _active_streams
        # counts forwarded Infer streams so the drain knows when the last
        # one finished — gRPC itself does not expose this.
        self._draining = False
        self._drain_retry_ms = "1000"
        self._active_streams = 0
        # Capacity-gossip observation timestamps (monotonic; 0.0 = never):
        # when a Health probe last carried our capacity report, and when
        # one carried it with the draining flag SET. The drain sequencer
        # reads these to hold teardown until a watching front has actually
        # seen the flag — without a watcher, shutdown is unchanged.
        self._capacity_probe_t = 0.0
        self._drain_announced_t = 0.0
        self._rebuild_routes()

    def begin_drain(self, retry_after_s: float = 1.0) -> None:
        """Stop admitting new RPCs: every subsequent Infer stream answers
        UNAVAILABLE carrying ``lumen-retry-after-ms`` (sized to the drain
        budget — by then this process is gone and the client's next
        attempt lands on a live sibling). In-flight streams are untouched;
        the gRPC server's grace period drains them."""
        from ..utils.qos import retry_after_ms

        self._drain_retry_ms = retry_after_ms(max(retry_after_s, 0.001))
        self._draining = True
        logger.info(
            "drain: refusing new RPCs (retry-after %sms)", self._drain_retry_ms
        )

    @property
    def draining(self) -> bool:
        return self._draining

    def capacity_probe_age(self) -> float | None:
        """Seconds since a Health probe last carried this host's capacity
        report (None = never, i.e. gossip off or nobody watching)."""
        if self._capacity_probe_t <= 0.0:
            return None
        return max(0.0, time.monotonic() - self._capacity_probe_t)

    def drain_announced(self) -> bool:
        """Whether a capacity report with the draining flag SET has been
        served since :meth:`begin_drain` — i.e. a watching front has had
        the chance to re-weight us to zero and start the hot-key handoff
        instead of discovering the shutdown through failover."""
        return self._drain_announced_t > 0.0

    def active_streams(self) -> int:
        """Forwarded Infer streams currently executing — the drain's
        "is the house empty yet" probe."""
        with self._lock:
            return self._active_streams

    def _rebuild_routes(self) -> None:
        table: dict[str, BaseService] = {}
        owner: dict[str, str] = {}
        for name, svc in self.services.items():
            for task in svc.registry.task_names():
                if task in table:
                    raise ValueError(
                        f"task {task!r} registered by multiple services "
                        f"(first: {owner[task]!r}, second: {name!r})"
                    )
                table[task] = svc
                owner[task] = name
        self._route_table = table
        logger.info(
            "hub routing table: %s",
            {t: s.registry.service_name for t, s in table.items()},
        )

    def replace_service(self, name: str, svc: BaseService) -> None:
        """Atomically swap a child service (degraded -> recovered) and
        rebuild the route table. The old service's in-flight streams keep
        their reference; new streams route to the replacement. A duplicate
        task in the replacement rolls the swap back."""
        with self._lock:
            old = self.services.get(name)
            self.services[name] = svc
            try:
                self._rebuild_routes()
            except ValueError:
                if old is None:
                    self.services.pop(name, None)
                else:
                    self.services[name] = old
                self._rebuild_routes()
                raise
        # Hot-swap cache invalidation: result-cache namespaces lead with
        # the service family name, so dropping the prefix guarantees the
        # swapped-in model never serves a predecessor's cached results —
        # even if id+revision happen to match (e.g. same model re-loaded
        # after a recovery). Lazy import: the router must stay importable
        # without the jax-importing runtime package.
        from ..runtime.result_cache import invalidate_namespace

        # Prefix = the service FAMILY (registry name: "clip"/"face"/...),
        # which is what the managers key their namespaces with; the router
        # key is a config alias that may differ. Ingest records embed
        # model ids mid-namespace where a prefix can't reach them, so any
        # hot-swap drops the whole (rebuildable) ingest cache too — swaps
        # are rare, stale whole-photo records are not worth the risk.
        prefixes = {getattr(svc.registry, "service_name", name), name, "ingest"}

        def sweep() -> int:
            return sum(invalidate_namespace(f"{p}/") for p in prefixes)

        dropped = sweep()
        close = getattr(old, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - best-effort teardown of the placeholder
                logger.exception("closing replaced service %r failed", name)
        # Sweep AGAIN after the old service is closed: a request that
        # entered the old instance after the first sweep captured a
        # post-invalidation fence, so the store-side fence cannot reject
        # it — but it completed before close() finished, so this second
        # sweep removes it. Anything starting later hits the old
        # instance's closed batchers and produces nothing to cache.
        dropped += sweep()
        if dropped:
            logger.info(
                "hot-swap of %r invalidated %d cached result(s)", name, dropped
            )

    def _drain_response(self, first: pb.InferRequest) -> pb.InferResponse:
        """The drain-gate refusal: in-band UNAVAILABLE with a parseable
        retry hint. ONE definition — the hub and the federation front
        tier must never drift on the drain contract."""
        from ..utils.qos import RETRY_AFTER_META

        return pb.InferResponse(
            correlation_id=first.correlation_id,
            is_final=True,
            meta={RETRY_AFTER_META: self._drain_retry_ms},
            error=pb.Error(
                code=pb.ERROR_CODE_UNAVAILABLE,
                message="server is draining for shutdown",
                detail=(
                    "graceful drain in progress; retry with backoff "
                    "(lumen-retry-after-ms) against another replica"
                ),
            ),
        )

    def _route(self, task: str) -> BaseService | None:
        with self._lock:
            return self._route_table.get(task)

    def _statuses(self) -> dict[str, str]:
        with self._lock:
            return {name: svc.status() for name, svc in sorted(self.services.items())}

    def attach_to_server(self, server: grpc.Server) -> None:
        from .proto.ml_service_pb2_grpc import add_InferenceServicer_to_server

        add_InferenceServicer_to_server(self, server)

    # -- rpcs -------------------------------------------------------------

    def _answer_cache_lookup(
        self, first: pb.InferRequest, context=None
    ) -> pb.InferResponse:
        """Server half of the federation cache-lookup protocol: probe the
        local result cache (and, with a ``wait_ms`` meta, ride a live
        single-flight) for the requested key. Reads the cache module via
        ``sys.modules`` — a process that never loaded the runtime package
        (jax-free echo deployments, the front tier itself) answers miss
        without importing anything.

        A ``meta["op"] == "put"`` request is the drain-handoff WRITE half
        (the front pushing a draining peer's hot entry onto a ring
        successor): the payload is the pickle blob, ``meta["key"]`` the
        cache key. Gated on the same capacity-gossip knob that produces
        the pushes — a host outside the gossip ignores stray writes."""
        mod = sys.modules.get("lumen_tpu.runtime.result_cache")
        if first.meta.get("op") == "put":
            stored = False
            if mod is not None and capacity_gossip_enabled():
                try:
                    stored = bool(
                        mod.peer_import(
                            first.meta.get("key", ""), bytes(first.payload)
                        )
                    )
                except Exception:  # noqa: BLE001 - a bad blob must never 500 the peer
                    logger.exception("federation cache import failed")
            return pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                meta={"fed_cache": "stored" if stored else "ignored"},
            )
        blob = None
        if mod is not None:
            try:
                wait_ms = int(first.meta.get("wait_ms", "0") or "0")
            except ValueError:
                wait_ms = 0
            wait_s = min(max(wait_ms, 0) / 1000.0, FED_CACHE_MAX_WAIT_S)
            # Never wait past the lookup RPC's own deadline: once the
            # requester's call has expired, riding the flight further
            # only parks this handler thread for nobody (handler-pool
            # exhaustion on the owner is how a HEALTHY host gets its
            # Health probes starved and ejected).
            rem_fn = getattr(context, "time_remaining", None)
            if callable(rem_fn):
                try:
                    rem = rem_fn()
                except Exception:  # noqa: BLE001 - stub contexts
                    rem = None
                if rem is not None:
                    wait_s = max(0.0, min(wait_s, rem - 0.1))
            key = bytes(first.payload).decode("utf-8", "replace")
            slots = _fed_wait_slots()
            parked = wait_s > 0 and slots.acquire(blocking=False)
            if wait_s > 0 and not parked:
                wait_s = 0.0  # wait budget spent: peek-only, never park
            try:
                blob = mod.peer_export(key, wait_s=wait_s)
            except Exception:  # noqa: BLE001 - a lookup must never 500 the peer
                logger.exception("federation cache export failed")
                blob = None
            finally:
                if parked:
                    slots.release()
        if blob is None or len(blob) > _FED_CACHE_MAX_BLOB:
            return pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                meta={"fed_cache": "miss"},
            )
        return pb.InferResponse(
            correlation_id=first.correlation_id,
            is_final=True,
            result=blob,
            result_mime="application/x-python-pickle",
            meta={"fed_cache": "hit"},
            total=1,
        )

    def _answer_kv_put(
        self, first: pb.InferRequest, request_iterator, context
    ) -> Iterator[pb.InferResponse]:
        """Server half of the KV page-migration protocol: delegate to the
        attached sink. Unlike the cache lookup this IS admission of real
        decode work, so the drain gate applies; every refusal is a typed
        in-band UNAVAILABLE — the prefill host treats ANY failure as
        "resume locally", so nothing here can lose a row."""
        if self._draining:
            yield self._drain_response(first)
            return
        sink = self.kv_migration
        if sink is None:
            yield pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                meta={"fed_kv": "refused"},
                error=pb.Error(
                    code=pb.ERROR_CODE_UNAVAILABLE,
                    message="this host accepts no KV migrations",
                    detail=(
                        "no continuous-batching VLM engine is attached "
                        "(front tier, modelless host, or non-continuous "
                        "scheduler); the prefill host decodes locally"
                    ),
                ),
            )
            return
        try:
            yield from sink.handle_kv_put(first, request_iterator, context)
        except Exception as e:  # noqa: BLE001 - a broken sink must answer in-band
            logger.exception("fed_kv_put sink failed")
            yield pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                meta={"fed_kv": "refused"},
                error=pb.Error(
                    code=pb.ERROR_CODE_INTERNAL,
                    message=f"fed_kv_put sink failed: {type(e).__name__}: {e}",
                ),
            )

    def Infer(self, request_iterator: Iterable[pb.InferRequest], context) -> Iterator[pb.InferResponse]:
        try:
            first = next(iter(request_iterator))
        except StopIteration:
            return
        if first.task == FED_CACHE_TASK:
            # Peer-cache protocol: answered before the drain gate and the
            # route table on purpose (read-only, O(1), and a draining or
            # modelless peer must still serve its cache).
            yield self._answer_cache_lookup(first, context)
            return
        if first.task == FED_KV_PUT_TASK:
            # KV-migration protocol: reserved like the cache lookup, but
            # the drain gate (inside) applies — this admits decode work.
            yield from self._answer_kv_put(first, request_iterator, context)
            return
        if self._draining:
            yield self._drain_response(first)
            return
        target = self._route(first.task)
        if target is None:
            degraded = {n: s for n, s in self._statuses().items() if s in ("degraded", "failed")}
            if degraded:
                # The task may belong to a service that failed to load and
                # could not even declare its tasks — answer "broken
                # backend", not "client bug".
                yield pb.InferResponse(
                    correlation_id=first.correlation_id,
                    is_final=True,
                    error=pb.Error(
                        code=pb.ERROR_CODE_UNAVAILABLE,
                        message=(
                            f"no healthy service handles task {first.task!r}; "
                            f"degraded services: {sorted(degraded)}"
                        ),
                        detail="recovery is retrying in the background; retry later",
                    ),
                )
                return
            yield pb.InferResponse(
                correlation_id=first.correlation_id,
                is_final=True,
                error=pb.Error(
                    code=pb.ERROR_CODE_INVALID_ARGUMENT,
                    message=f"no service handles task {first.task!r}",
                    detail=f"known tasks: {sorted(self._route_table)}",
                ),
            )
            return
        # Re-prepend the consumed first message; forward the stream as-is.
        # The active-stream count brackets the forward so a drain can tell
        # "in-flight work still running" from "house empty".
        with self._lock:
            self._active_streams += 1
        try:
            yield from target.Infer(itertools.chain([first], request_iterator), context)
        finally:
            with self._lock:
                self._active_streams -= 1

    def GetCapabilities(self, request, context) -> pb.Capability:
        # Aggregate: merge every child capability into one record (the
        # detailed per-service view is StreamCapabilities).
        agg = pb.Capability(
            service_name="hub",
            runtime="jax-tpu",
            protocol_version="1.0.0",
        )
        with self._lock:
            services = list(self.services.values())
        for svc in services:
            cap = svc.capability()
            agg.model_ids.extend(cap.model_ids)
            agg.tasks.extend(cap.tasks)
            for p in cap.precisions:
                if p not in agg.precisions:
                    agg.precisions.append(p)
            agg.max_concurrency = max(agg.max_concurrency, cap.max_concurrency)
        return agg

    def StreamCapabilities(self, request, context) -> Iterator[pb.Capability]:
        with self._lock:
            services = list(self.services.values())
        for svc in services:
            cap = svc.capability()
            breaker = getattr(svc, "breaker", None)
            if breaker is not None:
                # Live containment state rides the capability record so a
                # client refreshing capabilities sees "backend fast-failing"
                # without a failed Infer round-trip.
                cap.extra["breaker"] = breaker.state()
            yield cap

    def _breaker_states(self) -> dict[str, str]:
        with self._lock:
            services = list(self.services.items())
        return {
            name: breaker.state()
            for name, svc in services
            if (breaker := getattr(svc, "breaker", None)) is not None
        }

    def _replica_states(self) -> dict[str, dict]:
        """Per-service replica-fleet states ({service: {dispatcher:
        {replica: state}}}); services without a fleet report nothing.
        jax-free: the states come from the service objects, the router
        never touches the runtime package."""
        with self._lock:
            services = list(self.services.items())
        out: dict[str, dict] = {}
        for name, svc in services:
            try:
                states = svc.replica_states()
            except Exception:  # noqa: BLE001 - health must never fail on telemetry
                continue
            if states:
                out[name] = states
        return out

    @staticmethod
    def _qos_status() -> dict:
        """Live multi-tenant QoS state (jax-free — the implementation
        lives in ``utils.qos`` precisely so this router can read it on
        jax-free deployments). ``{}`` omits the key entirely."""
        from ..utils import qos

        try:
            return qos.status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    @staticmethod
    def _slo_state() -> dict:
        """Evaluated SLO burn state per task (jax-free — the engine lives
        in ``utils.telemetry``). ``{}`` (no objectives configured, or no
        traffic) omits the key entirely. Evaluating here is what makes a
        Health probe flip ``lumen-slo-status`` within one window: the
        engine is lazy, and Health is the operator's poll."""
        from ..utils import telemetry

        try:
            return telemetry.slo_status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    @staticmethod
    def _autopilot_state() -> dict:
        """Compact autopilot state WITHOUT importing the runtime package
        (jax — same rule as the quarantine probe): only report when the
        controller module is already loaded in-process. ``{}`` omits the
        key."""
        mod = sys.modules.get("lumen_tpu.runtime.autopilot")
        if mod is None:
            return {}
        try:
            return mod.health_status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    def _capacity_status(self) -> dict:
        """Compact capacity report for the ``lumen-fed-capacity``
        trailing-metadata key: duty fraction (busiest device meter over
        the last 30s), worst per-task 5m SLO burn, and the drain flag —
        the three signals the front's weighted ring is built from. While
        draining, the hottest result-cache keys ride along so successors
        can prefetch them before failover would discover the drain.
        ``{}`` (knob off, or nothing to report) omits the key entirely —
        the unconfigured Health payload stays byte-identical."""
        if not capacity_gossip_enabled():
            return {}
        from ..utils import telemetry

        cap: dict = {"draining": 1 if self._draining else 0}
        try:
            duty = telemetry.device_duty(30.0)
            if duty is not None:
                cap["duty"] = round(duty, 4)
            slo = telemetry.slo_status()
            if slo:
                burns = [
                    s.get("burn_5m")
                    for s in slo.values()
                    if isinstance(s, dict) and s.get("burn_5m") is not None
                ]
                if burns:
                    cap["burn_5m"] = round(max(burns), 3)
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            pass
        if self._draining:
            # Hot-key manifest for the drain handoff: the front fetches
            # these via the ordinary peer-cache path and pushes them onto
            # ring successors. sys.modules read — a jax-free front never
            # imports the runtime package for this.
            mod = sys.modules.get("lumen_tpu.runtime.result_cache")
            if mod is not None:
                try:
                    cap["hot"] = mod.hot_keys(8)
                except Exception:  # noqa: BLE001 - telemetry only
                    pass
        return cap

    def _fed_status(self) -> dict:
        """Per-peer federation state for the ``lumen-fed-status``
        trailing-metadata key. ``{}`` (no fleet attached) omits the key —
        single-host Health payloads stay byte-identical."""
        fed = self.federation
        if fed is None:
            return {}
        try:
            return fed.health_status()
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return {}

    @staticmethod
    def _quarantine_size() -> int | None:
        """Entries currently quarantined, WITHOUT importing the runtime
        package (which drags in jax — this router must stay importable and
        health-checkable on jax-free deployments like the echo service):
        only report when the runtime is already loaded in-process."""
        mod = sys.modules.get("lumen_tpu.runtime.quarantine")
        if mod is None:
            return None
        try:
            return len(mod.get_quarantine())
        except Exception:  # noqa: BLE001 - health must never fail on telemetry
            return None

    def Health(self, request, context):
        statuses = self._statuses()
        if context is not None:
            try:
                trailing = [("lumen-service-status", json.dumps(statuses))]
                breakers = self._breaker_states()
                if breakers:
                    trailing.append(("lumen-breaker-status", json.dumps(breakers)))
                quarantined = self._quarantine_size()
                if quarantined is not None:
                    trailing.append(("lumen-quarantine-size", str(quarantined)))
                replicas = self._replica_states()
                if replicas:
                    # Per-replica fleet health next to the breaker/
                    # quarantine keys: a DOWN replica is a reported
                    # condition (siblings keep the hub SERVING), exactly
                    # like a degraded sibling service.
                    trailing.append(("lumen-replica-status", json.dumps(replicas)))
                slo_state = self._slo_state()
                if slo_state:
                    # SLO burn next to the containment keys: a breaching
                    # task is a reported condition (clients may back off
                    # bulk traffic), not an outage — the hub still serves.
                    trailing.append(("lumen-slo-status", json.dumps(slo_state)))
                qos_state = self._qos_status()
                if qos_state:
                    # Multi-tenant QoS next to the containment keys:
                    # per-admission-queue occupancy/brownout and the
                    # quota gate's per-tenant admit/shed totals — a
                    # browned-out bulk lane is a reported condition, not
                    # an outage.
                    trailing.append(("lumen-qos-status", json.dumps(qos_state)))
                fed_state = self._fed_status()
                if fed_state:
                    # Fleet view next to the containment keys: an ejected
                    # peer is a reported condition (its ring segment
                    # spilled to successors), not an outage of THIS host.
                    trailing.append(("lumen-fed-status", json.dumps(fed_state)))
                role = advertised_fed_role()
                if role:
                    # Disaggregation lane: peers learn it from the Health
                    # probe they already run. Unset advertises nothing —
                    # the unconfigured payload stays byte-identical.
                    trailing.append((FED_ROLE_META, role))
                ap_state = self._autopilot_state()
                if ap_state:
                    # Whether the capacity controller is live, which loops
                    # it holds, and its last actuation — so "who parked
                    # that replica / forced that rung" is answerable from
                    # a Health probe.
                    trailing.append(("lumen-autopilot-status", json.dumps(ap_state)))
                cap = self._capacity_status()
                if cap:
                    # Capacity gossip: duty/burn/drain ride the probe the
                    # federation poll thread already runs — the front
                    # scales ring weights from this, no new RPC.
                    trailing.append((FED_CAPACITY_META, json.dumps(cap)))
                context.set_trailing_metadata(tuple(trailing))
                if cap:
                    # Stamp AFTER the metadata is attached: these feed the
                    # drain sequencer's "has a watcher seen the flag yet"
                    # hold, so they must mean served, not merely built.
                    self._capacity_probe_t = time.monotonic()
                    if cap.get("draining"):
                        self._drain_announced_t = time.monotonic()
            except Exception:  # noqa: BLE001 - test stubs may lack metadata support
                pass
        unhealthy = [n for n, s in statuses.items() if s == "unhealthy"]
        broken = [n for n, s in statuses.items() if s != "healthy"]
        if unhealthy:
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"service(s) unhealthy: {sorted(unhealthy)}",
            )
        if statuses and len(broken) == len(statuses):
            # Nothing left serving: a hub of only degraded placeholders is
            # not healthy, however gracefully it boots.
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"all services degraded: {sorted(broken)}",
            )
        return empty_pb2.Empty()


class FederationRouter(HubRouter):
    """Front tier: a lumen-tpu server that owns NO models and routes every
    Infer stream over N peer servers speaking the unchanged gRPC protocol
    (so a front tier can itself be fronted — tiers compose).

    Routing is consistent-hash by the request payload's sha256 — the same
    content address the result cache keys on — so identical payloads
    always land on the same peer and its cache concentrates the hits.
    Empty-payload tasks (vlm generate: the prompt rides in request meta)
    fold the first message's meta into the key instead, so a meta-borne
    workload still spreads across the ring.
    Per-request resilience: the hop budget (``LUMEN_FED_HOPS``) walks the
    ring owner's live successors on a transport failure (peer dead —
    feeds the ejection streak) or an in-band UNAVAILABLE shed (peer alive
    but refusing — neutral, the request just spills); when every hop is
    exhausted the LAST peer's answer is relayed verbatim so the
    ``lumen-retry-after-ms`` hint survives the front-tier hop (and is
    echoed as trailing metadata for clients that only read that).

    The request stream is buffered before the first forward: failover
    must be able to replay it, and replay is only safe while no response
    byte has been seen (the same contract the client's own stream-setup
    retry keeps). After the first forwarded response reaches the client,
    failures propagate — blind re-dispatch could double-run a task.
    """

    def __init__(self, federation):
        super().__init__({})
        self.federation = federation

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _forward_metadata(context) -> tuple | None:
        """Propagate every ``lumen-*`` request-metadata pair (tenant id,
        trace id) to the chosen peer — QoS identity and trace stitching
        must survive the hop."""
        md = getattr(context, "invocation_metadata", None)
        if not callable(md):
            return None
        out: list[tuple[str, str]] = []
        try:
            for item in md() or ():
                key = getattr(item, "key", None)
                value = getattr(item, "value", None)
                if key is None and isinstance(item, (tuple, list)) and len(item) == 2:
                    key, value = item
                if key and str(key).startswith("lumen-"):
                    out.append((str(key), str(value)))
        except Exception:  # noqa: BLE001 - metadata must never break routing
            return None
        return tuple(out) or None

    def _forward_timeout(self, context) -> float:
        """Deadline for one peer forward: the caller's own remaining
        budget when it set one, else the fleet default. Clamp: a
        no-deadline client surfaces as a HUGE ``time_remaining()`` on
        some gRPC stacks, and that number fed raw into the forward's
        deadline overflows C time — the call dies instantly instead of
        never (same trap the result cache's flight wait hit)."""
        timeout = None
        tr_fn = getattr(context, "time_remaining", None)
        if callable(tr_fn):
            try:
                timeout = tr_fn()
            except Exception:  # noqa: BLE001 - stub contexts
                timeout = None
        if timeout is None or timeout <= 0:
            timeout = self.federation.forward_timeout_s
        return min(timeout, 86400.0)

    @staticmethod
    def _reroutable_shed(resp: pb.InferResponse) -> bool:
        """An in-band UNAVAILABLE as the FIRST response: the peer refused
        before dispatch (drain, breaker, quota, queue shed) and said so
        parseably — re-sending elsewhere is explicitly safe."""
        return bool(
            resp.HasField("error")
            and resp.error.code == pb.ERROR_CODE_UNAVAILABLE
        )

    def _relay_exhausted(
        self, context, cid: str, last_shed: pb.InferResponse | None, tried: int
    ) -> pb.InferResponse:
        """Every hop failed: relay the last in-band answer verbatim (its
        response meta — retry hint included — is the peer's own words),
        echoing the hint into trailing metadata so it survives for
        clients that only read the RPC trailer."""
        from ..utils.qos import RETRY_AFTER_META, retry_after_ms

        metrics.count("fed_exhausted")
        if last_shed is not None:
            hint = last_shed.meta.get(RETRY_AFTER_META, "")
        else:
            hint = ""
        if not hint:
            hint = retry_after_ms(1.0)
        if context is not None:
            try:
                context.set_trailing_metadata(((RETRY_AFTER_META, hint),))
            except Exception:  # noqa: BLE001 - stubs may lack metadata support
                pass
        if last_shed is not None:
            return last_shed
        return pb.InferResponse(
            correlation_id=cid,
            is_final=True,
            meta={RETRY_AFTER_META: hint},
            error=pb.Error(
                code=pb.ERROR_CODE_UNAVAILABLE,
                message=f"all {tried} federation peer(s) unavailable",
                detail=(
                    "front tier exhausted its hop budget; retry with "
                    "backoff (lumen-retry-after-ms)"
                ),
            ),
        )

    # -- rpcs --------------------------------------------------------------

    def Infer(self, request_iterator: Iterable[pb.InferRequest], context) -> Iterator[pb.InferResponse]:
        try:
            first = next(iter(request_iterator))
        except StopIteration:
            return
        if first.task == FED_CACHE_TASK:
            # A cache lookup must NEVER be consistent-hash-forwarded: the
            # ring is keyed on the original payload's digest, not on the
            # key STRING this request carries, so a forward would land on
            # a random peer and park its handler for nothing. A front
            # tier owns no cache — answer miss honestly, right here.
            yield self._answer_cache_lookup(first, context)
            return
        if first.task == FED_KV_PUT_TASK:
            # A migration targets a SPECIFIC decode host, not a content
            # address — consistent-hashing the page payload to a random
            # peer would be wrong. A front tier never has a sink attached,
            # so this answers the typed in-band refusal.
            yield from self._answer_kv_put(first, request_iterator, context)
            return
        if self._draining:
            yield self._drain_response(first)
            return
        forward = (
            self._search_fanout
            if first.task in FED_SEARCH_TASKS
            else self._route_and_forward
        )
        tr = None
        if request_trace.enabled():
            tr = request_trace.begin_request(
                f"fed:{first.task}",
                trace_id=BaseService._trace_id_from(context),
            )
        if tr is None:
            yield from forward(first, request_iterator, context, None)
            return
        token = request_trace.activate(tr)
        try:
            for resp in forward(first, request_iterator, context, tr):
                if resp.HasField("error"):
                    tr.set_error(resp.error.message or "error")
                yield resp
        except BaseException as e:
            tr.set_error(f"{type(e).__name__}: {e}")
            raise
        finally:
            request_trace.deactivate(token)
            request_trace.finish_request(tr)

    def _route_and_forward(
        self, first: pb.InferRequest, request_iterator, context, tr
    ) -> Iterator[pb.InferResponse]:
        fed = self.federation
        # Buffer the whole request stream: the ring key needs the full
        # payload (chunked uploads), and failover needs an exact replay.
        msgs: list[pb.InferRequest] = [first]
        asm = _Assembly()
        asm.add(first)
        for req in request_iterator:
            msgs.append(req)
            if not asm.complete and req.correlation_id == first.correlation_id:
                asm.add(req)
        rspan = tr.begin("fed.route") if tr is not None else None
        body = asm.payload()
        h = hashlib.sha256(body)
        if not body:
            # Meta-borne tasks (vlm generate: the prompt rides in request
            # meta over an empty payload) would otherwise all collapse to
            # sha256(b"") — one ring owner for the whole workload and, in
            # a role-tagged fleet, one decode owner for every migrated
            # row. Fold the first message's meta in so content spreads;
            # payload-bearing tasks keep their exact digests.
            for k in sorted(first.meta):
                h.update(k.encode())
                h.update(b"\x00")
                h.update(first.meta[k].encode())
                h.update(b"\x00")
        digest = h.hexdigest()
        plan = fed.plan(digest)
        # Disaggregation rewrite: for generation tasks in a role-tagged
        # fleet, prefill-capable peers lead the plan and the first
        # decode-capable peer in ring order OWNS the decode — the chosen
        # prefill host migrates the row's KV there. Identity (plan, None)
        # whenever roles are unconfigured or the task has no phase split.
        decode_owner = None
        if plan:
            plan, decode_owner = fed.disagg_plan(first.task, plan)
        if rspan is not None:
            rattrs = {
                "owner": plan[0].name if plan else "none",
                "candidates": str(len(plan)),
            }
            if decode_owner:
                rattrs["decode_owner"] = decode_owner
            rspan.end(**rattrs)
        if not plan:
            yield self._relay_exhausted(context, first.correlation_id, None, 0)
            return
        timeout = self._forward_timeout(context)
        md = self._forward_metadata(context)
        kwargs = {"timeout": timeout} if md is None else {
            "timeout": timeout, "metadata": md,
        }
        with self._lock:
            self._active_streams += 1
        try:
            last_shed = None
            for attempt, peer in enumerate(plan):
                fed.record_dispatch(peer, failover=attempt > 0)
                fspan = (
                    tr.begin("fed.forward", {"peer": peer.name, "hop": str(attempt)})
                    if tr is not None
                    else None
                )
                fkw = kwargs
                if decode_owner is not None and peer.name != decode_owner:
                    # Pin the row's decode to the ring-chosen owner; the
                    # prefill host migrates the KV there after prefill.
                    # Omitted when the forward target IS the owner (or on
                    # the owner itself after failover) — decode locally.
                    from ..utils.disagg import DECODE_OWNER_META

                    fkw = dict(kwargs)
                    fkw["metadata"] = (md or ()) + (
                        (DECODE_OWNER_META, decode_owner),
                    )
                got_any = False
                shed = None
                try:
                    for resp in peer.stub.Infer(iter(msgs), **fkw):
                        if not got_any and self._reroutable_shed(resp):
                            shed = resp
                            break
                        got_any = True
                        yield resp
                except grpc.RpcError as e:
                    code = e.code() if callable(getattr(e, "code", None)) else None
                    # Only transport-unreachable feeds the ejection
                    # streak; DEADLINE_EXCEEDED/CANCELLED describe the
                    # CLIENT's budget or patience, and failing over on
                    # them would burn hops a dead client can't use.
                    unreachable = fed.record_unreachable(peer, e, "forward")
                    if fspan is not None:
                        fspan.end(error=str(code or type(e).__name__))
                    if got_any or not unreachable:
                        # Bytes already forwarded (replay unsafe), or the
                        # client itself gave up — propagate the break.
                        raise
                    continue
                if fspan is not None:
                    fspan.end(shed="1" if shed is not None else "0")
                if shed is not None:
                    fed.record_shed(peer)
                    last_shed = shed
                    continue
                fed.record_success(peer)
                return
            yield self._relay_exhausted(
                context, first.correlation_id, last_shed, len(plan)
            )
        finally:
            with self._lock:
                self._active_streams -= 1

    # -- sharded search fan-out --------------------------------------------

    def _search_fanout(
        self, first: pb.InferRequest, request_iterator, context, tr
    ) -> Iterator[pb.InferResponse]:
        """Front half of the sharded search path: buffer the request,
        resolve the tenant, and fan out to the ring owners of every
        ``ann/{tenant}/{shard}`` key — per-shard forwards run their own
        failover walk and the results merge HERE, so one dead shard
        owner degrades to its ring successor, never to a silently
        partial answer. Responses are collected (not streamed), which
        keeps replay safe for every shard hop: no byte reaches the
        client until all shards have answered."""
        fed = self.federation
        msgs: list[pb.InferRequest] = [first]
        asm = _Assembly()
        asm.add(first)
        for req in request_iterator:
            msgs.append(req)
            if not asm.complete and req.correlation_id == first.correlation_id:
                asm.add(req)
        # jax-free: runtime.ann defers its jax import past module level,
        # and the front only uses its placement/merge helpers.
        from ..runtime.ann import ann_shards
        from ..utils.qos import DEFAULT_TENANT, TENANT_META_KEY

        tenant = (
            first.meta.get("tenant")
            or BaseService._invocation_meta(context, TENANT_META_KEY)
            or DEFAULT_TENANT
        )
        n_shards = ann_shards()
        timeout = self._forward_timeout(context)
        md = self._forward_metadata(context)
        kwargs = {"timeout": timeout} if md is None else {
            "timeout": timeout, "metadata": md,
        }
        with self._lock:
            self._active_streams += 1
        try:
            if first.task == FED_SEARCH_UPSERT_TASK:
                yield from self._search_upsert_fanout(
                    first, asm, context, tr, tenant, n_shards, kwargs
                )
            else:
                yield from self._search_query_fanout(
                    first, msgs, context, tr, tenant, n_shards, kwargs
                )
        finally:
            with self._lock:
                self._active_streams -= 1

    def _search_query_fanout(
        self, first, msgs, context, tr, tenant, n_shards, kwargs
    ) -> Iterator[pb.InferResponse]:
        fed = self.federation
        cid = first.correlation_id
        metrics.count("fed_search_queries")

        def one_shard(shard: int):
            # Same payload (the query tensor forwards verbatim — a
            # fleet-internal hop never re-encodes), shard-pinned meta:
            # the owner answers ONLY from ann/{tenant}/{shard}.
            head = pb.InferRequest()
            head.CopyFrom(first)
            head.meta["shard"] = str(shard)
            head.meta["tenant"] = tenant
            key = hashlib.sha256(f"ann/{tenant}/{shard}".encode()).hexdigest()
            plan = fed.plan(key)
            span = (
                tr.begin("fed.search", {"shard": str(shard), "tenant": tenant})
                if tr is not None
                else None
            )
            got, peer, last_shed, tried = self._forward_collect(
                [head, *msgs[1:]], plan, kwargs
            )
            if span is not None:
                span.end(
                    owner=peer.name if peer is not None else "none",
                    hops=str(tried),
                    ok="1" if got is not None else "0",
                )
            return got, last_shed, tried

        parts: list[tuple[list, list]] = []
        last_shed = None
        total_tried = 0
        for got, shed, tried in self._fanout_run(one_shard, list(range(n_shards))):
            total_tried += tried
            if shed is not None:
                last_shed = shed
            if got is None:
                # One unreachable shard fails the WHOLE query: a quietly
                # partial top-k is a wrong answer, not a degraded one.
                yield self._relay_exhausted(context, cid, last_shed, total_tried)
                return
            final = got[-1]
            if final.HasField("error"):
                # The shard's own in-band error (bad k, bad vector...)
                # relays verbatim — its message is the ground truth.
                yield final
                return
            body = b"".join(bytes(r.result) for r in got)
            try:
                doc = json.loads(body.decode("utf-8"))
                parts.append((doc["ids"], doc["scores"]))
            except (ValueError, KeyError, UnicodeDecodeError) as e:
                yield pb.InferResponse(
                    correlation_id=cid,
                    is_final=True,
                    error=pb.Error(
                        code=pb.ERROR_CODE_INTERNAL,
                        message=f"shard returned a malformed search body: {e}",
                    ),
                )
                return
        from ..runtime.ann import merge_topk

        try:
            k = max(1, int(first.meta.get("k", "10") or "10"))
        except ValueError:
            k = 10  # the shards validated k; unreachable in practice
        ids, scores = merge_topk(parts, k)
        out = {
            "ids": ids,
            "scores": scores,
            "k": k,
            "shards": n_shards,
            "tenant": tenant,
        }
        yield pb.InferResponse(
            correlation_id=cid,
            is_final=True,
            result=json.dumps(out).encode(),
            result_mime="application/json",
            total=1,
        )

    def _search_upsert_fanout(
        self, first, asm, context, tr, tenant, n_shards, kwargs
    ) -> Iterator[pb.InferResponse]:
        import numpy as np

        from ..runtime.ann import shard_of
        from ..utils.tensorwire import BUNDLE_MIME, pack_bundle, unpack_bundle

        fed = self.federation
        cid = first.correlation_id
        payload = asm.payload()
        try:
            if asm.payload_mime == BUNDLE_MIME:
                tensors = unpack_bundle(payload)
                if len(tensors) != 2:
                    raise ValueError(
                        f"upsert bundle must hold [vectors, ids_json], "
                        f"got {len(tensors)} tensors"
                    )
                vecs = np.asarray(tensors[0], np.float32)
                ids = json.loads(
                    bytes(np.asarray(tensors[1], np.uint8)).decode("utf-8")
                )
            else:
                doc = json.loads(payload.decode("utf-8"))
                ids = doc["ids"]
                vecs = np.asarray(doc["vectors"], np.float32)
            if (
                not isinstance(ids, list)
                or not all(isinstance(i, str) for i in ids)
                or vecs.ndim != 2
                or len(ids) != vecs.shape[0]
                or not ids
            ):
                raise ValueError(
                    f"{len(ids) if isinstance(ids, list) else '?'} string ids "
                    f"over vectors {vecs.shape}"
                )
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            # The front must parse to PARTITION, so malformed batches
            # answer here — same contract the shard host would apply.
            yield pb.InferResponse(
                correlation_id=cid,
                is_final=True,
                error=pb.Error(
                    code=pb.ERROR_CODE_INVALID_ARGUMENT,
                    message=f"upsert batch did not parse: {type(e).__name__}: {e}",
                    detail=(
                        "expected tensor/bundle [vectors, ids_json] or "
                        "JSON {'ids': [...], 'vectors': [[...]]}"
                    ),
                ),
            )
            return
        metrics.count("fed_search_upserts")
        groups: dict[int, list[int]] = {}
        for row, vid in enumerate(ids):
            groups.setdefault(shard_of(vid, n_shards), []).append(row)

        def one_shard(item):
            shard, rows = item
            sub_ids = [ids[r] for r in rows]
            body = pack_bundle([
                np.ascontiguousarray(vecs[rows]),
                np.frombuffer(json.dumps(sub_ids).encode("utf-8"), np.uint8),
            ])
            meta = dict(first.meta)
            meta["shard"] = str(shard)
            meta["tenant"] = tenant
            shard_msgs = list(
                self._search_msgs(first.task, cid, bytes(body), BUNDLE_MIME, meta)
            )
            key = hashlib.sha256(f"ann/{tenant}/{shard}".encode()).hexdigest()
            plan = fed.plan(key)
            span = (
                tr.begin(
                    "fed.search",
                    {"shard": str(shard), "tenant": tenant, "rows": str(len(rows))},
                )
                if tr is not None
                else None
            )
            got, peer, last_shed, tried = self._forward_collect(
                shard_msgs, plan, kwargs
            )
            if span is not None:
                span.end(
                    owner=peer.name if peer is not None else "none",
                    hops=str(tried),
                    ok="1" if got is not None else "0",
                )
            return got, last_shed, tried

        added = updated = 0
        last_shed = None
        total_tried = 0
        items = sorted(groups.items())
        for got, shed, tried in self._fanout_run(one_shard, items):
            total_tried += tried
            if shed is not None:
                last_shed = shed
            if got is None:
                # Partial-write honesty: some slices may have landed, but
                # upserts are idempotent by id — the client retries the
                # whole batch and converges.
                yield self._relay_exhausted(context, cid, last_shed, total_tried)
                return
            final = got[-1]
            if final.HasField("error"):
                yield final
                return
            body = b"".join(bytes(r.result) for r in got)
            try:
                doc = json.loads(body.decode("utf-8"))
                added += int(doc.get("added", 0))
                updated += int(doc.get("updated", 0))
            except (ValueError, TypeError) as e:
                yield pb.InferResponse(
                    correlation_id=cid,
                    is_final=True,
                    error=pb.Error(
                        code=pb.ERROR_CODE_INTERNAL,
                        message=f"shard returned a malformed upsert body: {e}",
                    ),
                )
                return
        out = {
            "added": added,
            "updated": updated,
            "shards": len(items),
            "tenant": tenant,
        }
        yield pb.InferResponse(
            correlation_id=cid,
            is_final=True,
            result=json.dumps(out).encode(),
            result_mime="application/json",
            total=1,
        )

    def _forward_collect(self, msgs, plan, kwargs):
        """One shard's forward: walk the ring owner's live successors
        exactly like :meth:`_route_and_forward`, but COLLECT the response
        messages instead of streaming them. Returns ``(responses | None,
        serving_peer | None, last_shed, hops_tried)`` — ``None`` responses
        mean the plan is exhausted (empty plan included)."""
        fed = self.federation
        last_shed = None
        for attempt, peer in enumerate(plan):
            fed.record_dispatch(peer, failover=attempt > 0)
            got: list[pb.InferResponse] = []
            shed = None
            try:
                for resp in peer.stub.Infer(iter(msgs), **kwargs):
                    if not got and self._reroutable_shed(resp):
                        shed = resp
                        break
                    got.append(resp)
            except grpc.RpcError as e:
                if not fed.record_unreachable(peer, e, "search"):
                    # DEADLINE_EXCEEDED/CANCELLED describe the CLIENT's
                    # budget or patience — burning more hops serves a
                    # caller that is already gone. Replay stays safe
                    # (nothing was forwarded), but pointless.
                    raise
                continue
            if shed is not None:
                fed.record_shed(peer)
                last_shed = shed
                continue
            if not got:
                # A peer that half-answered an empty stream is broken in
                # a way record_unreachable never saw; try the successor.
                continue
            fed.record_success(peer)
            return got, peer, last_shed, attempt + 1
        return None, None, last_shed, len(plan)

    def _fanout_run(self, fn, items: list) -> list:
        """Run ``fn(item)`` for every item CONCURRENTLY (the per-shard
        forwards are network-bound; serial fan-out would multiply query
        latency by the shard count) and return results in item order.
        A worker exception propagates — same surface as a failed single
        forward."""
        if len(items) <= 1:
            return [fn(i) for i in items]
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=min(len(items), 8), thread_name_prefix="fed-search"
        ) as pool:
            return list(pool.map(fn, items))

    @staticmethod
    def _search_msgs(
        task: str, cid: str, payload: bytes, mime: str, meta: dict[str, str]
    ) -> Iterator[pb.InferRequest]:
        """Chunked request messages for a front-built shard sub-request
        (the same framing the client's ``_requests`` helper emits)."""
        if len(payload) <= _FED_SEARCH_CHUNK:
            yield pb.InferRequest(
                correlation_id=cid, task=task, payload=payload,
                payload_mime=mime, meta=meta,
            )
            return
        total = (len(payload) + _FED_SEARCH_CHUNK - 1) // _FED_SEARCH_CHUNK
        for i in range(total):
            part = payload[i * _FED_SEARCH_CHUNK : (i + 1) * _FED_SEARCH_CHUNK]
            yield pb.InferRequest(
                correlation_id=cid, task=task, payload=part,
                payload_mime=mime, meta=meta if i == 0 else {},
                seq=i, total=total, offset=i * _FED_SEARCH_CHUNK,
            )

    def GetCapabilities(self, request, context) -> pb.Capability:
        """Aggregate the LIVE peers' capabilities into one record (the
        same merge the hub applies to its child services, one level up)."""
        fed = self.federation
        agg = pb.Capability(
            service_name="fed-front",
            runtime="jax-tpu",
            protocol_version="1.0.0",
        )
        for peer in fed.peers.values():
            if peer.state != "serving":
                continue
            try:
                cap = peer.stub.GetCapabilities(request, timeout=5.0)
            except Exception as e:  # noqa: BLE001 - a dead peer is not a caps error
                fed.record_unreachable(peer, e, "caps")
                continue
            for mid in cap.model_ids:
                if mid not in agg.model_ids:
                    agg.model_ids.append(mid)
            known = {t.name for t in agg.tasks}
            for task in cap.tasks:
                if task.name not in known:
                    agg.tasks.append(task)
            for p in cap.precisions:
                if p not in agg.precisions:
                    agg.precisions.append(p)
            agg.max_concurrency += cap.max_concurrency
        return agg

    def StreamCapabilities(self, request, context) -> Iterator[pb.Capability]:
        fed = self.federation
        for peer in fed.peers.values():
            if peer.state != "serving":
                continue
            try:
                for cap in peer.stub.StreamCapabilities(request, timeout=5.0):
                    # Stamp provenance so a topology client sees WHICH
                    # host each capability record came from.
                    cap.extra["fed_peer"] = peer.name
                    yield cap
            except Exception as e:  # noqa: BLE001 - skip dead peers
                fed.record_unreachable(peer, e, "caps")
                continue

    def Health(self, request, context):
        status = self._fed_status()
        if context is not None and status:
            try:
                context.set_trailing_metadata(
                    (("lumen-fed-status", json.dumps(status)),)
                )
            except Exception:  # noqa: BLE001 - test stubs may lack metadata support
                pass
        peers = status.get("peers", {})
        live = [n for n, s in peers.items() if s == "serving"]
        if peers and not live:
            # A front tier with every peer ejected serves nothing: fail
            # health exactly like a hub of only degraded placeholders.
            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"all federation peers ejected: {sorted(peers)}",
            )
        return empty_pb2.Empty()
