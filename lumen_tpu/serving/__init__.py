"""Serving layer: wire protocol, task registry, routing, gRPC servers.

TPU-native counterpart of the reference's ``src/lumen`` hub package plus the
per-package service scaffolding it duplicates.
"""

from .base_service import (
    BaseService,
    InvalidArgument,
    ServiceError,
    Unavailable,
    reassemble_result,
)
from .registry import TaskDefinition, TaskRegistry
from .router import HubRouter

__all__ = [
    "BaseService",
    "ServiceError",
    "InvalidArgument",
    "Unavailable",
    "TaskDefinition",
    "TaskRegistry",
    "HubRouter",
    "reassemble_result",
]
