"""Serving layer: wire protocol, task registry, routing, gRPC servers.

TPU-native counterpart of the reference's ``src/lumen`` hub package plus the
per-package service scaffolding it duplicates.
"""

from .base_service import (
    BaseService,
    DeadlineExceeded,
    InvalidArgument,
    ResourceExhausted,
    ServiceError,
    Unavailable,
    reassemble_result,
)
from .breaker import CircuitBreaker
from .registry import TaskDefinition, TaskRegistry
from .resilience import DegradedService, RecoveryManager
from .router import FederationRouter, HubRouter

__all__ = [
    "BaseService",
    "ServiceError",
    "InvalidArgument",
    "Unavailable",
    "ResourceExhausted",
    "DeadlineExceeded",
    "CircuitBreaker",
    "DegradedService",
    "RecoveryManager",
    "TaskDefinition",
    "TaskRegistry",
    "HubRouter",
    "FederationRouter",
    "reassemble_result",
]
