"""Hub / single-service gRPC server entry point (console script ``lumen-tpu``).

Startup sequence mirrors the reference hub runner
(``src/lumen/server.py:188-385``): load+validate config -> ensure model
artifacts -> instantiate services from their configured ``registry_class``
dotted paths -> bind gRPC (with OS-assigned port fallback) -> advertise
over mDNS -> serve until SIGINT/SIGTERM.

Unlike the reference, ``single`` and ``hub`` modes share this one entry
point (the reference duplicates a per-package server runner in each of the
four model packages); single mode is simply a hub with one service.

A third mode exists beyond the reference: with ``LUMEN_FED_PEERS`` set
and no enabled services, this server boots as a federation **front
tier** — it owns no models and consistent-hash-routes every request over
its peer servers on the unchanged protocol (docs/ARCHITECTURE.md "Fleet
federation"). With services AND peers it is a *peer-aware backend*:
local result-cache misses consult the ring owner's cache before
computing.

Unlike the reference (and this repo's seed), startup failure of ONE
service no longer aborts the hub: a failed download or ``from_config``
boots that service as a :class:`~lumen_tpu.serving.resilience.DegradedService`
(tasks answer UNAVAILABLE with a recovery hint) while a background
:class:`~lumen_tpu.serving.resilience.RecoveryManager` retries the load
with exponential backoff and hot-swaps the real service in on success.
``LUMEN_STRICT_BOOT=1`` restores the old abort-on-any-failure behavior.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import threading
from concurrent import futures

import grpc

from ..core.config import LumenConfig, load_config
from ..core.downloader import Downloader
from ..core.exceptions import DownloadError
from ..utils.logger import setup_logging
from .base_service import BaseService
from .breaker import CircuitBreaker, breaker_failures
from .loader import resolve
from .mdns import MdnsAdvertiser
from .resilience import DegradedService, RecoveryManager, expected_tasks_for
from .router import FederationRouter, HubRouter

logger = logging.getLogger(__name__)

GRPC_OPTIONS = [
    ("grpc.max_send_message_length", 64 * 1024 * 1024),
    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
]

DRAIN_ENV = "LUMEN_DRAIN_S"
DRAIN_ANNOUNCE_ENV = "LUMEN_DRAIN_ANNOUNCE_S"

#: a capacity probe older than this means nobody is watching anymore (a
#: front polls every LUMEN_FED_POLL_S, default 2s) — don't hold shutdown
#: for a departed observer.
_DRAIN_WATCHER_STALE_S = 15.0


def grpc_workers() -> int:
    """``LUMEN_GRPC_WORKERS``: gRPC handler threads (default 10, the
    reference's ThreadPoolExecutor size). A federation front tier wants
    more — each forwarded stream parks one handler thread on a peer RPC,
    so the front's concurrency ceiling is exactly this number."""
    from ..utils.env import env_int

    return env_int("LUMEN_GRPC_WORKERS", 10, minimum=1)


def drain_budget_s() -> float:
    """``LUMEN_DRAIN_S``: seconds a SIGTERM/SIGINT shutdown spends
    draining (default 10) — new RPCs answer UNAVAILABLE with a retry-after
    hint while queued and in-flight work completes; stragglers past the
    budget are aborted, then the process exits. ``0`` restores the
    immediate-stop behavior."""
    from ..utils.env import env_float

    return env_float(DRAIN_ENV, 10.0, minimum=0.0)


def drain_announce_s() -> float:
    """``LUMEN_DRAIN_ANNOUNCE_S``: max extra seconds an idle drain holds
    the server open so capacity gossip can announce the draining flag to
    a watching front (default 5; ``0`` disables the hold). Only applies
    when a Health probe carried this host's capacity report recently —
    a standalone or ungossiped server shuts down exactly as before. The
    hold ends early the moment a probe is served with the flag set, plus
    a short margin for the front's hot-key handoff fetches to arrive;
    always capped by the remaining ``LUMEN_DRAIN_S`` budget."""
    from ..utils.env import env_float

    return env_float(DRAIN_ANNOUNCE_ENV, 5.0, minimum=0.0)


def build_one_service(config: LumenConfig, name: str) -> BaseService:
    """Load exactly one service via its ``import_info.registry_class``
    factory (``from_config(service_config, cache_dir)`` classmethod
    contract, reference: ``src/lumen/service.py:12-49``). Shared by first
    boot and background recovery so both exercise the identical path
    (including the ``model_load`` fault point)."""
    from ..testing.faults import faults

    svc_cfg = config.services[name]
    faults.check("model_load", name)
    cls = resolve(svc_cfg.import_info.registry_class)
    logger.info("loading service %r via %s", name, svc_cfg.import_info.registry_class)
    return cls.from_config(svc_cfg, config.metadata.cache_path)


def build_services(
    config: LumenConfig, failed: dict[str, str] | None = None
) -> dict[str, BaseService]:
    """Instantiate every enabled service; services named in ``failed`` (or
    whose construction raises) become :class:`DegradedService` placeholders
    instead of killing their healthy siblings."""
    services: dict[str, BaseService] = {}
    for name, svc_cfg in config.enabled_services().items():
        error = (failed or {}).get(name)
        if error is None:
            try:
                services[name] = build_one_service(config, name)
                continue
            except Exception as e:  # noqa: BLE001 - degrade, don't kill siblings
                logger.exception("service %r failed to load; booting degraded", name)
                error = f"{type(e).__name__}: {e}"
        services[name] = DegradedService(
            name, error, tasks=expected_tasks_for(name, svc_cfg)
        )
    return services


def ensure_models(config: LumenConfig, strict: bool | None = None) -> dict[str, str]:
    """Fetch every enabled model; returns ``{service: error}`` for the
    services whose artifacts could not be made ready. With ``strict``
    (``LUMEN_STRICT_BOOT=1``) any failure aborts, the seed behavior."""
    if strict is None:
        strict = os.environ.get("LUMEN_STRICT_BOOT") == "1"
    report = Downloader(config).download_all()
    failures: dict[str, str] = {}
    for r in report.failures():
        logger.error("model fetch failed: %s/%s (%s): %s", r.service, r.alias, r.model, r.error)
        msg = f"{r.alias} ({r.model}): {r.error}"
        failures[r.service] = f"{failures[r.service]}; {msg}" if r.service in failures else msg
    if failures and strict:
        raise SystemExit(1)
    return failures


def rebuild_service(config: LumenConfig, name: str, skip_download: bool = False) -> BaseService:
    """Recovery path for one degraded service: re-fetch its artifacts and
    reconstruct it. Raises on any failure (the RecoveryManager backs off
    and retries)."""
    if not skip_download:
        report = Downloader(config).download_service(name)
        if not report.ok:
            errs = "; ".join(f"{r.alias}: {r.error}" for r in report.failures())
            raise DownloadError(f"model fetch failed for {name!r}: {errs}")
    return build_one_service(config, name)


def attach_breaker(
    recovery: RecoveryManager, name: str, svc: BaseService
) -> BaseService:
    """Give one live service its circuit breaker (no-op for degraded
    placeholders — they already fast-fail — and when
    ``LUMEN_BREAKER_FAILURES=0`` disables breakers). With
    ``LUMEN_BREAKER_RELOAD=1``, an opening breaker hands the service to
    the RecoveryManager: the same full-reload path a degraded boot uses
    (re-fetch + ``from_config`` + hot-swap), which also replaces any
    wedged batchers the watchdog disabled. Without it, the breaker still
    sheds and half-open-probes — reload stays an operator decision."""
    if isinstance(svc, DegradedService) or breaker_failures() == 0:
        return svc
    reload_on_open = os.environ.get("LUMEN_BREAKER_RELOAD") == "1"

    def on_open() -> None:
        if reload_on_open:
            logger.warning(
                "breaker for %r opened: handing to recovery for a reload", name
            )
            recovery.register(name)

    svc.breaker = CircuitBreaker(name, on_open=on_open)
    return svc


class ServerHandle:
    """A running gRPC server + its lifecycle helpers (returned by ``serve``
    for tests; the CLI blocks on ``wait``)."""

    def __init__(
        self,
        server: grpc.Server,
        port: int,
        mdns: MdnsAdvertiser | None,
        metrics_server=None,
        services: dict | None = None,
        recovery: RecoveryManager | None = None,
        router: HubRouter | None = None,
        autopilot=None,
        federation=None,
    ):
        self.server = server
        self.port = port
        self.mdns = mdns
        self.metrics_server = metrics_server
        # Live view: recovery hot-swaps promoted services into this dict
        # (it is the router's), so teardown closes what is actually running.
        self.services = services if services is not None else {}
        self.recovery = recovery
        self.router = router
        self.autopilot = autopilot
        self.federation = federation
        self._stopped = threading.Event()

    def drain_and_stop(self, drain_s: float | None = None) -> None:
        """Graceful shutdown: refuse new RPCs — the router gate answers
        in-band UNAVAILABLE with a ``lumen-retry-after-ms`` hint while the
        gRPC server keeps accepting, so late clients get a parseable
        back-off instead of a torn connection — let queued and in-flight
        streams complete for up to ``drain_s`` (``LUMEN_DRAIN_S``), flush
        ``server_drain`` flight-recorder events, then tear down. The
        SIGTERM/SIGINT path — shutdown used to drop in-flight work on the
        floor."""
        import time as _time

        if drain_s is None:
            drain_s = drain_budget_s()
        if drain_s <= 0:
            # LUMEN_DRAIN_S=0: the documented immediate-stop behavior —
            # no drain gate, the legacy default grace, no drain events.
            self.stop()
            return
        from ..utils import telemetry

        started = _time.monotonic()
        deadline = started + drain_s
        if self.router is not None:
            self.router.begin_drain(retry_after_s=max(drain_s, 1.0))
        telemetry.record_event(
            "server_drain", "server",
            f"drain started: refusing new RPCs, draining in-flight work "
            f"(budget {drain_s:.0f}s)",
        )
        # Announce hold: a PLANNED shutdown must be gossiped, not
        # discovered. If a front was recently reading our capacity report
        # off Health probes, an idle drain would otherwise tear the
        # listener down before the next poll — and the front would eject
        # us via failover (fed_peer_down incident) instead of re-weighting
        # to zero and prefetching hot keys. Hold (bounded) until a probe
        # is served WITH the draining flag, then a short margin so the
        # front's handoff fetches land while we still answer.
        announce_s = drain_announce_s()
        probe_age = (
            getattr(self.router, "capacity_probe_age", lambda: None)()
            if self.router is not None
            else None
        )
        if (
            announce_s > 0
            and probe_age is not None
            and probe_age <= _DRAIN_WATCHER_STALE_S
        ):
            announce_deadline = min(deadline, started + announce_s)
            while (
                not self.router.drain_announced()
                and _time.monotonic() < announce_deadline
            ):
                _time.sleep(0.05)
            if self.router.drain_announced():
                logger.info(
                    "drain: draining flag gossiped to a watching front "
                    "(%.2fs after SIGTERM)", _time.monotonic() - started,
                )
                _time.sleep(
                    min(1.0, max(deadline - _time.monotonic(), 0.0))
                )
            else:
                logger.info(
                    "drain: no probe observed the draining flag within "
                    "%.1fs; proceeding", announce_s,
                )
        # Hold the gRPC server OPEN while in-flight streams finish: once
        # server.stop() runs, new RPCs die at the transport with no
        # metadata — the in-band hint only exists during this window.
        stragglers = 0
        if self.router is not None:
            while self.router.active_streams() > 0 and _time.monotonic() < deadline:
                _time.sleep(0.05)
            stragglers = self.router.active_streams()
        # Remaining budget (floored small) covers response bytes still on
        # the wire; genuinely stuck streams are aborted at the floor.
        self.stop(grace=max(deadline - _time.monotonic(), 0.5))
        telemetry.record_event(
            "server_drain", "server",
            f"drain complete in {_time.monotonic() - started:.2f}s "
            f"({stragglers} straggler stream(s) past the budget); exiting",
        )

    def stop(self, grace: float = 5.0) -> None:
        if self.autopilot is not None:
            # First of all: the controller must not actuate (park, force a
            # rung, retune a window) against services mid-teardown — and
            # the process-global slot must not keep advertising a dead
            # controller on /autopilot and Health if another server boots
            # in this process later.
            from ..runtime.autopilot import get_autopilot, install_autopilot

            self.autopilot.stop()
            if get_autopilot() is self.autopilot:
                install_autopilot(None)
            self.autopilot = None
        if self.federation is not None:
            # Fleet teardown next: the poller must stop probing (and the
            # process-global slot stop advertising on /peers) before the
            # services it might mark healthy are closed underneath it.
            from ..runtime.federation import get_federation, install_federation
            from ..runtime.result_cache import detach_peer_lookup

            detach_peer_lookup(self.federation.peer_cache_lookup)
            self.federation.close()
            if get_federation() is self.federation:
                install_federation(None)
            self.federation = None
        if self.recovery:
            # Next: a recovery attempt finishing mid-shutdown would swap a
            # fresh service in after the close pass below already ran.
            self.recovery.stop()
        if self.mdns:
            self.mdns.stop()
        if self.metrics_server:
            self.metrics_server.stop()
        # Let in-flight RPCs drain FIRST, then close the services so their
        # batcher threads retire cleanly (instead of dying as daemons
        # mid-batch) and any queued requests are failed loudly rather than
        # silently dropped. grpc sets the stop event only AFTER aborting
        # stragglers at t=grace, so the wait needs margin past the grace
        # window or close() can race still-running handlers.
        self.server.stop(grace).wait(grace + 5.0)
        for name, svc in list(self.services.items()):
            close = getattr(svc, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    logger.exception("closing service %r failed", name)
        self._stopped.set()

    def wait(self) -> None:
        self.server.wait_for_termination()


def serve(
    config: LumenConfig,
    port_override: int | None = None,
    skip_download: bool = False,
    metrics_port: int | None = None,
) -> ServerHandle:
    from ..runtime import enable_persistent_cache
    from ..runtime.federation import maybe_federation

    enable_persistent_cache()  # warm restarts hit compiled buckets on disk
    # Fleet federation (LUMEN_FED_PEERS / LUMEN_FED_DISCOVER): resolved
    # once at boot, logged once. Unset -> None having done NOTHING — no
    # thread, no gauge, no per-request cost beyond one task-name compare
    # (tier-1 guard) — the single-host path boots byte-identical.
    federation = maybe_federation()
    failed: dict[str, str] = {}
    if not skip_download:
        failed = ensure_models(config)
    services = build_services(config, failed=failed)
    recovery: RecoveryManager | None = None
    if not services:
        if federation is None:
            logger.error("no enabled services selected by deployment config")
            raise SystemExit(1)
        # Front-tier mode: this server owns no models; every Infer stream
        # consistent-hash-routes over the peer set (a front tier speaks
        # the same protocol, so tiers compose).
        router: HubRouter = FederationRouter(federation)
        logger.info(
            "front-tier mode: no local services; routing %d peer(s) with "
            "hop budget %d", len(federation.peers), federation.hops,
        )
    else:
        router = HubRouter(services)

        degraded = sorted(n for n, s in services.items() if isinstance(s, DegradedService))

        def rebuild(n: str) -> BaseService:
            # Recovered/reloaded services get a fresh breaker too: the swap
            # replaces the instance whose breaker (and possibly watchdog-wedged
            # batchers) tripped, and its gauge registration supersedes the old
            # one (last-writer-wins in the metrics registry).
            return attach_breaker(
                recovery, n, rebuild_service(config, n, skip_download=skip_download)
            )

        # Always built (not only on a degraded boot): the per-service circuit
        # breakers can hand a service over for reload at ANY point in the
        # process's life (LUMEN_BREAKER_RELOAD=1).
        recovery = RecoveryManager(router, rebuild=rebuild)
        for name, svc in services.items():
            attach_breaker(recovery, name, svc)
        if degraded:
            logger.warning(
                "booting with %d degraded service(s): %s — healthy siblings keep "
                "serving; background recovery is retrying the failed loads",
                len(degraded), degraded,
            )
            for name in degraded:
                recovery.register(name)
        recovery.start()
        if federation is not None:
            # Peer-aware backend: fleet state rides this hub's Health
            # (lumen-fed-status), and — when this server knows which ring
            # member it is — local cache misses consult the ring owner's
            # cache before computing (the cross-host dedupe tier).
            router.federation = federation
            if federation.self_listed:
                from ..runtime.result_cache import get_result_cache

                get_result_cache().peer_lookup = federation.peer_cache_lookup
                logger.info(
                    "federation: peer-cache lookups enabled (self=%s)",
                    federation.self_name,
                )
            else:
                # Unset OR mislisted: either way the `owner == self`
                # guard cannot work, so no hook (the manager already
                # warned loudly on a mislisted self).
                logger.info(
                    "federation: %s %s — peer-cache lookups disabled on "
                    "this backend (health gossip + Health surfacing only)",
                    "LUMEN_FED_SELF",
                    "unset" if not federation.self_name else "not in peer list",
                )
            # Disaggregated prefill/decode: wire both halves of the KV
            # migration protocol. This host can ANSWER fed_kv_put (decode
            # sink) and DISPATCH migrations (prefill side); which lane it
            # actually plays is the front tier's routing call, driven by
            # each host's LUMEN_FED_ROLE advertisement.
            from ..runtime.federation import fed_role
            from ..utils import disagg

            vlm = next(
                (s for s in services.values() if hasattr(s, "handle_kv_put")),
                None,
            )
            engines = (
                list(getattr(getattr(vlm, "manager", None), "_engines", None) or [])
                if vlm is not None
                else []
            )
            if engines:
                disagg.enable()
                router.kv_migration = vlm
                for eng in engines:
                    eng.migrator = federation.kv_migrate
                logger.info(
                    "federation: KV migration wire enabled on %d engine(s) "
                    "(role=%s)", len(engines), fed_role(),
                )
    if federation is not None:
        federation.start()  # the one background health-poll thread

    server = grpc.server(
        futures.ThreadPoolExecutor(
            max_workers=grpc_workers(), thread_name_prefix="grpc"
        ),
        options=GRPC_OPTIONS,
    )
    router.attach_to_server(server)

    host = config.server.host
    port = port_override or config.server.port
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        # Requested port unavailable: fall back to an OS-assigned one
        # (reference behavior, src/lumen/server.py:242-263).
        bound = server.add_insecure_port(f"{host}:0")
        if bound == 0:
            logger.error("could not bind any port on %s", host)
            raise SystemExit(1)
        logger.warning("port %d unavailable; bound %d instead", port, bound)
    server.start()

    # Sidecar starts (and logs its endpoint) BEFORE the readiness line:
    # supervisors treat that line as "fully up", so everything they may
    # immediately query must already be announced. Binds loopback only —
    # profiler control must not be reachable from the network.
    metrics_server = None
    if metrics_port is not None:
        from .observability import MetricsServer

        metrics_server = MetricsServer(port=metrics_port, host="127.0.0.1")
        metrics_server.start()

    # Request-tracing status belongs in the boot log: whether per-stage
    # attribution (GET /traces on the sidecar) is live is a deploy-time
    # fact an operator should not have to probe for.
    from ..utils import trace as request_trace

    if request_trace.enabled():
        logger.info(
            "request tracing ON (sample=%.3g, ring=%d, slowest-%d retained)",
            request_trace.sample_rate(),
            request_trace.trace_ring(),
            request_trace.trace_slow_n(),
        )
    else:
        logger.info("request tracing off (set LUMEN_TRACE_SAMPLE to enable)")

    # Same deploy-time-facts rule for the capacity/SLO layer: whether
    # /stats has windows and whether any SLO objective is armed should be
    # one boot-log line, not a probe.
    from ..utils import telemetry as capacity_telemetry

    objectives = capacity_telemetry.slo_objectives()
    availability = capacity_telemetry.slo_availability()
    if capacity_telemetry.telemetry_enabled():
        logger.info(
            "capacity telemetry ON (bucket=%.0fs, retain=%.0fs); SLO: %s",
            capacity_telemetry.telemetry_bucket_s(),
            capacity_telemetry.telemetry_retain_s(),
            (
                f"{sorted(objectives)} p95 objectives"
                + (f", availability>={availability}" if availability else "")
                if objectives or availability
                else "no objectives (set LUMEN_SLO_<TASK>_P95_MS)"
            ),
        )
    else:
        logger.info("capacity telemetry off (LUMEN_TELEMETRY=0)")

    # Autopilot boot wiring (one-shot log either way): with
    # LUMEN_AUTOPILOT=1 the background controller closes the scale/
    # brownout/window loops over the telemetry spine; default-off keeps
    # tier-1 and unconfigured deployments byte-for-byte unchanged.
    from ..runtime.autopilot import maybe_start_autopilot

    autopilot = maybe_start_autopilot()

    logger.info("serving %d service(s) on %s:%d: %s", len(services), host, bound, sorted(services))
    for name, svc in services.items():
        logger.info("  %s [%s] tasks: %s", name, svc.status(), svc.registry.task_names())

    mdns = None
    mdns_cfg = config.server.mdns
    if mdns_cfg and mdns_cfg.enabled:
        mdns = MdnsAdvertiser(
            mdns_cfg.service_name or "lumen-tpu",
            bound,
            properties={"tasks": ",".join(t for s in services.values() for t in s.registry.task_names())},
        )
        mdns.start()
    return ServerHandle(
        server, bound, mdns, metrics_server, services=router.services,
        recovery=recovery, router=router, autopilot=autopilot,
        federation=federation,
    )


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="lumen-tpu", description="lumen-tpu inference server")
    parser.add_argument("--config", required=True, help="path to lumen config YAML")
    parser.add_argument("--port", type=int, default=None, help="override configured port")
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument(
        "--skip-download", action="store_true", help="assume model artifacts are already cached"
    )
    parser.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        help="expose /metrics + jax profiler control on this HTTP port (0 = auto)",
    )
    parser.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu"],
        help="force a JAX platform (e.g. cpu for a hardware-free dry run)",
    )
    args = parser.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    setup_logging(args.log_level)
    config = load_config(args.config)
    handle = serve(
        config,
        port_override=args.port,
        skip_download=args.skip_download,
        metrics_port=args.metrics_port,
    )

    stop_event = threading.Event()

    def _on_signal(signum, frame):  # noqa: ARG001
        logger.info("signal %d received; shutting down", signum)
        stop_event.set()

    signal.signal(signal.SIGINT, _on_signal)
    signal.signal(signal.SIGTERM, _on_signal)
    while not stop_event.wait(timeout=1.0):
        pass
    # Graceful drain (LUMEN_DRAIN_S): late RPCs answer UNAVAILABLE with a
    # retry-after hint, in-flight work completes, a server_drain event
    # lands in the flight recorder, then the process exits.
    handle.drain_and_stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
