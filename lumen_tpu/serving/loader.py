"""Dotted-path -> object resolution for dynamic service loading.

Same role as the reference ``src/lumen/loader.py:9-45``.
"""

from __future__ import annotations

import importlib
from typing import Any


class ServiceLoadError(Exception):
    pass


def resolve(dotted_path: str) -> Any:
    """Resolve ``pkg.module.Attr`` to the attribute object."""
    module_path, _, attr = dotted_path.rpartition(".")
    if not module_path:
        raise ServiceLoadError(f"not a dotted path: {dotted_path!r}")
    try:
        module = importlib.import_module(module_path)
    except ImportError as e:
        raise ServiceLoadError(f"cannot import module {module_path!r}: {e}") from e
    try:
        return getattr(module, attr)
    except AttributeError as e:
        raise ServiceLoadError(
            f"module {module_path!r} has no attribute {attr!r}"
        ) from e
