"""Shared gRPC servicer base for every model service.

Implements, once, the per-service plumbing the reference repeats in each
package's ``*_service.py`` (e.g.
``packages/lumen-clip/src/lumen_clip/general_clip/clip_service.py:208-414``):

- ``Infer`` loop with chunked-payload reassembly keyed by ``correlation_id``
  (``seq``/``total``/``offset`` contract),
- handler dispatch through a :class:`~lumen_tpu.serving.registry.TaskRegistry`,
- unified error mapping to wire ``Error`` records,
- ``GetCapabilities`` / ``StreamCapabilities`` / ``Health``.

Additionally supports **true server-side streaming**: a task handler may
return an iterator of ``(bytes, mime, meta)`` chunks, which are forwarded as
incremental ``InferResponse`` messages (the reference collects VLM "stream"
chunks into one response, ``fastvlm_service.py:492-506``).

**Bulk streaming lane** (high-occupancy serving): a stream whose requests
carry ``meta["bulk"] == "1"`` is treated as MANY tagged items on one
stream. Items are fanned into the task handlers CONCURRENTLY (a shared
bounded executor, ``LUMEN_BULK_WORKERS``) — so N images on one stream
coalesce into full micro-batches instead of arriving one at a time — and
tagged responses stream back as each item settles, out of order. Per-item
semantics are exactly the unary ones (each item runs the full
``_dispatch``: breaker gate, payload limit, deadline, cache/coalesce,
quarantine, error mapping), and a client disconnect mid-stream cancels the
not-yet-started remainder of the fan-out. This amortizes stream setup,
admission and context bookkeeping that BENCH_r05 showed costing more than
the device call itself (77 rps through gRPC vs 9k images/s on-device).

**Multi-tenant QoS** (:mod:`lumen_tpu.utils.qos`): every dispatch resolves
a ``(tenant, lane)`` identity — tenant from the ``lumen-tenant`` gRPC
request-metadata key (or a ``tenant`` request-meta field), lane from an
explicit ``priority`` meta or the bulk lane's auto-tag — gates it through
the per-tenant token buckets (``LUMEN_QOS_TENANT_RPS``; sheds answer
RESOURCE_EXHAUSTED-style with a ``lumen-retry-after-ms`` hint in O(1),
before payload/cache/decode work), and carries the identity on a
contextvar into the batcher's weighted-fair admission queue.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Iterator

import grpc
from google.protobuf import empty_pb2

from ..utils import deadline as request_deadline, request_notes
from ..utils import disagg
from ..utils import qos as request_qos
from ..utils import tensorwire
from ..utils import trace as request_trace
from ..utils.deadline import DeadlineExpired, PoisonInput, QueueFull, WatchdogTimeout
from ..utils.env import env_int
from ..utils.metrics import metrics
from .proto import ml_service_pb2 as pb
from .proto.ml_service_pb2_grpc import InferenceServicer
from .registry import TaskRegistry

logger = logging.getLogger(__name__)


#: request-meta key that switches a stream onto the bulk fan-out lane
BULK_META = "bulk"


def bulk_workers() -> int:
    """``LUMEN_BULK_WORKERS``: concurrent per-item dispatches a bulk
    stream may hold in flight, process-wide (default
    ``max(8, min(cpu*2, 16))`` — workers mostly BLOCK on batcher futures
    (decode runs on the decode pool, the device call on the batcher), so
    they are waiters, not CPU burners: the floor keeps enough of them to
    fill a device batch even on small hosts)."""
    n = env_int("LUMEN_BULK_WORKERS", 0, minimum=0)
    if n > 0:
        return n
    return max(8, min((os.cpu_count() or 4) * 2, 16))


_bulk_pool: ThreadPoolExecutor | None = None
_bulk_pool_lock = threading.Lock()


def _get_bulk_pool() -> ThreadPoolExecutor:
    """Process-wide executor for bulk-stream item dispatch (lazily sized
    from the env; shared across services so total fan-out concurrency is
    bounded no matter how many bulk streams are open)."""
    global _bulk_pool
    if _bulk_pool is None:
        with _bulk_pool_lock:
            if _bulk_pool is None:
                _bulk_pool = ThreadPoolExecutor(
                    bulk_workers(), thread_name_prefix="bulk-infer"
                )
    return _bulk_pool


#: LUMEN_RPC_TRIM (default on): request-path micro-trims — response-proto
#: reuse on the real-gRPC direct lane (the server serializes each yielded
#: message before pulling the next, so one scratch proto per thread
#: replaces an allocation + map copy per response). Read once at import;
#: the bench A/Bs the serialize span by toggling the module flag.
RPC_TRIM = env_int("LUMEN_RPC_TRIM", 1) != 0

_proto_scratch = threading.local()


def _response_chunk_bytes() -> int:
    """LUMEN_RESPONSE_CHUNK_BYTES, clamped to [1 MB, 60 MB]; malformed
    values fall back to the 48 MB default (degrade, not crash — with the
    shared parser's one-shot warning)."""
    return env_int(
        "LUMEN_RESPONSE_CHUNK_BYTES",
        48 * 1024 * 1024,
        minimum=1 << 20,
        maximum=60 * 1024 * 1024,
    )


def reassemble_result(responses) -> tuple[bytes, str, dict[str, str]]:
    """Client-side inverse of the server's chunked unary response: join
    ``seq``/``total``/``offset`` chunks back into (result, mime, meta).
    Works on single-message responses too. Raises :class:`ServiceError`
    on a wire error or an incomplete stream (missing chunks / cut short
    before ``is_final``) — truncated bytes must never pass as a result."""
    parts: dict[int, bytes] = {}
    mime, meta = "", {}
    total = 0
    for r in responses:
        # code 0 is ERROR_CODE_UNSPECIFIED but the field being SET at all
        # means failure (matching the server's _error emission).
        if r.HasField("error") and (r.error.code or r.error.message):
            raise ServiceError(r.error.code, r.error.message, r.error.detail)
        parts[r.seq] = r.result
        total = max(total, r.total)
        mime = r.result_mime or mime
        if r.meta:  # convert only populated maps (once per response at most)
            meta = dict(r.meta)
    if total and len(parts) < total:
        raise ServiceError(
            0,
            f"incomplete chunked response: {len(parts)} of {total} chunks",
        )
    return b"".join(parts[i] for i in sorted(parts)), mime, meta


class ServiceError(Exception):
    """Error with a wire error-code; raised by task handlers."""

    def __init__(self, code: int, message: str, detail: str = ""):
        super().__init__(message)
        self.code = code
        self.detail = detail


class InvalidArgument(ServiceError):
    def __init__(self, message: str, detail: str = ""):
        super().__init__(pb.ERROR_CODE_INVALID_ARGUMENT, message, detail)


class Unavailable(ServiceError):
    def __init__(self, message: str, detail: str = ""):
        super().__init__(pb.ERROR_CODE_UNAVAILABLE, message, detail)


class ResourceExhausted(ServiceError):
    """Load shed by admission control. The wire enum has no dedicated
    RESOURCE_EXHAUSTED value, so this rides UNAVAILABLE with an explicit
    retry hint — retryable-with-backoff is exactly the client contract."""

    def __init__(self, message: str, detail: str = ""):
        super().__init__(
            pb.ERROR_CODE_UNAVAILABLE,
            message,
            detail or "server overloaded; retry with exponential backoff",
        )


class DeadlineExceeded(ServiceError):
    def __init__(self, message: str, detail: str = ""):
        super().__init__(pb.ERROR_CODE_DEADLINE_EXCEEDED, message, detail)


def first_meta_key(meta: dict[str, str], *keys: str) -> str | None:
    """First present key among ``keys`` — shared alias resolution so every
    service treats reference-client meta names (e.g. the face service's
    ``detection_confidence_threshold`` for our ``conf_threshold``) with the
    same precedence rule: our name first, then the reference aliases."""
    for key in keys:
        if key in meta:
            return key
    return None


@dataclass
class _Assembly:
    task: str = ""
    payload_mime: str = ""
    meta: dict[str, str] = field(default_factory=dict)
    chunks: dict[int, bytes] = field(default_factory=dict)
    total: int = 0
    #: first-chunk arrival instant — the request trace back-dates to here
    #: so the ``rpc.recv`` span covers chunked-payload reassembly.
    t0: float = field(default_factory=time.perf_counter)

    def add(self, req: pb.InferRequest) -> None:
        if not self.task:
            self.task = req.task
            self.payload_mime = req.payload_mime
        if req.meta:
            self.meta.update(dict(req.meta))
        self.chunks[req.seq] = req.payload
        if req.total:
            self.total = req.total

    @property
    def complete(self) -> bool:
        # total==0 (single-chunk fast path) or all declared chunks present.
        if self.total == 0:
            return True
        return len(self.chunks) >= self.total

    def payload(self) -> bytes:
        if len(self.chunks) == 1:
            # The overwhelmingly common single-chunk request: hand the
            # buffer straight through — no sort, no join, no copy.
            return next(iter(self.chunks.values()))
        return b"".join(self.chunks[i] for i in sorted(self.chunks))


class BaseService(InferenceServicer):
    """Subclasses populate ``self.registry`` and implement ``capability()``."""

    #: Per-service circuit breaker (attached by the server after
    #: construction; None = no breaker, the default for tests and
    #: hand-built services). When set, ``_dispatch`` gates every request
    #: through it and records request outcomes.
    breaker = None

    def __init__(self, registry: TaskRegistry):
        self.registry = registry

    # -- to override ------------------------------------------------------

    def capability(self) -> pb.Capability:
        raise NotImplementedError

    def healthy(self) -> bool:
        return True

    def replica_states(self) -> dict:
        """Per-replica health states keyed by dispatcher name, e.g.
        ``{"clip-image": {"r0": "serving", "r1": "down"}}``. Populated by
        services whose managers run a replica fleet
        (:mod:`lumen_tpu.runtime.fleet`); ``{}`` means single-replica.
        Surfaces in ``Health`` trailing metadata (``lumen-replica-status``)
        next to the breaker/quarantine keys."""
        return {}

    def _record_outcome(self, e: BaseException | None) -> None:
        """One source of truth for breaker accounting (shared by the unary
        and streaming dispatch paths). ``None`` = success. Backend-health
        verdicts: :class:`WatchdogTimeout` and INTERNAL-class crashes
        count toward tripping; :class:`PoisonInput` is the payload's fault
        (telemetry only); overload/deadline/client errors are *neutral* —
        no verdict either way, but they release a half-open probe slot so
        a probe that was itself shed cannot pin the breaker."""
        if self.breaker is None:
            return
        if e is None:
            self.breaker.record_success()
        elif isinstance(e, WatchdogTimeout):
            self.breaker.record_failure()
        elif isinstance(e, PoisonInput):
            self.breaker.record_poison()
        elif isinstance(e, (QueueFull, DeadlineExpired, ServiceError)):
            self.breaker.record_neutral()
        else:
            self.breaker.record_failure()

    def status(self) -> str:
        """One-word state for the hub's per-service health report:
        ``healthy``, ``unhealthy`` (unexpected — fails hub health),
        ``degraded``/``recovering`` (known-broken with background recovery
        — reported, but healthy siblings keep the hub serving), or
        ``breaker_open``/``breaker_half_open`` (fast-failing after repeated
        backend failures — reported like degraded: siblings keep the hub
        up, but a hub that is ALL broken still fails health)."""
        if self.breaker is not None:
            state = self.breaker.state()
            if state != "closed":
                return f"breaker_{state}"
        return "healthy" if self.healthy() else "unhealthy"

    # -- Inference rpc implementation ------------------------------------

    def Infer(self, request_iterator, context) -> Iterator[pb.InferResponse]:
        buffers: dict[str, _Assembly] = {}
        it = iter(request_iterator)
        # Response-proto reuse is safe ONLY when each yielded message is
        # serialized before the next is produced — true for the real gRPC
        # server (it serializes per yield), NOT for in-process callers
        # that collect responses into a list (tests, the bulk fan-out).
        reuse = RPC_TRIM and isinstance(context, grpc.ServicerContext)
        for req in it:
            cid = req.correlation_id
            asm = buffers.setdefault(cid, _Assembly())
            asm.add(req)
            if not asm.complete:
                continue
            del buffers[cid]
            if asm.meta.get(BULK_META) == "1":
                # Bulk lane: this and every further item on the stream fan
                # out concurrently; responses come back tagged, unordered.
                yield from self._bulk_infer(cid, asm, it, buffers, context)
                return
            yield from self._dispatch(cid, asm, context, reuse=reuse)

    def _bulk_infer(
        self,
        first_cid: str,
        first_asm: _Assembly,
        request_iter,
        buffers: dict[str, _Assembly],
        context,
    ) -> Iterator[pb.InferResponse]:
        """Concurrent fan-out for a bulk stream.

        A reader thread keeps draining the request iterator (so item k+1
        is being reassembled while item k runs), every completed assembly
        is dispatched on the shared bulk executor, and this generator
        streams each item's responses back the moment its dispatch
        settles. ``stop`` is the cancellation latch: it is set when the
        client disconnects (the reader's iterator raises, or gRPC closes
        this generator mid-yield) and makes queued-but-unstarted items
        no-ops while already-running ones finish and are discarded."""
        out: queue.Queue = queue.Queue()
        stop = threading.Event()
        lock = threading.Lock()
        state = {"submitted": 0, "settled": 0, "eof": False}
        # PENDING futures only: settled ones are discarded on drain so a
        # long stream's retained memory is the backpressure window, not
        # every buffered response list since the stream began.
        pending: set = set()
        pool = _get_bulk_pool()
        # Request-path trim: the stream's gRPC request metadata (where the
        # tenant id lives) is identical for every item — resolve it ONCE
        # instead of scanning the metadata tuple per item (BENCH_r05
        # attribution charges that per-item bookkeeping to rpc overhead).
        stream_tenant = self._invocation_meta(context, request_qos.TENANT_META_KEY)
        # Backpressure: bound items submitted-but-unsettled so a 100k-item
        # stream cannot buffer every payload in the executor queue at once
        # (the unary path was naturally one-at-a-time; this restores gRPC
        # flow control — the reader pauses, the transport window fills,
        # the client stops sending). A few windows per worker keeps the
        # pool fed without holding the whole stream in RAM.
        window = threading.Semaphore(bulk_workers() * 4)

        def run_one(cid: str, asm: _Assembly):
            if stop.is_set():
                return None
            return list(self._dispatch(cid, asm, context, tenant=stream_tenant))

        def submit(cid: str, asm: _Assembly) -> bool:
            while not window.acquire(timeout=0.1):
                if stop.is_set():
                    return False  # abandoned stream: stop buffering
            with lock:
                state["submitted"] += 1
            fut = pool.submit(run_one, cid, asm)
            with lock:
                pending.add(fut)
            fut.add_done_callback(lambda f, c=cid: out.put((c, f)))
            return True

        submit(first_cid, first_asm)

        def reader() -> None:
            try:
                for req in request_iter:
                    if stop.is_set():
                        break
                    cid = req.correlation_id
                    asm = buffers.setdefault(cid, _Assembly())
                    asm.add(req)
                    if not asm.complete:
                        continue
                    del buffers[cid]
                    if not submit(cid, asm):
                        break
            except Exception:  # noqa: BLE001 - client hung up mid-stream
                stop.set()
            finally:
                with lock:
                    state["eof"] = True
                out.put(None)  # wake the drain loop for the exit check

        threading.Thread(target=reader, name="bulk-reader", daemon=True).start()
        try:
            while True:
                with lock:
                    if state["eof"] and state["settled"] >= state["submitted"]:
                        break
                got = out.get()
                if got is None:
                    continue
                cid, fut = got
                with lock:
                    state["settled"] += 1
                    pending.discard(fut)
                window.release()  # free a backpressure slot for the reader
                if fut.cancelled() or stop.is_set():
                    continue
                err = fut.exception()
                if err is not None:
                    # _dispatch maps its own errors; anything escaping it
                    # is infrastructure failure — isolate to this item.
                    logger.exception("bulk item %s failed", cid, exc_info=err)
                    metrics.count("bulk_item_crashes")
                    yield self._error(
                        cid, pb.ERROR_CODE_INTERNAL, f"{type(err).__name__}: {err}"
                    )
                    continue
                responses = fut.result()
                if responses:
                    yield from responses
        finally:
            # Client gone (GeneratorExit) or stream complete: nothing may
            # keep burning device time on answers nobody reads. cancel()
            # kills queued-unstarted items; running ones see `stop`.
            stop.set()
            with lock:
                remaining = list(pending)
            for fut in remaining:
                fut.cancel()

    @staticmethod
    def _context_deadline(context) -> float | None:
        """Absolute monotonic deadline from a gRPC context, or None when the
        client set no deadline (or the context is a test stub without
        ``time_remaining``)."""
        tr = getattr(context, "time_remaining", None)
        if not callable(tr):
            return None
        try:
            rem = tr()
        except Exception:  # noqa: BLE001 - a stub context must not break dispatch
            return None
        return None if rem is None else time.monotonic() + rem

    @staticmethod
    def _invocation_meta(context, wanted: str) -> str | None:
        """One gRPC request-metadata value by key (None on stub contexts
        or absent keys) — shared by the trace-id and tenant-id reads."""
        md = getattr(context, "invocation_metadata", None)
        if not callable(md):
            return None
        try:
            for item in md() or ():
                key = getattr(item, "key", None)
                value = getattr(item, "value", None)
                if key is None and isinstance(item, (tuple, list)) and len(item) == 2:
                    key, value = item
                if key == wanted and value:
                    return str(value)
        except Exception:  # noqa: BLE001 - metadata must never break dispatch
            return None
        return None

    @classmethod
    def _trace_id_from(cls, context) -> str | None:
        """Client-propagated trace id from the ``lumen-trace`` gRPC
        request metadata key (None on stub contexts or untraced callers)
        — lets a client stitch its side of the request into ``/traces``."""
        return cls._invocation_meta(context, request_trace.TRACE_META_KEY)

    @classmethod
    def _qos_identity(
        cls, asm: _Assembly, context, tenant: str | None = None
    ) -> tuple[str, str]:
        """Resolve the request's ``(tenant, lane)``. Tenant: the
        ``lumen-tenant`` gRPC request-metadata key, else a ``tenant``
        request-meta field (in-process/stub callers), else ``default``.
        Lane: an explicit ``priority`` meta (``interactive``/``bulk``)
        wins; otherwise the bulk streaming lane auto-tags ``bulk`` and
        everything else is interactive. ``tenant`` short-circuits the
        metadata scan when the caller already resolved it (the bulk lane
        resolves once per STREAM — the metadata is stream-constant)."""
        tenant = (
            tenant
            or cls._invocation_meta(context, request_qos.TENANT_META_KEY)
            or asm.meta.get("tenant")
            or request_qos.DEFAULT_TENANT
        )
        explicit = asm.meta.get("priority")
        if explicit in request_qos.LANES:
            lane = explicit
        elif asm.meta.get(BULK_META) == "1":
            lane = request_qos.LANE_BULK
        else:
            lane = request_qos.LANE_INTERACTIVE
        return tenant, lane

    def _dispatch(
        self, cid: str, asm: _Assembly, context=None,
        tenant: str | None = None, reuse: bool = False,
    ) -> Iterator[pb.InferResponse]:
        """Trace-lifecycle wrapper around :meth:`_dispatch_inner`. With
        tracing off (``LUMEN_TRACE_SAMPLE=0``, the default) the cost is
        one cached env check; with it on, the request gets a contextvar-
        propagated :class:`~lumen_tpu.utils.trace.Trace` back-dated to
        the first chunk's arrival (the ``rpc.recv`` span), every error
        response marks the trace errored (tail sampling always retains
        those), and the finished trace lands in the process recorder."""
        tr = None
        if request_trace.enabled():
            tr = request_trace.begin_request(
                asm.task, trace_id=self._trace_id_from(context), t0=asm.t0
            )
        if tr is None:
            yield from self._dispatch_inner(cid, asm, context, tenant, reuse)
            return
        tr.add_span("rpc.recv", asm.t0, time.perf_counter())
        token = request_trace.activate(tr)
        try:
            for resp in self._dispatch_inner(cid, asm, context, tenant, reuse):
                if resp.HasField("error"):
                    tr.set_error(resp.error.message or "error")
                yield resp
        except BaseException as e:
            # Includes GeneratorExit: a client that hung up mid-stream
            # leaves an errored (always-retained) trace behind.
            tr.set_error(f"{type(e).__name__}: {e}")
            raise
        finally:
            request_trace.deactivate(token)
            request_trace.finish_request(tr)

    def _dispatch_inner(
        self, cid: str, asm: _Assembly, context=None,
        tenant: str | None = None, reuse: bool = False,
    ) -> Iterator[pb.InferResponse]:
        task = self.registry.get(asm.task)
        if task is None:
            yield self._error(
                cid,
                pb.ERROR_CODE_INVALID_ARGUMENT,
                f"unknown task {asm.task!r}",
                f"supported: {self.registry.task_names()}",
            )
            return
        # Circuit-breaker gate: an open breaker sheds HERE — before the
        # payload is even assembled into the model path, before deadline
        # and admission accounting, in O(1) — with the same retryable
        # UNAVAILABLE shape a DegradedService answers, plus a retry-after
        # hint and a ``breaker_open`` meta note so clients can tell
        # shed-by-breaker (backend broken, back off hard) from
        # shed-by-queue (overload, back off briefly).
        if self.breaker is not None:
            tr = request_trace.current_trace()
            bspan = tr.begin("breaker") if tr is not None else None
            admitted, retry_after = self.breaker.allow()
            if bspan is not None:
                bspan.end(admitted="1" if admitted else "0")
            if not admitted:
                metrics.count("breaker_sheds")
                metrics.count_error(asm.task)
                yield self._error(
                    cid,
                    pb.ERROR_CODE_UNAVAILABLE,
                    f"circuit breaker open for service "
                    f"{self.registry.service_name!r}; request shed",
                    f"backend failing repeatedly; retry after ~{retry_after:.1f}s",
                    meta={
                        "breaker_open": "1",
                        request_qos.RETRY_AFTER_META: request_qos.retry_after_ms(
                            retry_after
                        ),
                    },
                )
                return
        # Per-tenant quota gate: a tenant over its token-bucket rate
        # (LUMEN_QOS_TENANT_RPS / LUMEN_QOS_RPS_<TENANT>) is shed HERE —
        # before payload assembly, cache lookups, the decode pool and the
        # admission queue, in O(1) (~10µs, same order as a breaker shed) —
        # with the RESOURCE_EXHAUSTED shape plus a ``lumen-retry-after-ms``
        # hint saying exactly when the next token lands.
        tenant, lane = self._qos_identity(asm, context, tenant)
        admitted, retry_after = request_qos.get_quota().gate(tenant)
        if not admitted:
            err = ResourceExhausted(
                f"tenant {tenant!r} over its request-rate quota; "
                f"{asm.task!r} shed",
                f"per-tenant quota exceeded; retry after ~{retry_after:.2f}s",
            )
            # A quota shed says nothing about backend health, but it may
            # hold the half-open probe slot — release it (neutral).
            self._record_outcome(err)
            metrics.count_error(asm.task)
            yield self._error(
                cid,
                err.code,
                str(err),
                err.detail,
                meta={
                    "qos_shed": "1",
                    request_qos.RETRY_AFTER_META: request_qos.retry_after_ms(
                        retry_after
                    ),
                },
            )
            return
        payload = asm.payload()
        if len(payload) > task.max_payload_bytes:
            # Past the breaker gate but before the handler: this request
            # may hold the half-open probe slot, and a client error is no
            # verdict on backend health — release the slot (neutral), or
            # the breaker keeps shedding for a full reset window.
            self._record_outcome(InvalidArgument("payload exceeds limit"))
            yield self._error(
                cid,
                pb.ERROR_CODE_INVALID_ARGUMENT,
                f"payload exceeds limit ({len(payload)} > {task.max_payload_bytes} bytes)",
            )
            return
        # tensor/raw gate: a pre-decoded tensor payload is validated
        # against the task's ADVERTISED input spec (capability extra
        # ``tensor_input:<task>``) right here — before the handler, the
        # cache, the decode pool and the batcher. A mismatch is a client
        # error with a precise message: it is never cached, never
        # quarantined, and releases a held half-open probe slot exactly
        # like the payload-limit gate above.
        if asm.payload_mime == tensorwire.TENSOR_MIME:
            if task.tensor_spec is None:
                self._record_outcome(InvalidArgument("tensor input unsupported"))
                metrics.count_error(asm.task)
                yield self._error(
                    cid,
                    pb.ERROR_CODE_INVALID_ARGUMENT,
                    f"task {asm.task!r} does not accept tensor/raw payloads",
                    "tasks with a tensor_input:* capability key do",
                )
                return
            try:
                tensorwire.validate_tensor_meta(
                    asm.meta, len(payload), task.tensor_spec
                )
            except ValueError as e:
                self._record_outcome(InvalidArgument(str(e)))
                metrics.count_error(asm.task)
                yield self._error(cid, pb.ERROR_CODE_INVALID_ARGUMENT, str(e))
                return
        # Deadline propagation (L2 -> L4): expired requests are answered
        # without touching the model, and the remaining budget rides a
        # contextvar so the micro-batcher can drop entries that expire
        # while queued — before the device call burns a batch slot.
        deadline = self._context_deadline(context)
        if deadline is not None and time.monotonic() >= deadline:
            # Same probe-release rule as the payload gate above: an
            # expired deadline says nothing about backend health.
            self._record_outcome(DeadlineExpired("expired before dispatch"))
            metrics.count("deadline_drops")
            metrics.count_error(asm.task)
            yield self._error(
                cid,
                pb.ERROR_CODE_DEADLINE_EXCEEDED,
                f"deadline expired before dispatch of {asm.task!r}",
            )
            return
        t0 = time.perf_counter()
        # The token scope covers streaming output too: a lazy handler's
        # body runs inside _stream_out's iteration, and its batcher
        # submits must still see the request deadline.
        token = request_deadline.set_deadline(deadline)
        # QoS identity scope: the batcher's weighted-fair admission queue
        # (and the result cache's per-tenant accounting) read the tenant
        # and priority lane from this contextvar — no signature in
        # between grows a parameter, same pattern as the deadline.
        qos_token = request_qos.activate(tenant, lane)
        # Cache-note scope: the result cache (layers below, in the manager)
        # marks hit/coalesce here; unary responses surface the marks as
        # trailing ``cache_hit`` / ``cache_coalesced`` meta. A hit is
        # decided on the raw payload bytes before the decode pool and the
        # batcher, so it is answered without touching deadline or
        # admission accounting (no shed, no deadline_drop, no batch slot).
        notes_token = request_notes.begin_notes()
        # Decode-owner scope (disaggregated prefill/decode): the front
        # tier's ``lumen-decode-owner`` metadata rides down to the VLM
        # manager's request construction — same contextvar pattern as the
        # deadline. Gated on disagg.enabled() (server boot with a
        # federation attached) so unconfigured hosts never even scan
        # request metadata for the key.
        owner_token = (
            disagg.activate(self._invocation_meta(context, disagg.DECODE_OWNER_META))
            if disagg.enabled()
            else None
        )
        try:
            try:
                out = task.handler(payload, asm.payload_mime, asm.meta)
            except ServiceError as e:
                self._record_outcome(e)
                metrics.count_error(asm.task)
                yield self._error(cid, e.code, str(e), e.detail)
                return
            except (QueueFull, DeadlineExpired, PoisonInput, WatchdogTimeout) as e:
                self._record_outcome(e)
                metrics.count_error(asm.task)
                yield self._overload_error(cid, asm.task, e)
                return
            except Exception as e:  # noqa: BLE001 - handler crash -> INTERNAL
                self._record_outcome(e)
                logger.exception("task %s failed", asm.task)
                metrics.count_error(asm.task)
                yield self._error(cid, pb.ERROR_CODE_INTERNAL, f"{type(e).__name__}: {e}")
                return
            if isinstance(out, tuple):
                self._record_outcome(None)
                result, mime, meta = out
                meta = dict(meta)
                lat_ms = (time.perf_counter() - t0) * 1e3
                metrics.observe(asm.task, lat_ms)
                meta["lat_ms"] = f"{lat_ms:.2f}"
                marks = request_notes.current()
                if marks.get("hit"):
                    meta["cache_hit"] = "1"
                if marks.get("coalesced"):
                    meta["cache_coalesced"] = "1"
                if marks.get("peer_hit"):
                    # Served from a PEER host's cache via the federation
                    # lookup: no device work anywhere in the fleet.
                    meta["cache_peer_hit"] = "1"
                tr = request_trace.current_trace()
                ser = None
                if tr is not None:
                    # Echo the id so the client can join its span with
                    # ours; the span covers protobuf construction AND the
                    # consumer-side sends (the generator resumes per chunk).
                    meta[request_trace.TRACE_RESPONSE_META] = tr.trace_id
                    ser = tr.begin("serialize", {"bytes": len(result)})
                yield from self._chunked_response(cid, result, mime, meta, reuse)
                if ser is not None:
                    ser.end()
            else:
                # Streaming handler: iterator of (bytes, mime, meta) chunks.
                yield from self._stream_out(cid, asm.task, out, t0)
        finally:
            if owner_token is not None:
                disagg.deactivate(owner_token)
            request_notes.end_notes(notes_token)
            request_qos.deactivate(qos_token)
            request_deadline.reset(token)

    #: Split unary results larger than this into seq/total/offset chunks
    #: (the proto carries the fields on InferResponse for exactly this,
    #: reference ``ml_service.proto:60-73``). Clamped under the 64 MB
    #: gRPC message cap (``server.GRPC_OPTIONS``) with protobuf headroom;
    #: a malformed override degrades to the default instead of crashing
    #: the import (same policy as LUMEN_FLASH_BLOCK_Q/K).
    RESPONSE_CHUNK_BYTES = _response_chunk_bytes()

    def _chunked_response(
        self, cid: str, result: bytes, mime: str, meta: dict[str, str],
        reuse: bool = False,
    ) -> Iterator[pb.InferResponse]:
        """One message when the result fits; otherwise seq/total/offset
        chunks with ``is_final`` on the last. meta rides every chunk so a
        client reading only the final message still sees it, and early
        readers (progress UIs) see it too.

        ``reuse=True`` (the ``LUMEN_RPC_TRIM`` request-path trim, set only
        on the real-gRPC direct lane where each yield is serialized before
        the next message is built) recycles one thread-local scratch proto
        instead of allocating per response; on the multi-chunk path the
        meta map is populated ONCE and only result/seq/offset mutate per
        chunk."""
        size = self.RESPONSE_CHUNK_BYTES
        if reuse:
            resp = getattr(_proto_scratch, "resp", None)
            if resp is None:
                resp = _proto_scratch.resp = pb.InferResponse()
            resp.Clear()
            resp.correlation_id = cid
            resp.result_mime = mime
            for k, v in meta.items():
                resp.meta[k] = v
            if len(result) <= size:
                resp.is_final = True
                resp.result = result
                resp.total = 1
                yield resp
                return
            n = (len(result) + size - 1) // size
            resp.total = n
            for i in range(n):
                off = i * size
                resp.is_final = i == n - 1
                resp.result = result[off : off + size]
                resp.seq = i
                resp.offset = off
                yield resp
            return
        if len(result) <= size:
            yield pb.InferResponse(
                correlation_id=cid,
                is_final=True,
                result=result,
                meta=meta,
                result_mime=mime,
                seq=0,
                total=1,
            )
            return
        n = (len(result) + size - 1) // size
        for i in range(n):
            off = i * size
            yield pb.InferResponse(
                correlation_id=cid,
                is_final=(i == n - 1),
                result=result[off : off + size],
                meta=meta,
                result_mime=mime,
                seq=i,
                total=n,
                offset=off,
            )

    def _stream_out(self, cid: str, task_name: str, chunks, t0: float) -> Iterator[pb.InferResponse]:
        seq = 0
        pending: tuple[bytes, str, dict[str, str]] | None = None
        try:
            for chunk in chunks:
                if pending is not None:
                    result, mime, meta = pending
                    yield pb.InferResponse(
                        correlation_id=cid,
                        is_final=False,
                        result=result,
                        meta=meta,
                        result_mime=mime,
                        seq=seq,
                    )
                    seq += 1
                pending = chunk
        except ServiceError as e:
            self._record_outcome(e)
            metrics.count_error(task_name)
            yield self._error(cid, e.code, str(e), e.detail)
            return
        except (QueueFull, DeadlineExpired, PoisonInput, WatchdogTimeout) as e:
            self._record_outcome(e)
            metrics.count_error(task_name)
            yield self._overload_error(cid, task_name, e)
            return
        except Exception as e:  # noqa: BLE001
            self._record_outcome(e)
            logger.exception("streaming task %s failed", task_name)
            metrics.count_error(task_name)
            yield self._error(cid, pb.ERROR_CODE_INTERNAL, f"{type(e).__name__}: {e}")
            return
        if pending is None:
            # INTERNAL-class backend symptom: must reach the breaker like
            # any other crash (count toward tripping / resolve a probe).
            self._record_outcome(RuntimeError("streaming handler yielded no chunks"))
            metrics.count_error(task_name)
            yield self._error(cid, pb.ERROR_CODE_INTERNAL, "streaming handler yielded no chunks")
            return
        self._record_outcome(None)
        result, mime, meta = pending
        meta = dict(meta)
        lat_ms = (time.perf_counter() - t0) * 1e3
        metrics.observe(task_name, lat_ms)
        meta["lat_ms"] = f"{lat_ms:.2f}"
        tr = request_trace.current_trace()
        if tr is not None:
            meta[request_trace.TRACE_RESPONSE_META] = tr.trace_id
        yield pb.InferResponse(
            correlation_id=cid,
            is_final=True,
            result=result,
            meta=meta,
            result_mime=mime,
            seq=seq,
            total=seq + 1,
        )

    @classmethod
    def _overload_error(cls, cid: str, task_name: str, e: Exception) -> pb.InferResponse:
        """One source of truth for the overload/containment exceptions'
        wire mapping: a batcher :class:`QueueFull` is a
        :class:`ResourceExhausted` (UNAVAILABLE + backoff hint), a
        :class:`DeadlineExpired` is a :class:`DeadlineExceeded`, a
        :class:`PoisonInput` is an :class:`InvalidArgument` (the PAYLOAD is
        broken — retrying it is pointless; the message names the bisection
        isolation or quarantine verdict, and the response meta carries
        ``quarantined`` when the quarantine registry flagged it), and a
        :class:`WatchdogTimeout` is an :class:`Unavailable` (backend
        stalled; the breaker/recovery path is already on it). A
        :class:`QueueFull` that carries the batcher's drain-time estimate
        surfaces it as the ``lumen-retry-after-ms`` response-meta hint —
        the same key quota and breaker sheds use — so every shed tells
        the client when to come back."""
        meta = None
        if isinstance(e, QueueFull):
            err: ServiceError = ResourceExhausted(f"{task_name}: {e}")
            hint = getattr(e, "retry_after_s", None)
            if hint is not None:
                meta = {
                    request_qos.RETRY_AFTER_META: request_qos.retry_after_ms(hint)
                }
        elif isinstance(e, PoisonInput):
            err = InvalidArgument(
                f"{task_name}: {e}",
                "this payload repeatedly fails its batch; fix the input "
                "instead of retrying",
            )
            if request_notes.current().get("quarantined"):
                meta = {"quarantined": "1"}
        elif isinstance(e, WatchdogTimeout):
            err = Unavailable(
                f"{task_name}: {e}",
                "backend stalled past its watchdog budget; retry after the "
                "service reloads",
            )
        else:
            err = DeadlineExceeded(f"{task_name}: {e}")
        return cls._error(cid, err.code, str(err), err.detail, meta=meta)

    @staticmethod
    def _error(
        cid: str,
        code: int,
        message: str,
        detail: str = "",
        meta: dict[str, str] | None = None,
    ) -> pb.InferResponse:
        return pb.InferResponse(
            correlation_id=cid,
            is_final=True,
            error=pb.Error(code=code, message=message, detail=detail),
            meta=meta or None,
        )

    # -- capability / health rpcs ----------------------------------------

    def GetCapabilities(self, request, context) -> pb.Capability:
        return self.capability()

    def StreamCapabilities(self, request, context) -> Iterator[pb.Capability]:
        yield self.capability()

    def Health(self, request, context):
        if not self.healthy():
            context.abort(grpc.StatusCode.UNAVAILABLE, "service unhealthy")
        return empty_pb2.Empty()
