"""Per-model gRPC services (clip, face, ocr, vlm)."""
