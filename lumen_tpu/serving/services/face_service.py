"""Face gRPC service: detect / embed / detect-and-embed tasks.

Task surface and meta knobs mirror the reference ``GeneralFaceService``
(``packages/lumen-face/src/lumen_face/general_face/face_service.py:214-590``):
``face_detect`` (conf_threshold, size_min/max, max_faces; the NMS
threshold is a pack-spec constant baked into the compiled program),
``face_embed`` (optional ``landmarks`` JSON in meta), and
``face_detect_and_embed``.
"""

from __future__ import annotations

import json
import logging
import os

import numpy as np

from ...core.config import ServiceConfig
from ...core.result_schemas import FaceItem, FaceV1
from ...models.face import FaceManager
from ...runtime.rknn import require_executable_runtime
from ...utils.qos import service_extra as qos_service_extra
from ...utils.tensorwire import TENSOR_MIME, TensorSpec, tensor_from_payload
from ..base_service import BaseService, InvalidArgument, first_meta_key
from ..registry import TaskDefinition, TaskRegistry

logger = logging.getLogger(__name__)

IMAGE_MIMES = ("image/jpeg", "image/png", "image/webp", "application/octet-stream")

#: tensor/raw input for the detection tasks: any pre-decoded uint8 HWC RGB
#: image (coordinates come back in the tensor's own frame).
FACE_TENSOR_SPEC = TensorSpec("uint8", (None, None, 3))


class FaceService(BaseService):
    def __init__(self, manager: FaceManager, service_name: str = "face"):
        self.manager = manager
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name="face_detect",
                handler=self._detect,
                description="detect faces: bboxes + landmarks + confidences",
                input_mimes=IMAGE_MIMES,
                output_mime=FaceV1.mime(),
                tensor_spec=FACE_TENSOR_SPEC,
            )
        )
        registry.register(
            TaskDefinition(
                name="face_embed",
                handler=self._embed,
                description="embed one face crop (optional landmarks meta)",
                input_mimes=IMAGE_MIMES,
                output_mime=FaceV1.mime(),
            )
        )
        registry.register(
            TaskDefinition(
                name="face_detect_and_embed",
                handler=self._detect_and_embed,
                description="detect all faces and embed each",
                input_mimes=IMAGE_MIMES,
                output_mime=FaceV1.mime(),
                tensor_spec=FACE_TENSOR_SPEC,
            )
        )
        super().__init__(registry)

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        """Tasks this service would register — used by the hub to build a
        degraded placeholder when the real load fails, so the routes answer
        UNAVAILABLE instead of vanishing."""
        return ["face_detect", "face_embed", "face_detect_and_embed"]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "FaceService":
        bs = service_config.backend_settings
        alias, mc = next(iter(service_config.models.items()))
        require_executable_runtime(mc)
        model_dir = os.path.join(cache_dir, "models", mc.model.split("/")[-1])
        manager = FaceManager(
            model_dir,
            dtype=bs.dtype,
            batch_size=bs.batch_size,
            max_batch_latency_ms=bs.max_batch_latency_ms,
            mesh_axes=bs.mesh.axes if bs.mesh else None,
            warmup=bs.warmup,
        )
        manager.initialize()
        return cls(manager)

    def capability(self):
        return self.registry.build_capability(
            model_ids=[self.manager.model_id],
            runtime="jax-tpu",
            max_concurrency=self.manager.batch_size,
            precisions=["bf16", "fp32"],
            extra={
                "det_size": str(self.manager.det_cfg.input_size),
                "embedding_dim": str(self.manager.rec_cfg.embed_dim),
                "bulk_stream": "1",  # many-items-per-stream Infer lane
                # Multi-tenant QoS: WFQ admission state + brownout level
                # of the face-det/face-rec admission queues.
                "qos": qos_service_extra("face"),
                # device topology + replica layout (fleet-internal clients
                # pick endpoints from these instead of probing)
                **self.manager.topology(),
            },
        )

    def healthy(self) -> bool:
        return self.manager._initialized

    def replica_states(self) -> dict:
        from ...runtime.fleet import replica_states_of

        # getattr: the batchers only exist after manager.initialize(), and
        # Health may probe the construct-before-initialize window.
        return replica_states_of(
            getattr(self.manager, "_det_batcher", None),
            getattr(self.manager, "_rec_batcher", None),
        )

    def close(self) -> None:
        self.manager.close()

    # -- handlers ---------------------------------------------------------

    def _det_kwargs(self, meta: dict[str, str]) -> dict:
        kw = {}
        # First alias per arg is ours; the rest are the reference client's
        # exact key names (``general_face/face_service.py:439-443``) so a
        # drop-in client's knobs aren't silently ignored.
        for arg, aliases in (
            ("conf_threshold", ("conf_threshold", "detection_confidence_threshold")),
            ("size_min", ("size_min", "face_size_min")),
            ("size_max", ("size_max", "face_size_max")),
            ("nms_threshold", ("nms_threshold",)),
        ):
            meta_key = first_meta_key(meta, *aliases)
            if meta_key is not None:
                kw[arg] = _float_meta(meta, meta_key)
        if "max_faces" in meta:
            try:
                kw["max_faces"] = int(meta["max_faces"])
            except ValueError as e:
                raise InvalidArgument("meta max_faces must be an integer") from e
        return kw

    def _detect(self, payload: bytes, mime: str, meta: dict[str, str]):
        if mime == TENSOR_MIME:
            # Base class already validated against FACE_TENSOR_SPEC:
            # materialize and go straight to letterbox + detector — no
            # decode pool on this path.
            pixels = tensor_from_payload(payload, meta)
            faces = self._call(
                lambda: self.manager.detect_faces_tensor(
                    pixels, raw=payload, **self._det_kwargs(meta)
                )
            )
            return self._result(faces)
        faces = self._call(lambda: self.manager.detect_faces(payload, **self._det_kwargs(meta)))
        return self._result(faces)

    def _embed(self, payload: bytes, mime: str, meta: dict[str, str]):
        landmarks = None
        if "landmarks" in meta:
            try:
                landmarks = np.asarray(json.loads(meta["landmarks"]), np.float32)
                # Contract allows 5-point OR 68-point landmarks (reference
                # ``backends/base.py:91-103``); 68-point sets reduce to the
                # canonical 5 in the manager.
                if landmarks.shape not in ((5, 2), (68, 2)):
                    raise ValueError(f"expected [5,2] or [68,2], got {landmarks.shape}")
            except (ValueError, json.JSONDecodeError) as e:
                raise InvalidArgument(f"invalid landmarks meta: {e}") from e
        emb = self._call(lambda: self.manager.extract_embedding(payload, landmarks))
        face = FaceItem(
            bbox=[0.0, 0.0, 0.0, 0.0],
            confidence=1.0,
            landmarks=landmarks.tolist() if landmarks is not None else None,
            embedding=[float(x) for x in emb],
        )
        return self._result_items([face])

    def _detect_and_embed(self, payload: bytes, mime: str, meta: dict[str, str]):
        if mime == TENSOR_MIME:
            pixels = tensor_from_payload(payload, meta)
            faces = self._call(
                lambda: self.manager.detect_and_extract_tensor(
                    pixels, raw=payload, **self._det_kwargs(meta)
                )
            )
            return self._result(faces)
        faces = self._call(
            lambda: self.manager.detect_and_extract(payload, **self._det_kwargs(meta))
        )
        return self._result(faces)

    def _call(self, fn):
        try:
            return fn()
        except ValueError as e:
            raise InvalidArgument(f"cannot process image: {e}") from e

    def _result(self, faces):
        items = [
            FaceItem(
                bbox=[float(v) for v in f.bbox],
                confidence=min(max(f.confidence, 0.0), 1.0),
                landmarks=f.landmarks.tolist() if f.landmarks is not None else None,
                embedding=[float(x) for x in f.embedding] if f.embedding is not None else None,
            )
            for f in faces
        ]
        return self._result_items(items)

    def _result_items(self, items):
        body = FaceV1(faces=items, count=len(items), model_id=self.manager.model_id)
        return body.to_json_bytes(), FaceV1.mime(), {}


def _float_meta(meta: dict[str, str], key: str) -> float:
    try:
        return float(meta[key])
    except ValueError as e:
        raise InvalidArgument(f"meta {key!r} must be a number") from e
