"""CLIP gRPC service: embeddings + zero-shot classification tasks.

Covers all three reference service variants in one class, selected by which
model aliases the config carries (the reference picks the variant the same
way in its single-mode server, ``packages/lumen-clip/src/lumen_clip/server.py:240-287``):

- alias ``clip``    -> tasks ``clip_text_embed``, ``clip_image_embed``, and
  ``clip_classify`` / ``clip_scene_classify`` when a dataset is loaded
  (reference ``GeneralCLIPService``, ``clip_service.py:140-183``);
- alias ``bioclip`` -> ``bioclip_{text_embed,image_embed,classify}`` with
  raw-cosine scoring (reference ``BioCLIPService``);
- both aliases      -> additionally ``smartclip_{text_embed,image_embed,
  classify,scene_classify,bioclassify}`` (reference ``SmartCLIPService``,
  including the ``namespace=bioatlas`` meta check at
  ``smartclip_service.py:450-455``).
"""

from __future__ import annotations

import logging
import os

from ...core.config import ServiceConfig
from ...core.result_schemas import EmbeddingV1, LabelsV1, LabelItem
from ...models.clip import CLIPManager
from ...runtime.rknn import require_executable_runtime
from ...utils.qos import service_extra as qos_service_extra
from ...utils.tensorwire import TENSOR_MIME, TensorSpec, tensor_from_payload
from ..base_service import BaseService, InvalidArgument, Unavailable, first_meta_key
from ..registry import TaskDefinition, TaskRegistry

logger = logging.getLogger(__name__)

IMAGE_MIMES = ("image/jpeg", "image/png", "image/webp", "application/octet-stream")


class ClipService(BaseService):
    def __init__(self, managers: dict[str, CLIPManager], service_name: str = "clip"):
        self.managers = managers
        registry = TaskRegistry(service_name)
        clip = managers.get("clip")
        bioclip = managers.get("bioclip")
        if clip is not None:
            self._register_tasks(registry, "clip", clip, scene=True)
        if bioclip is not None:
            self._register_tasks(registry, "bioclip", bioclip, scene=False)
        if clip is not None and bioclip is not None:
            self._register_tasks(registry, "smartclip", clip, scene=True)
            registry.register(
                TaskDefinition(
                    name="smartclip_bioclassify",
                    handler=self._smart_bioclassify,
                    description="species classification (bioatlas namespace)",
                    input_mimes=IMAGE_MIMES,
                    output_mime=LabelsV1.mime(),
                )
            )
        super().__init__(registry)

    def _register_tasks(self, registry: TaskRegistry, prefix: str, mgr: CLIPManager, scene: bool):
        registry.register(
            TaskDefinition(
                name=f"{prefix}_text_embed",
                handler=lambda p, m, meta, _mgr=mgr: self._text_embed(_mgr, p),
                description="text -> unit-norm embedding",
                input_mimes=("text/plain",),
                output_mime=EmbeddingV1.mime(),
            )
        )
        registry.register(
            TaskDefinition(
                name=f"{prefix}_image_embed",
                handler=lambda p, m, meta, _mgr=mgr: self._image_embed(_mgr, p, m, meta),
                description="image -> unit-norm embedding",
                input_mimes=IMAGE_MIMES,
                output_mime=EmbeddingV1.mime(),
                # tensor/raw wire path: accept the exact pre-decoded
                # tensor the clip_resize decode spec produces — callers
                # holding decoded pixels skip JPEG AND the decode pool.
                tensor_spec=TensorSpec("uint8", mgr.tensor_input_shape()),
            )
        )
        if mgr.dataset_name:
            registry.register(
                TaskDefinition(
                    name=f"{prefix}_classify",
                    handler=lambda p, m, meta, _mgr=mgr: self._classify(_mgr, p, meta),
                    description="zero-shot classification against the configured dataset",
                    input_mimes=IMAGE_MIMES,
                    output_mime=LabelsV1.mime(),
                )
            )
        if scene:
            registry.register(
                TaskDefinition(
                    name=f"{prefix}_scene_classify",
                    handler=lambda p, m, meta, _mgr=mgr: self._scene(_mgr, p, meta),
                    description="coarse scene bucket classification",
                    input_mimes=IMAGE_MIMES,
                    output_mime=LabelsV1.mime(),
                )
            )

    # -- factory ----------------------------------------------------------

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:
        """Tasks this service would register for the given config — mirrors
        the alias/dataset selection in ``__init__`` so a degraded
        placeholder exposes the same routes the live service would."""
        by_key = {}
        for alias, mc in service_config.models.items():
            by_key["bioclip" if "bioclip" in alias.lower() else "clip"] = mc

        def tasks(prefix: str, mc, scene: bool) -> list[str]:
            out = [f"{prefix}_text_embed", f"{prefix}_image_embed"]
            if mc.dataset:
                out.append(f"{prefix}_classify")
            if scene:
                out.append(f"{prefix}_scene_classify")
            return out

        expected: list[str] = []
        if "clip" in by_key:
            expected += tasks("clip", by_key["clip"], scene=True)
        if "bioclip" in by_key:
            expected += tasks("bioclip", by_key["bioclip"], scene=False)
        if "clip" in by_key and "bioclip" in by_key:
            expected += tasks("smartclip", by_key["clip"], scene=True)
            expected.append("smartclip_bioclassify")
        return expected

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "ClipService":
        bs = service_config.backend_settings
        managers: dict[str, CLIPManager] = {}
        for alias, mc in service_config.models.items():
            require_executable_runtime(mc)
            key = "bioclip" if "bioclip" in alias.lower() else "clip"
            model_dir = os.path.join(cache_dir, "models", mc.model.split("/")[-1])
            managers[key] = CLIPManager(
                model_dir,
                dataset=mc.dataset,
                dtype=bs.dtype,
                batch_size=bs.batch_size,
                max_batch_latency_ms=bs.max_batch_latency_ms,
                mesh_axes=bs.mesh.axes if bs.mesh else None,
                classify_mode="cosine" if key == "bioclip" else "softmax",
                warmup=bs.warmup,
                quantize=bs.quantize,
                # Scope batcher/gauge names per manager so a clip+bioclip
                # hub never collides on "clip-image" gauges or fleet keys.
                name_prefix=key,
            )
        svc = cls(managers)
        for mgr in managers.values():
            mgr.initialize()
        return svc

    def capability(self):
        ids = [m.model_id for m in self.managers.values()]
        # Routes reflect what initialize() actually chose — a manager that
        # opted into int8 but fell back to bf16 (warmup A/B showed a
        # regression) must not advertise int8.
        routes = sorted({getattr(m, "quant_route", "bf16") for m in self.managers.values()})
        precisions = ["bf16", "fp32"] + (["int8"] if "int8" in routes else [])
        # Device topology + replica layout (the primary manager's view):
        # fleet-internal clients pick endpoints from these keys instead of
        # probing — device_count, mesh_axes, replicas, replica_policy and
        # live replica_states.
        primary = next(iter(self.managers.values()))
        return self.registry.build_capability(
            model_ids=ids,
            runtime=f"jax-{_backend_name()}",
            max_concurrency=max(m.batch_size for m in self.managers.values()),
            precisions=precisions,
            extra={
                "embed_dims": ",".join(str(m.cfg.embed_dim) for m in self.managers.values()),
                "quant_routes": ",".join(routes),
                "bulk_stream": "1",  # many-items-per-stream Infer lane
                # Multi-tenant QoS: WFQ admission state + brownout level
                # of this family's batchers (clip-image/clip-text, plus
                # bioclip-* when both aliases are loaded).
                "qos": qos_service_extra(*self.managers.keys()),
                **primary.topology(),
            },
        )

    def healthy(self) -> bool:
        return all(m._initialized for m in self.managers.values())

    def replica_states(self) -> dict:
        from ...runtime.fleet import replica_states_of

        return replica_states_of(
            *(b for m in self.managers.values()
              for b in (m._image_batcher, m._text_batcher))
        )

    def close(self) -> None:
        for m in self.managers.values():
            m.close()

    # -- handlers ---------------------------------------------------------

    def _text_embed(self, mgr: CLIPManager, payload: bytes):
        try:
            text = payload.decode("utf-8").strip()
        except UnicodeDecodeError as e:
            raise InvalidArgument("payload is not valid UTF-8 text") from e
        if not text:
            raise InvalidArgument("empty text payload")
        vec = mgr.encode_text(text)
        return self._embedding_result(mgr, vec)

    def _image_embed(
        self, mgr: CLIPManager, payload: bytes, mime: str = "",
        meta: dict[str, str] | None = None,
    ):
        if mime == TENSOR_MIME:
            # Pre-validated by the base class against this task's
            # tensor_spec: materialize with one np.frombuffer and go
            # straight to the batcher — the decode pool is never entered.
            try:
                vec = mgr.encode_image_tensor(
                    tensor_from_payload(payload, meta or {}), raw=payload
                )
            except ValueError as e:
                raise InvalidArgument(f"cannot process tensor: {e}") from e
            return self._embedding_result(mgr, vec)
        vec = self._encode_image(mgr, payload)
        return self._embedding_result(mgr, vec)

    def _classify(self, mgr: CLIPManager, payload: bytes, meta: dict[str, str]):
        top_k = _top_k(meta, 5)
        try:
            result = mgr.classify_image(payload, top_k=top_k)
        except RuntimeError as e:
            raise Unavailable(str(e)) from e
        except ValueError as e:
            raise InvalidArgument(f"cannot process image: {e}") from e
        return self._labels_result(mgr, result)

    def _scene(self, mgr: CLIPManager, payload: bytes, meta: dict[str, str]):
        try:
            result = mgr.classify_scene(payload, top_k=_top_k(meta, 3))
        except ValueError as e:
            raise InvalidArgument(f"cannot process image: {e}") from e
        return self._labels_result(mgr, result)

    def _smart_bioclassify(self, payload: bytes, mime: str, meta: dict[str, str]):
        ns = meta.get("namespace", "bioatlas")
        if ns != "bioatlas":
            raise InvalidArgument(f"unsupported namespace {ns!r} (expected 'bioatlas')")
        mgr = self.managers["bioclip"]
        top_k = _top_k(meta, 5)
        try:
            result = mgr.classify_image(payload, top_k=top_k)
        except ValueError as e:
            raise InvalidArgument(f"cannot process image: {e}") from e
        return self._labels_result(mgr, result)

    def _encode_image(self, mgr: CLIPManager, payload: bytes):
        if not payload:
            raise InvalidArgument("empty image payload")
        try:
            return mgr.encode_image(payload)
        except ValueError as e:
            raise InvalidArgument(f"cannot process image: {e}") from e

    @staticmethod
    def _embedding_result(mgr: CLIPManager, vec):
        body = EmbeddingV1(vector=[float(x) for x in vec], dim=int(vec.shape[0]), model_id=mgr.model_id)
        return body.to_json_bytes(), EmbeddingV1.mime(), {}

    @staticmethod
    def _labels_result(mgr: CLIPManager, result):
        body = LabelsV1(
            labels=[LabelItem(label=l, score=s) for l, s in result.labels],
            model_id=mgr.model_id,
        )
        return body.to_json_bytes(), LabelsV1.mime(), {}


def _int_meta(meta: dict[str, str], key: str, default: int) -> int:
    try:
        return int(meta.get(key, default))
    except ValueError as e:
        raise InvalidArgument(f"meta {key!r} must be an integer") from e


def _top_k(meta: dict[str, str], default: int) -> int:
    """Accept our ``top_k`` and the reference client's ``topk``
    (``clip_service.py:317``) so drop-in clients keep their knob."""
    key = first_meta_key(meta, "top_k", "topk")
    return _int_meta(meta, key, default) if key else default


def _backend_name() -> str:
    import jax

    return jax.default_backend()
