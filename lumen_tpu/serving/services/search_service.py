"""Semantic-search gRPC service: per-tenant device-resident ANN index.

Two tasks on the unchanged streaming protocol:

- ``search_query`` — one L2-normalized embedding in, top-k ``(ids,
  scores)`` out. The query vector rides the tensorwire raw-tensor path
  (``tensor/raw`` float32 ``(dim,)``, validated against this task's
  advertised spec BEFORE the handler), so a fleet-internal hop from the
  federation front tier re-decodes nothing; a JSON body (``{"vector":
  [...]}``
  ) is accepted for hand-written clients. Queries submit into a
  per-(tenant, shard) :class:`MicroBatcher` — concurrent searches
  coalesce into ONE jitted matmul + top_k device call, and the WFQ
  admission queue keys them to the INTERACTIVE lane, so a bulk indexing
  convoy browns out before a search ever queues behind it.

- ``search_upsert`` — a batch of vectors + ids in, ``{added, updated,
  total}`` out. The batch rides a ``tensor/bundle`` (ordered: vectors
  float32 ``(N, dim)``, then the ids as a UTF-8 JSON array in a uint8
  tensor); JSON bodies work too. Upserts never touch the query batcher:
  the handler writes the device buffers directly in bounded chunks
  (``LUMEN_ANN_UPSERT_CHUNK``) under whatever lane the request arrived on
  — the bulk streaming lane auto-tags ``bulk`` — so indexing a library
  cannot occupy interactive batch slots (the PR 8 QoS invariant, proven
  by the ``search`` bench phase).

Sharding: a ``shard`` request meta pins the write/read to one named
shard — that is the FEDERATION hop shape (the front tier owns placement:
it keys the hash ring by ``ann/{tenant}/{i}`` and fans out, see
``serving/router.py``). Without ``shard``, a direct single-host caller
gets the same placement function locally (``runtime/ann.shard_of``) on
upsert and a fan-over-all-local-shards merge on query, so a standalone
library reshards identically when a fleet grows around it.
"""

from __future__ import annotations

import json
import logging
import threading

import numpy as np

from ...core.config import ServiceConfig
from ...runtime.ann import (
    AnnIndex,
    ann_k_cap,
    ann_shards,
    merge_topk,
)
from ...runtime.batcher import MicroBatcher
from ...utils.env import env_int
from ...utils.qos import current_qos, service_extra as qos_service_extra
from ...utils.tensorwire import (
    BUNDLE_MIME,
    TENSOR_MIME,
    TensorSpec,
    tensor_from_payload,
    unpack_bundle,
)
from ..base_service import BaseService, InvalidArgument
from ..registry import TaskDefinition, TaskRegistry

logger = logging.getLogger(__name__)

SEARCH_QUERY_TASK = "search_query"
SEARCH_UPSERT_TASK = "search_upsert"

#: embedding dimensionality of the index (must match the CLIP family
#: feeding it; 512 is the reference ViT-B tower).
DIM_ENV = "LUMEN_ANN_DIM"
#: rows per device write during one upsert request — bounds the scatter
#: bucket ladder and interleaves indexing with query dispatches.
UPSERT_CHUNK_ENV = "LUMEN_ANN_UPSERT_CHUNK"


def ann_dim() -> int:
    return env_int(DIM_ENV, 512, minimum=1)


def upsert_chunk() -> int:
    return env_int(UPSERT_CHUNK_ENV, 1024, minimum=1)


class SearchService(BaseService):
    def __init__(
        self,
        dim: int | None = None,
        batch_size: int = 8,
        max_latency_ms: float = 2.0,
        service_name: str = "search",
    ):
        self.dim = int(dim or ann_dim())
        self.index = AnnIndex(self.dim)
        self._batch_size = max(1, batch_size)
        self._max_latency_ms = max_latency_ms
        self._batchers: dict[tuple[str, str], MicroBatcher] = {}
        self._batcher_lock = threading.Lock()
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name=SEARCH_QUERY_TASK,
                handler=self._query,
                description="embedding -> top-k (ids, scores) from the tenant's ANN index",
                input_mimes=(TENSOR_MIME, "application/json"),
                output_mime="application/json",
                tensor_spec=TensorSpec("float32", (self.dim,)),
            )
        )
        registry.register(
            TaskDefinition(
                name=SEARCH_UPSERT_TASK,
                handler=self._upsert,
                description="vector batch + ids -> index upsert {added, updated, total}",
                input_mimes=(BUNDLE_MIME, "application/json"),
                output_mime="application/json",
                # A 100k-vector f32/512 batch is ~200MB; keep headroom
                # under the 64MB gRPC frame by chunking client-side, but
                # allow a healthy bundle.
                max_payload_bytes=64 * 1024 * 1024,
            )
        )
        super().__init__(registry)

    # -- factory ----------------------------------------------------------

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        return [SEARCH_QUERY_TASK, SEARCH_UPSERT_TASK]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "SearchService":  # noqa: ARG003
        bs = service_config.backend_settings
        return cls(
            batch_size=bs.batch_size,
            max_latency_ms=bs.max_batch_latency_ms,
        )

    def capability(self):
        return self.registry.build_capability(
            model_ids=["ann-exact"],
            runtime=f"jax-{_backend_name()}",
            max_concurrency=self._batch_size,
            extra={
                "ann_dim": str(self.dim),
                "ann_shards": str(ann_shards()),
                "bulk_stream": "1",
                "qos": qos_service_extra("search"),
            },
        )

    def healthy(self) -> bool:
        return True

    def close(self) -> None:
        with self._batcher_lock:
            batchers, self._batchers = list(self._batchers.values()), {}
        for b in batchers:
            b.close()

    # -- query path -------------------------------------------------------

    def _batcher(self, tenant: str, shard: str) -> MicroBatcher:
        """Lazily-started interactive batcher for one (tenant, shard):
        its ``fn`` is the shard's dispatch-only ``query_raw`` at the k
        cap, so coalesced searches share ONE compiled program and slice
        their own k after the fetch."""
        key = (tenant, shard)
        with self._batcher_lock:
            got = self._batchers.get(key)
            if got is None:
                shard_obj = self.index.shard(tenant, shard)

                def fn(batch: np.ndarray, n_valid: int, _s=shard_obj):  # noqa: ARG001
                    scores, idx = _s.query_raw(np.asarray(batch), ann_k_cap())
                    return scores, idx

                got = MicroBatcher(
                    fn,
                    max_batch=self._batch_size,
                    max_latency_ms=self._max_latency_ms,
                    name=f"search:{tenant}:{shard}",
                ).start()
                self._batchers[key] = got
            return got

    def _query(self, payload: bytes, mime: str, meta: dict[str, str]):
        vec = self._parse_query_vector(payload, mime, meta)
        k = _int_meta(meta, "k", 10)
        if k < 1:
            raise InvalidArgument("meta 'k' must be >= 1")
        tenant = _tenant(meta)
        shard = meta.get("shard")
        if shard is not None:
            shards = [shard]
        else:
            # Direct (unfederated) query: fan over every local shard of
            # the tenant and merge — identical results to the fleet path.
            shards = sorted(self.index.shards_for(tenant)) or ["0"]
        parts: list[tuple[list[str], list[float]]] = []
        futures = [
            (self.index.shard(tenant, sh), self._batcher(tenant, sh).submit(vec))
            for sh in shards
        ]
        for shard_obj, fut in futures:
            scores, idx = fut.result()
            ids_rows, score_rows = shard_obj.resolve_rows(scores, idx)
            parts.append((ids_rows[0], score_rows[0]))
        ids, scores = merge_topk(parts, k)
        body = {
            "ids": ids,
            "scores": scores,
            "k": k,
            "shards": len(shards),
            "tenant": tenant,
        }
        return json.dumps(body).encode(), "application/json", {}

    def _parse_query_vector(
        self, payload: bytes, mime: str, meta: dict[str, str]
    ) -> np.ndarray:
        if mime == TENSOR_MIME:
            # Pre-validated against tensor_spec by the base class.
            return np.asarray(tensor_from_payload(payload, meta), np.float32)
        try:
            body = json.loads(payload.decode("utf-8"))
            vec = np.asarray(body["vector"], np.float32)
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            raise InvalidArgument(
                f"query body must be tensor/raw or JSON {{'vector': [...]}}: {e}"
            ) from e
        if vec.shape != (self.dim,):
            raise InvalidArgument(
                f"query vector shape {vec.shape} != ({self.dim},)"
            )
        return vec

    # -- upsert path ------------------------------------------------------

    def _upsert(self, payload: bytes, mime: str, meta: dict[str, str]):
        ids, vecs = self._parse_upsert(payload, mime)
        tenant = _tenant(meta)
        shard = meta.get("shard")
        added = updated = 0
        # Bounded device writes: one request's batch lands chunk by chunk,
        # so the scatter bucket ladder stays small and a query dispatched
        # mid-upsert interleaves instead of waiting out one giant write.
        # Runs on the REQUEST's lane (bulk streaming auto-tags bulk) and
        # never enters the interactive query batcher.
        step = upsert_chunk()
        for lo in range(0, len(ids), step):
            a, u = self.index.upsert(
                tenant, ids[lo : lo + step], vecs[lo : lo + step], shard=shard
            )
            added += a
            updated += u
        total = sum(s.count for s in self.index.shards_for(tenant).values())
        body = {
            "added": added,
            "updated": updated,
            "total": total,
            "tenant": tenant,
        }
        return json.dumps(body).encode(), "application/json", {}

    def _parse_upsert(
        self, payload: bytes, mime: str
    ) -> tuple[list[str], np.ndarray]:
        if mime == BUNDLE_MIME:
            try:
                tensors = unpack_bundle(payload)
            except ValueError as e:
                raise InvalidArgument(f"bad tensor bundle: {e}") from e
            if len(tensors) != 2:
                raise InvalidArgument(
                    f"upsert bundle must hold [vectors, ids_json], got "
                    f"{len(tensors)} tensors"
                )
            vecs = np.asarray(tensors[0], np.float32)
            try:
                ids = json.loads(bytes(np.asarray(tensors[1], np.uint8)).decode("utf-8"))
            except (ValueError, UnicodeDecodeError) as e:
                raise InvalidArgument(f"ids tensor is not a JSON array: {e}") from e
        else:
            try:
                body = json.loads(payload.decode("utf-8"))
                ids = body["ids"]
                vecs = np.asarray(body["vectors"], np.float32)
            except (ValueError, KeyError, UnicodeDecodeError) as e:
                raise InvalidArgument(
                    "upsert body must be tensor/bundle or JSON "
                    f"{{'ids': [...], 'vectors': [[...]]}}: {e}"
                ) from e
        if not isinstance(ids, list) or not all(isinstance(i, str) for i in ids):
            raise InvalidArgument("ids must be a JSON array of strings")
        if vecs.ndim != 2 or vecs.shape[1] != self.dim:
            raise InvalidArgument(
                f"vectors must be (N, {self.dim}) float32, got {vecs.shape}"
            )
        if len(ids) != vecs.shape[0]:
            raise InvalidArgument(
                f"{len(ids)} ids but {vecs.shape[0]} vectors"
            )
        if not ids:
            raise InvalidArgument("empty upsert batch")
        return ids, vecs


def _tenant(meta: dict[str, str]) -> str:
    """Tenant identity: explicit request meta first (the federation front
    forwards it), then the QoS contextvar the base service activated from
    invocation metadata, else the default tenant."""
    got = meta.get("tenant")
    if got:
        return got
    qos_tenant = current_qos()[0]
    return qos_tenant or "default"


def _int_meta(meta: dict[str, str], key: str, default: int) -> int:
    try:
        return int(meta.get(key, default))
    except ValueError as e:
        raise InvalidArgument(f"meta {key!r} must be an integer") from e


def _backend_name() -> str:
    import jax

    return jax.default_backend()
