"""VLM gRPC service: ``vlm_generate`` + ``vlm_generate_stream``.

Task surface mirrors the reference ``GeneralFastVLMService``
(``packages/lumen-vlm/src/lumen_vlm/fastvlm/fastvlm_service.py:47-621``):
chat messages ride as JSON in request ``meta`` (``_extract_messages_from_
meta:539-560``), the image is the payload, generation knobs are meta
fields. Unlike the reference — whose "stream" task collects every chunk
into one response (``:492-506``) — ``vlm_generate_stream`` here emits true
incremental ``InferResponse`` chunks through the streaming path in
``BaseService``.
"""

from __future__ import annotations

import json
import logging
import os

from ...core.config import ServiceConfig
from ...core.result_schemas import TextGenerationV1
from ...models.vlm import ChatMessage, VLMManager
from ...runtime.rknn import require_executable_runtime
from ...utils.qos import service_extra as qos_service_extra
from ..base_service import BaseService, InvalidArgument
from ..registry import TaskDefinition, TaskRegistry

logger = logging.getLogger(__name__)

IMAGE_MIMES = ("image/jpeg", "image/png", "image/webp", "application/octet-stream")


class VlmService(BaseService):
    def __init__(self, manager: VLMManager, service_name: str = "vlm"):
        self.manager = manager
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name="vlm_generate",
                handler=self._generate,
                description="multimodal caption/chat generation (single response)",
                input_mimes=IMAGE_MIMES,
                output_mime=TextGenerationV1.mime(),
            )
        )
        registry.register(
            TaskDefinition(
                name="vlm_generate_stream",
                handler=self._generate_stream,
                description="multimodal generation with incremental streaming chunks",
                input_mimes=IMAGE_MIMES,
                output_mime=TextGenerationV1.mime(),
            )
        )
        super().__init__(registry)

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        """Tasks this service would register (degraded-placeholder routes)."""
        return ["vlm_generate", "vlm_generate_stream"]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "VlmService":
        bs = service_config.backend_settings
        alias, mc = next(iter(service_config.models.items()))
        require_executable_runtime(mc)
        model_dir = os.path.join(cache_dir, "models", mc.model.split("/")[-1])
        kw = {}
        if bs.batch_buckets:
            kw["prefill_buckets"] = tuple(bs.batch_buckets)
        # batch_size here is the decode batch (requests coalesced per
        # program) and the stream-cache bound — NOT a CLIP-style image
        # batch. Configs written before per-family sizing may carry the
        # headline batch (e.g. 256); clamp to a sane decode width instead
        # of allocating hundreds of KV caches.
        gen_batch = max(1, min(bs.batch_size, 16))
        if gen_batch != bs.batch_size:
            logger.warning(
                "vlm batch_size %d clamped to %d (decode batch)", bs.batch_size, gen_batch
            )
        manager = VLMManager(
            model_dir,
            dtype=bs.dtype,
            warmup=bs.warmup,
            gen_batch_size=gen_batch,
            gen_batch_latency_ms=bs.max_batch_latency_ms,
            scheduler=bs.scheduler,
            gen_slots=gen_batch,  # pool width = configured decode batch
            gen_block=bs.decode_block,
            quantize=bs.quantize,
            mesh_axes=bs.mesh.axes if bs.mesh else None,
            **kw,
        )
        manager.initialize()
        return cls(manager)

    def capability(self):
        # Suggested client concurrency = the decode width the scheduler
        # actually coalesces (slot-pool width x engine replicas for
        # continuous, batcher width otherwise) — advertising 1 made
        # clients serialize requests the server batches fine (reference
        # field semantics: proto Capability.max_concurrency, "Suggested
        # max concurrency").
        width = (
            self.manager.gen_slots * max(1, len(self.manager._engines))
            if self.manager.scheduler == "continuous"
            else self.manager.gen_batch_size
        )
        return self.registry.build_capability(
            model_ids=[self.manager.model_id],
            runtime="jax-tpu",
            max_concurrency=max(1, width),
            # Routes reflect what initialize() actually chose — a manager
            # that opted into int8 but fell back to bf16 (warmup A/B
            # showed a decode regression) must not advertise int8.
            precisions=["bf16", "fp32"]
            + (["int8"] if self.manager.quant_route == "int8" else []),
            extra={
                "max_new_cap": str(self.manager.max_new_cap),
                "max_seq": str(self.manager.max_seq),
                "vision_tokens": str(self.manager.vision_tokens),
                "vocab_size": str(self.manager.cfg.decoder.vocab_size),
                "bulk_stream": "1",  # many-items-per-stream Infer lane
                # Multi-tenant QoS: the VLM generation batcher schedules
                # its own slot pool, so this reports the quota/lane
                # config (the gRPC-layer gate still applies to it).
                "qos": qos_service_extra("vlm"),
                "quant_route": self.manager.quant_route,
                # Decode scheduling on the wire: which scheduler actually
                # serves (env knob may have overridden the config) and how
                # KV is laid out — previously constructor-only and
                # invisible to clients/dashboards.
                "scheduler": self.manager.scheduler,
                "kv_layout": self.manager.kv_layout(),
                **self.manager.topology(),
            },
        )

    def healthy(self) -> bool:
        return self.manager._initialized

    def close(self) -> None:
        self.manager.close()

    # -- request parsing ---------------------------------------------------

    def _parse_request(self, payload: bytes, meta: dict[str, str]):
        raw = meta.get("messages")
        if not raw:
            raise InvalidArgument("meta 'messages' (JSON list of {role, content}) is required")
        try:
            entries = json.loads(raw)
        except json.JSONDecodeError as e:
            raise InvalidArgument(f"meta 'messages' is not valid JSON: {e}") from e
        if not isinstance(entries, list) or not entries:
            raise InvalidArgument("meta 'messages' must be a non-empty JSON list")
        messages = []
        for entry in entries:
            if not isinstance(entry, dict) or "role" not in entry or "content" not in entry:
                raise InvalidArgument("each message needs 'role' and 'content'")
            messages.append(ChatMessage(role=str(entry["role"]), content=str(entry["content"])))

        kw = {}
        for key, cast in (
            ("max_new_tokens", int),
            ("temperature", float),
            ("top_p", float),
            ("repetition_penalty", float),
        ):
            if key in meta:
                try:
                    kw[key] = cast(meta[key])
                except ValueError as e:
                    raise InvalidArgument(f"meta {key!r} must be a {cast.__name__}") from e
        if "do_sample" in meta:
            kw["do_sample"] = meta["do_sample"].lower() in ("1", "true", "yes")
        if "add_generation_prompt" in meta:
            # Reference knob (``fastvlm_service.py:398``): render the chat
            # template without the trailing assistant turn when false.
            kw["add_generation_prompt"] = meta["add_generation_prompt"].lower() in ("1", "true", "yes")
        if "stop_sequences" in meta:
            try:
                stops = json.loads(meta["stop_sequences"])
            except json.JSONDecodeError:
                stops = [meta["stop_sequences"]]
            if not isinstance(stops, list):
                stops = [str(stops)]
            kw["stop_sequences"] = [str(s) for s in stops]
        return messages, payload or None, kw

    # -- handlers ----------------------------------------------------------

    def _generate(self, payload: bytes, mime: str, meta: dict[str, str]):
        messages, image, kw = self._parse_request(payload, meta)
        try:
            result = self.manager.generate(messages, image_bytes=image, **kw)
        except ValueError as e:
            # bad image bytes / over-long prompt -> client error, not INTERNAL
            raise InvalidArgument(f"cannot process request: {e}") from e
        body = TextGenerationV1(
            text=result.text,
            finish_reason=result.finish_reason,
            generated_tokens=len(result.tokens),
            input_tokens=result.input_tokens,
            model_id=self.manager.model_id,
            metadata=result.metadata,
        )
        return body.to_json_bytes(), TextGenerationV1.mime(), {}

    def _generate_stream(self, payload: bytes, mime: str, meta: dict[str, str]):
        messages, image, kw = self._parse_request(payload, meta)

        def chunks():
            pieces: list[str] = []
            n_chunks = 0
            stream = _reraise_value_errors(
                self.manager.generate_stream(messages, image_bytes=image, **kw)
            )
            for chunk in stream:
                if chunk.is_final:
                    body = TextGenerationV1(
                        text="".join(pieces),
                        finish_reason=str(chunk.metadata.get("finish_reason", "stop")),
                        generated_tokens=int(chunk.metadata.get("generated_tokens", 0)),
                        input_tokens=int(chunk.metadata.get("input_tokens", 0)),
                        model_id=self.manager.model_id,
                        metadata={**chunk.metadata, "streaming_chunks": n_chunks},
                    )
                    yield body.to_json_bytes(), TextGenerationV1.mime(), {}
                else:
                    pieces.append(chunk.text)
                    n_chunks += 1
                    yield (
                        chunk.text.encode("utf-8"),
                        "text/plain; charset=utf-8",
                        {"chunk": "delta"},
                    )

        return chunks()


def _reraise_value_errors(it):
    """Map manager ValueErrors (bad image, over-long prompt) to the wire
    INVALID_ARGUMENT code; ``BaseService._stream_out`` handles the rest."""
    try:
        yield from it
    except ValueError as e:
        raise InvalidArgument(f"cannot process request: {e}") from e
