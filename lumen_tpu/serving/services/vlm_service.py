"""VLM gRPC service: ``vlm_generate`` + ``vlm_generate_stream``.

Task surface mirrors the reference ``GeneralFastVLMService``
(``packages/lumen-vlm/src/lumen_vlm/fastvlm/fastvlm_service.py:47-621``):
chat messages ride as JSON in request ``meta`` (``_extract_messages_from_
meta:539-560``), the image is the payload, generation knobs are meta
fields. Unlike the reference — whose "stream" task collects every chunk
into one response (``:492-506``) — ``vlm_generate_stream`` here emits true
incremental ``InferResponse`` chunks through the streaming path in
``BaseService``.
"""

from __future__ import annotations

import json
import logging
import os

from ...core.config import ServiceConfig
from ...core.result_schemas import TextGenerationV1
from ...models.vlm import ChatMessage, VLMManager
from ...runtime.rknn import require_executable_runtime
from ...utils.qos import service_extra as qos_service_extra
from ...utils.metrics import metrics
from ..base_service import BaseService, InvalidArgument, _Assembly
from ..registry import TaskDefinition, TaskRegistry
from ..router import advertised_fed_role

logger = logging.getLogger(__name__)

IMAGE_MIMES = ("image/jpeg", "image/png", "image/webp", "application/octet-stream")


class VlmService(BaseService):
    def __init__(self, manager: VLMManager, service_name: str = "vlm"):
        self.manager = manager
        registry = TaskRegistry(service_name)
        registry.register(
            TaskDefinition(
                name="vlm_generate",
                handler=self._generate,
                description="multimodal caption/chat generation (single response)",
                input_mimes=IMAGE_MIMES,
                output_mime=TextGenerationV1.mime(),
            )
        )
        registry.register(
            TaskDefinition(
                name="vlm_generate_stream",
                handler=self._generate_stream,
                description="multimodal generation with incremental streaming chunks",
                input_mimes=IMAGE_MIMES,
                output_mime=TextGenerationV1.mime(),
            )
        )
        super().__init__(registry)

    @classmethod
    def expected_tasks(cls, service_config: ServiceConfig) -> list[str]:  # noqa: ARG003
        """Tasks this service would register (degraded-placeholder routes)."""
        return ["vlm_generate", "vlm_generate_stream"]

    @classmethod
    def from_config(cls, service_config: ServiceConfig, cache_dir: str) -> "VlmService":
        bs = service_config.backend_settings
        alias, mc = next(iter(service_config.models.items()))
        require_executable_runtime(mc)
        model_dir = os.path.join(cache_dir, "models", mc.model.split("/")[-1])
        kw = {}
        if bs.batch_buckets:
            kw["prefill_buckets"] = tuple(bs.batch_buckets)
        # batch_size here is the decode batch (requests coalesced per
        # program) and the stream-cache bound — NOT a CLIP-style image
        # batch. Configs written before per-family sizing may carry the
        # headline batch (e.g. 256); clamp to a sane decode width instead
        # of allocating hundreds of KV caches.
        gen_batch = max(1, min(bs.batch_size, 16))
        if gen_batch != bs.batch_size:
            logger.warning(
                "vlm batch_size %d clamped to %d (decode batch)", bs.batch_size, gen_batch
            )
        manager = VLMManager(
            model_dir,
            dtype=bs.dtype,
            warmup=bs.warmup,
            gen_batch_size=gen_batch,
            gen_batch_latency_ms=bs.max_batch_latency_ms,
            scheduler=bs.scheduler,
            gen_slots=gen_batch,  # pool width = configured decode batch
            gen_block=bs.decode_block,
            quantize=bs.quantize,
            mesh_axes=bs.mesh.axes if bs.mesh else None,
            **kw,
        )
        manager.initialize()
        return cls(manager)

    def capability(self):
        # Suggested client concurrency = the decode width the scheduler
        # actually coalesces (slot-pool width x engine replicas for
        # continuous, batcher width otherwise) — advertising 1 made
        # clients serialize requests the server batches fine (reference
        # field semantics: proto Capability.max_concurrency, "Suggested
        # max concurrency").
        width = (
            self.manager.gen_slots * max(1, len(self.manager._engines))
            if self.manager.scheduler == "continuous"
            else self.manager.gen_batch_size
        )
        return self.registry.build_capability(
            model_ids=[self.manager.model_id],
            runtime="jax-tpu",
            max_concurrency=max(1, width),
            # Routes reflect what initialize() actually chose — a manager
            # that opted into int8 but fell back to bf16 (warmup A/B
            # showed a decode regression) must not advertise int8.
            precisions=["bf16", "fp32"]
            + (["int8"] if self.manager.quant_route == "int8" else []),
            extra={
                "max_new_cap": str(self.manager.max_new_cap),
                "max_seq": str(self.manager.max_seq),
                "vision_tokens": str(self.manager.vision_tokens),
                "vocab_size": str(self.manager.cfg.decoder.vocab_size),
                "bulk_stream": "1",  # many-items-per-stream Infer lane
                # Multi-tenant QoS: the VLM generation batcher schedules
                # its own slot pool, so this reports the quota/lane
                # config (the gRPC-layer gate still applies to it).
                "qos": qos_service_extra("vlm"),
                "quant_route": self.manager.quant_route,
                # Decode scheduling on the wire: which scheduler actually
                # serves (env knob may have overridden the config) and how
                # KV is laid out — previously constructor-only and
                # invisible to clients/dashboards.
                "scheduler": self.manager.scheduler,
                "kv_layout": self.manager.kv_layout(),
                **self.manager.topology(),
                # Disaggregation lane only when configured — unconfigured
                # capability records stay byte-identical.
                **({"fed_role": r} if (r := advertised_fed_role()) else {}),
            },
        )

    def healthy(self) -> bool:
        return self.manager._initialized

    def close(self) -> None:
        self.manager.close()

    # -- request parsing ---------------------------------------------------

    def _parse_request(self, payload: bytes, meta: dict[str, str]):
        raw = meta.get("messages")
        if not raw:
            raise InvalidArgument("meta 'messages' (JSON list of {role, content}) is required")
        try:
            entries = json.loads(raw)
        except json.JSONDecodeError as e:
            raise InvalidArgument(f"meta 'messages' is not valid JSON: {e}") from e
        if not isinstance(entries, list) or not entries:
            raise InvalidArgument("meta 'messages' must be a non-empty JSON list")
        messages = []
        for entry in entries:
            if not isinstance(entry, dict) or "role" not in entry or "content" not in entry:
                raise InvalidArgument("each message needs 'role' and 'content'")
            messages.append(ChatMessage(role=str(entry["role"]), content=str(entry["content"])))

        kw = {}
        for key, cast in (
            ("max_new_tokens", int),
            ("temperature", float),
            ("top_p", float),
            ("repetition_penalty", float),
        ):
            if key in meta:
                try:
                    kw[key] = cast(meta[key])
                except ValueError as e:
                    raise InvalidArgument(f"meta {key!r} must be a {cast.__name__}") from e
        if "do_sample" in meta:
            kw["do_sample"] = meta["do_sample"].lower() in ("1", "true", "yes")
        if "add_generation_prompt" in meta:
            # Reference knob (``fastvlm_service.py:398``): render the chat
            # template without the trailing assistant turn when false.
            kw["add_generation_prompt"] = meta["add_generation_prompt"].lower() in ("1", "true", "yes")
        if "stop_sequences" in meta:
            try:
                stops = json.loads(meta["stop_sequences"])
            except json.JSONDecodeError:
                stops = [meta["stop_sequences"]]
            if not isinstance(stops, list):
                stops = [str(stops)]
            kw["stop_sequences"] = [str(s) for s in stops]
        return messages, payload or None, kw

    # -- handlers ----------------------------------------------------------

    def _generate(self, payload: bytes, mime: str, meta: dict[str, str]):
        messages, image, kw = self._parse_request(payload, meta)
        try:
            result = self.manager.generate(messages, image_bytes=image, **kw)
        except ValueError as e:
            # bad image bytes / over-long prompt -> client error, not INTERNAL
            raise InvalidArgument(f"cannot process request: {e}") from e
        body = TextGenerationV1(
            text=result.text,
            finish_reason=result.finish_reason,
            generated_tokens=len(result.tokens),
            input_tokens=result.input_tokens,
            model_id=self.manager.model_id,
            metadata=result.metadata,
        )
        return body.to_json_bytes(), TextGenerationV1.mime(), {}

    def _generate_stream(self, payload: bytes, mime: str, meta: dict[str, str]):
        messages, image, kw = self._parse_request(payload, meta)

        def chunks():
            pieces: list[str] = []
            n_chunks = 0
            stream = _reraise_value_errors(
                self.manager.generate_stream(messages, image_bytes=image, **kw)
            )
            for chunk in stream:
                if chunk.is_final:
                    body = TextGenerationV1(
                        text="".join(pieces),
                        finish_reason=str(chunk.metadata.get("finish_reason", "stop")),
                        generated_tokens=int(chunk.metadata.get("generated_tokens", 0)),
                        input_tokens=int(chunk.metadata.get("input_tokens", 0)),
                        model_id=self.manager.model_id,
                        metadata={**chunk.metadata, "streaming_chunks": n_chunks},
                    )
                    yield body.to_json_bytes(), TextGenerationV1.mime(), {}
                else:
                    pieces.append(chunk.text)
                    n_chunks += 1
                    yield (
                        chunk.text.encode("utf-8"),
                        "text/plain; charset=utf-8",
                        {"chunk": "delta"},
                    )

        return chunks()


    # -- disaggregated decode: the fed_kv_put sink --------------------------

    def handle_kv_put(self, first, request_iterator, context):  # noqa: ARG002
        """Server half of the KV page-migration protocol, attached as
        ``HubRouter.kv_migration`` on decode-capable boots.

        Two ops share the reserved ``fed_kv_put`` task:

        - ``offer``: the prefill host ships the prompt's chain-key
          manifest; we answer how many LEADING pages our prefix cache
          already holds (advisory peek — the commit re-resolves
          authoritatively on the loop thread). Those pages migrate as
          references; only the missed suffix rides the commit.
        - ``commit``: chunked ``tensor/bundle`` frames carrying the
          sliced page payload + exact decode state. We rebuild the spill
          record, admit it via ``submit_migrated`` (zero re-prefill),
          relay the engine's token stream back as ``fed_kv: tok`` frames,
          and finish with a ``done`` frame. Every refusal is typed and
          in-band — the prefill host resumes from its own snapshot, so
          nothing here can lose a row.
        """
        from ...models.vlm import migration
        from ...runtime.federation import note_migration
        from ..proto import ml_service_pb2 as pb

        cid = first.correlation_id

        def refuse(code, message, detail="", marker="refused"):
            note_migration(in_rejected=1)
            metrics.count("fed_kv_in_rejected")
            return pb.InferResponse(
                correlation_id=cid,
                is_final=True,
                meta={"fed_kv": marker},
                error=pb.Error(code=code, message=message, detail=detail),
            )

        mgr = self.manager
        eng = mgr._pick_engine() if mgr._continuous is not None else None
        if eng is None:
            yield refuse(
                pb.ERROR_CODE_UNAVAILABLE,
                "this host runs no continuous-batching engine",
                "fed_kv_put needs the paged continuous scheduler "
                "(scheduler=continuous); the prefill host decodes locally",
            )
            return
        op = first.meta.get("op", "")
        if op == "offer":
            yield self._kv_offer_answer(eng, first, pb)
            return
        if op != "commit":
            yield refuse(
                pb.ERROR_CODE_INVALID_ARGUMENT,
                f"fed_kv_put op {op!r} unknown",
                "expected meta op=offer|commit",
            )
            return

        # Reassemble the chunked commit payload (same seq/total protocol
        # as any chunked upload).
        it = iter(request_iterator)
        asm = _Assembly()
        asm.add(first)
        while not asm.complete:
            nxt = next(it, None)
            if nxt is None:
                yield refuse(
                    pb.ERROR_CODE_INVALID_ARGUMENT,
                    f"fed_kv_put commit stream ended after "
                    f"{len(asm.chunks)} of {asm.total} chunk(s)",
                )
                return
            asm.add(nxt)
        blob = asm.payload()
        try:
            m = migration.parse_commit_meta(asm.meta)
            leaves = migration.unpack_payload(blob, m["crc"])
        except ValueError as e:
            yield refuse(pb.ERROR_CODE_INVALID_ARGUMENT, str(e))
            return
        try:
            req, rec = self._kv_build_row(eng, m, leaves, len(blob))
        except ValueError as e:
            yield refuse(pb.ERROR_CODE_INVALID_ARGUMENT, str(e))
            return
        try:
            eng.submit_migrated(
                req, rec, manifest=m["manifest"], n_shared=m["n_shared"]
            )
        except (ValueError, RuntimeError) as e:
            yield refuse(
                pb.ERROR_CODE_UNAVAILABLE,
                f"cannot admit migrated row: {e}",
                "the prefill host decodes locally",
            )
            return
        note_migration(in_commits=1, in_bytes=len(blob))
        metrics.count("fed_kv_in_commits")
        yield from self._kv_stream_tokens(req, cid, pb, refuse)

    @staticmethod
    def _kv_offer_answer(eng, first, pb):
        from ...models.vlm import migration

        try:
            keys = migration.manifest_from_csv(first.meta.get("manifest", ""))
        except ValueError:
            keys = []
        hit = 0
        if keys and eng.prefix is not None:
            try:
                # Advisory read off the loop thread (PrefixCache.peek is
                # mutation-free); any exception answers 0 — the prefill
                # host then ships full contents, which is always correct.
                hit = eng.prefix.peek(keys)
            except Exception:  # noqa: BLE001 - advisory only
                hit = 0
        return pb.InferResponse(
            correlation_id=first.correlation_id,
            is_final=True,
            meta={"fed_kv": "ok", "hit": str(hit)},
        )

    @staticmethod
    def _kv_build_row(eng, m: dict, leaves: list, nbytes: int):
        """Rebuild the engine-side request + spill record from validated
        commit meta and unpacked wire leaves. Raises ValueError (mapped
        to INVALID_ARGUMENT) on any layout mismatch with THIS host's
        model — a heterogeneous fleet must refuse loudly, not scatter
        garbage into the pool."""
        import queue

        import jax
        import numpy as np

        from ...models.vlm.continuous import _Request, _SpillRecord
        from ...models.vlm import migration

        # The treedef cannot ride the wire (a jax object); rebuild it
        # from OUR pool's container structure — leaf values are
        # irrelevant to tree structure, and a structure mismatch is
        # exactly the layout incompatibility we must reject.
        tmpl_leaves, treedef = jax.tree.flatten(
            {"pages": eng.pool["caches"], "seen": 0}
        )
        n_page_leaves = len(tmpl_leaves) - 1
        if m["n_page_leaves"] != n_page_leaves:
            raise ValueError(
                f"page layout mismatch: peer ships {m['n_page_leaves']} "
                f"page leaves, this model has {n_page_leaves}"
            )
        if m["page_size"] != eng.page_size:
            raise ValueError(
                f"page size mismatch: peer uses {m['page_size']}, "
                f"this host uses {eng.page_size}"
            )
        if len(leaves) != n_page_leaves + 3:
            raise ValueError(
                f"commit payload carries {len(leaves)} tensors; expected "
                f"{n_page_leaves + 3} (page stacks..., seen, rng, prompt_ids)"
            )
        n_fresh = m["n_pages"] - m["n_shared"]
        for i in range(n_page_leaves):
            if int(leaves[i].shape[0]) != n_fresh:
                raise ValueError(
                    f"page leaf #{i} carries {int(leaves[i].shape[0])} "
                    f"page(s); commit declared {n_fresh}"
                )
        n_pad = 1
        while n_pad < max(1, n_fresh):
            n_pad *= 2
        padded = migration.pad_pages(
            leaves[: n_page_leaves + 1], n_page_leaves, n_pad
        )
        rng = np.asarray(leaves[-2])
        prompt_ids = np.asarray(leaves[-1])
        if prompt_ids.ndim != 2 or prompt_ids.shape[0] != 1:
            raise ValueError(
                f"prompt_ids must be [1, S]; got shape {prompt_ids.shape}"
            )
        req = _Request(
            embeds=None,
            positions=None,
            length=None,
            prompt_ids=prompt_ids,
            max_new=m["max_new"],
            temperature=m["temperature"],
            top_p=m["top_p"],
            do_sample=m["do_sample"],
            repetition_penalty=m["repetition_penalty"],
            rng=rng,
            stream_q=queue.SimpleQueue(),
        )
        rec = _SpillRecord(
            n_pages=n_fresh,
            n_pad=n_pad,
            nbytes=nbytes,
            treedef=treedef,
            crc=0,
            cur_tok=m["cur_tok"],
            cur_len=m["cur_len"],
            n_gen=m["n_gen"],
            rng=rng,
            prompt_len=m["prompt_len"],
            arrays=padded,
        )
        return req, rec

    @staticmethod
    def _kv_stream_tokens(req, cid: str, pb, refuse):
        """Relay the migrated row's token stream back to the prefill host
        as batched ``fed_kv: tok`` frames, finishing with ``done``
        (retired) or a typed refusal (admission lost a race / failed)."""
        import queue

        from ...models.vlm import migration
        from ...models.vlm.continuous import _STREAM_END

        seq = 0
        try:
            ended = False
            while not ended:
                tok = req.stream_q.get()
                if tok is _STREAM_END:
                    break
                batch = [int(tok)]
                while True:
                    try:
                        nxt = req.stream_q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _STREAM_END:
                        ended = True
                        break
                    batch.append(int(nxt))
                yield pb.InferResponse(
                    correlation_id=cid,
                    is_final=False,
                    seq=seq,
                    meta={"fed_kv": "tok", "toks": ",".join(map(str, batch))},
                )
                seq += 1
            try:
                _, n_gen, eos = req.future.result(timeout=30.0)
            except migration.ChunksMissing as e:
                # Offer/commit race (promised prefix pages evicted):
                # retryable — the prefill host re-commits full contents.
                yield refuse(
                    pb.ERROR_CODE_UNAVAILABLE, str(e),
                    "re-commit with full page contents",
                    marker="chunks_missing",
                )
                return
            except Exception as e:  # noqa: BLE001 - typed in-band, never a 500
                yield refuse(
                    pb.ERROR_CODE_UNAVAILABLE,
                    f"migrated row failed on this host: "
                    f"{type(e).__name__}: {e}",
                    "the prefill host resumes from its own snapshot",
                )
                return
            yield pb.InferResponse(
                correlation_id=cid,
                is_final=True,
                total=seq + 1,
                meta={
                    "fed_kv": "done",
                    "n_gen": str(int(n_gen)),
                    "eos": "1" if eos else "0",
                },
            )
        finally:
            # Prefill host gone mid-stream (client cancelled the RPC):
            # stop decoding a row nobody reads. Harmless after retirement.
            req.cancelled = True


def _reraise_value_errors(it):
    """Map manager ValueErrors (bad image, over-long prompt) to the wire
    INVALID_ARGUMENT code; ``BaseService._stream_out`` handles the rest."""
    try:
        yield from it
    except ValueError as e:
        raise InvalidArgument(f"cannot process request: {e}") from e
